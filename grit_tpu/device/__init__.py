"""Device layer — TPU-native quiesce + HBM snapshot engine.

This package is the all-new replacement for the reference's NVIDIA device
path (CRIU ``cuda_plugin.so`` + ``cuda-checkpoint --toggle --pid``, see
reference ``docs/experiments/checkpoint-restore-tuning-job.md:52-83,126,147``).
Where the reference treats device state as a black box behind ``runc
checkpoint``, the TPU build owns it explicitly:

- :mod:`grit_tpu.device.quiesce` — drain in-flight XLA:TPU work so a
  consistent cut exists (the analogue of ``cuda-checkpoint`` removing the
  process from the GPU).
- :mod:`grit_tpu.device.snapshot` — serialize/deserialize HBM-resident
  sharded arrays (the analogue of CRIU folding GPU memory into
  ``pages-*.img``), with streaming device→host→disk overlap and an atomic
  work-dir/rename commit protocol mirroring the reference agent
  (``pkg/gritagent/checkpoint/runtime.go:147-152``).
- :mod:`grit_tpu.device.agentlet` — the in-process toggle endpoint that the
  external ``tpu-checkpoint`` CLI talks to (the analogue of the
  ``cuda-checkpoint --toggle --pid`` control channel).
"""

from grit_tpu.device.quiesce import quiesce
from grit_tpu.device.snapshot import (
    PostcopyRestore,
    SnapshotManifest,
    restore_snapshot,
    restore_snapshot_postcopy,
    snapshot_delta_nbytes,
    snapshot_exists,
    snapshot_nbytes,
    write_snapshot,
)

__all__ = [
    "quiesce",
    "write_snapshot",
    "restore_snapshot",
    "restore_snapshot_postcopy",
    "PostcopyRestore",
    "snapshot_exists",
    "snapshot_nbytes",
    "snapshot_delta_nbytes",
    "SnapshotManifest",
]
