"""Checkpoint agentlet — the in-process toggle endpoint.

The reference's device freeze is driven from *outside* the workload:
``cuda-checkpoint --toggle --pid`` reaches into a process via the CUDA
driver and stalls it (reference ``docs/experiments/checkpoint-restore-
tuning-job.md:126-147``). libtpu has no such externally-injectable toggle —
and mid-collective preemption would wedge the ICI mesh anyway — so the TPU
contract is cooperative: the workload links this agentlet, which serves a
tiny JSON protocol on a per-pid unix socket, and parks the training loop at
a step boundary when asked.

Protocol (newline-delimited JSON, one request per line):

    {"op": "quiesce"}                → {"ok": true, "step": N}   toggle off
    {"op": "dump", "dir": "<path>"}  → {"ok": true, "dir": ...}  HBM snapshot
      optional "base": "<path>"  — delta-dump against that committed
      snapshot (pre-copy: only chunks that changed since the base are
      written; see grit_tpu.device.snapshot)
      optional "mirror": "<path>" — stream a byte-identical committed
      copy to this (upload-destination) dir concurrently with the dump
      optional "wire": {"endpoint": "host:port", "prefix": "<rel>"} —
      wire-mode migration: stream every physically appended chunk to
      the destination's WireReceiver AS THE DUMP DRAINS (rel path
      ``<prefix>/data-h<pidx>.bin``). The response carries
      "wire": {"ok": bool, "files": {rel: nbytes}, "error": ...} so the
      agent knows which bytes already crossed (wire failures never fail
      the dump — the agent falls back to the PVC path, loudly)
    {"op": "resume"}                 → {"ok": true}              toggle on
      optional "reload": "<path>" — before unparking, reload device
      state from that committed snapshot (the TPU analogue of the
      second cuda-checkpoint toggle: after a CRIU-style process
      restore, host memory is back but HBM must be re-attached from
      the checkpoint; requires the workload to have passed reload_fn)
    {"op": "status"}                 → {"ok": true, "step": N, "paused": ...}

Socket path: ``{GRIT_TPU_SOCKET_DIR:-/tmp}/grit-tpu-{pid}.sock`` — the
node agent (or the C++ ``tpu-checkpoint`` CLI) finds a workload's endpoint
by pid, exactly how ``cuda-checkpoint`` is addressed.

Wiring: the training loop calls :meth:`Agentlet.checkpoint_point` once per
step (one dict lookup when idle). On a pending quiesce the loop drains
device work and parks there until ``resume`` (or ``shutdown``). ``dump``
executes while the loop is parked, so the state pytree is stable.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Callable

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.device.quiesce import quiesce
from grit_tpu.device.snapshot import write_snapshot


def socket_path(pid: int | None = None) -> str:
    pid = pid if pid is not None else os.getpid()
    base = config.TPU_SOCKET_DIR.get()
    return os.path.join(base, f"grit-tpu-{pid}.sock")


class Agentlet:
    """Serve the toggle protocol for one workload process.

    Args:
      state_fn: returns the *current* migratable state pytree (a getter,
        because training steps rebind/donate the state object).
      step_fn: returns the current step (int) for status/acks.
      meta_fn: optional extra manifest metadata at dump time.
    """

    def __init__(
        self,
        state_fn: Callable[[], Any],
        step_fn: Callable[[], int] = lambda: -1,
        meta_fn: Callable[[], dict] | None = None,
        path: str | None = None,
        reload_fn: Callable[[str], Any] | None = None,
        slice_gate=None,
        quiesce_state_fn: Callable[[], Any] | None = None,
        pre_park_fn: Callable[[], None] | None = None,
    ) -> None:
        self.state_fn = state_fn
        self.step_fn = step_fn
        self.meta_fn = meta_fn or (lambda: {})
        self.reload_fn = reload_fn
        # What the park's device drain blocks on. Defaults to state_fn;
        # callers whose state_fn derives a transformed dump view (the
        # serving adapter's tagged KV grid) pass the RAW state here so
        # the quiesce doesn't materialize — and discard — a full copy.
        self.quiesce_state_fn = quiesce_state_fn or state_fn
        # Runs once per quiesce round, on the loop thread, after the
        # pause request is observed but BEFORE the device drain + park
        # (the serving adapter's request-drain policy). Hooking here —
        # not in the caller before checkpoint_point — closes the race
        # where a quiesce lands between the caller's own pending check
        # and the park, which would park without ever draining. A raise
        # aborts the park attempt loudly; the request stays pending for
        # the agent's error path.
        self.pre_park_fn = pre_park_fn
        # Gang slice migration: a SliceQuiesceGate
        # (grit_tpu.parallel.coordination) turns "park at the next step
        # boundary" into "park at the SAME agreed boundary on every
        # host" — engaged only for quiesce requests that ask for the
        # slice cut (the blackout dump; momentary pre-copy probes stay
        # per-host). None = single-host behavior, bit-identical.
        self.slice_gate = slice_gate
        self._slice_pending = False
        self._explicit_path = path is not None
        self.path = path or socket_path()
        # Single condition variable guards the pause protocol. Invariants:
        # _want_pause is the *request* (set by quiesce, cleared only by
        # resume/shutdown); _parked is the loop's acknowledgment. The loop
        # stays parked exactly while _want_pause holds, so resume-then-
        # quiesce races keep it parked and a timed-out quiesce is recovered
        # by the agent's error-path resume rather than leaking a stuck loop.
        self._cond = threading.Condition()
        self._want_pause = False
        self._is_parked = False
        self._dumps_in_flight = 0
        self._reloads_in_flight = 0
        self._dump_lock = threading.Lock()  # one snapshot write at a time
        self._shutdown = False
        self._started = False
        self._srv: socket.socket | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------------

    def start(self) -> "Agentlet":
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self._srv.bind(self.path)
        except OSError:
            self._srv.close()
            self._srv = None
            raise
        self._srv.listen(4)
        self._started = True
        self._thread = threading.Thread(
            target=self._serve, name="grit-agentlet", daemon=True
        )
        self._thread.start()
        # Opt-in workload-side /metrics (GRIT_WORKLOAD_METRICS_PORT):
        # the agentlet is the one component guaranteed to live in every
        # managed workload process — dump/place/codec metrics become
        # scrapeable without touching the training loop. No-op unless
        # the knob is set; never raises.
        from grit_tpu.obs.server import (  # noqa: PLC0415
            start_workload_metrics_server,
        )

        start_workload_metrics_server()
        # Workload logs carry the migration uid/role once a dump's
        # flight context exists — joinable to gritscope timelines.
        from grit_tpu.obs.logctx import install_log_correlation  # noqa: PLC0415

        install_log_correlation()
        return self

    def stop(self) -> None:
        with self._cond:
            self._shutdown = True
            self._want_pause = False
            self._cond.notify_all()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        if os.path.exists(self.path):
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __enter__(self) -> "Agentlet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- loop-side hook ---------------------------------------------------------

    def checkpoint_point(self) -> None:
        """Call once per training step. Parks while a quiesce is pending.

        Also self-heals: if the server thread died — after a raw-process
        restore (minicriu's fd scope turns the listening socket into
        /dev/null; real CRIU restores unix sockets, but the engines must
        be interchangeable) the accept loop exits — rebind under the
        CURRENT pid and serve again, so a restored workload stays
        re-checkpointable (iterative migration)."""
        self._heal()
        with self._cond:
            if not self._want_pause:
                return
            slice_pending = self._slice_pending
        if slice_pending and self.slice_gate is not None:
            # Cross-host quiesce barrier: agree on the max cut, run
            # forward to it, then wait (bounded) for every host. False
            # = keep training — below the cut, or the barrier failed
            # loudly (then the agent's quiesce request times out and
            # the gang aborts; this loop must never half-park).
            if not self.slice_gate.ready_to_park(int(self.step_fn())):
                return
        if self.pre_park_fn is not None:
            self.pre_park_fn()
        # Drain device work outside the lock (can take a while on big
        # state); re-check the request after — it may have been cancelled.
        quiesce(self.quiesce_state_fn())
        with self._cond:
            if not self._want_pause:
                return
            self._is_parked = True
            self._cond.notify_all()
            while self._want_pause and not self._shutdown:
                if self._cond.wait(timeout=2.0):
                    continue
                # Periodic liveness check WHILE parked: the migration
                # flow dumps the process exactly here (quiesced, then
                # CRIU'd), so a raw restore wakes this thread still
                # inside the park with a dead serve socket — without a
                # heal from inside the loop, the resume that unparks it
                # could never arrive.
                self._cond.release()
                try:
                    self._heal()
                finally:
                    self._cond.acquire()
            self._is_parked = False
            self._cond.notify_all()

    def _heal(self) -> None:
        """Restart the serve loop if its thread died (post-restore).

        One liveness check per step when healthy; a never-started
        agentlet (caller opted out of the toggle endpoint) is left
        alone. The rebind recomputes the default pid-derived socket path
        — the restored process has a NEW pid, and that pid is how the
        node agent addresses it; the old pid's stale socket file is
        removed so an agent probing it gets a clean ENOENT."""
        t = self._thread
        if not self._started or self._shutdown or (
                t is not None and t.is_alive()):
            return
        try:
            if self._srv is not None:
                try:
                    self._srv.close()
                except OSError:
                    pass
                self._srv = None
            if not self._explicit_path:
                if os.path.exists(self.path):
                    try:
                        os.unlink(self.path)
                    except OSError:
                        pass
                self.path = socket_path()
            self.start()
        except OSError:
            # Socket dir gone on this host: stay unreachable but alive —
            # the next checkpoint_point retries. Close any half-created
            # socket so the retry loop cannot leak an fd per step.
            if self._srv is not None:
                try:
                    self._srv.close()
                except OSError:
                    pass
            self._srv = None
            self._thread = None

    @property
    def paused(self) -> bool:
        with self._cond:
            return self._is_parked

    @property
    def quiesce_pending(self) -> bool:
        """A quiesce request is waiting for the loop to park. The
        serving adapter's request-drain hook polls this at each batch
        boundary: a pending request switches the engine from serving to
        draining (policy-dependent) BEFORE the park."""
        with self._cond:
            return self._want_pause and not self._is_parked

    # -- server side ------------------------------------------------------------

    def _serve(self) -> None:
        # Thread-per-connection: the node agent's ToggleClient keeps its
        # connection open, and the CLI / CRIU plugin / status probes must
        # still get through (dispatch is already lock-protected).
        while not self._shutdown:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._conn_worker, args=(conn,), daemon=True
            ).start()

    def _conn_worker(self, conn: socket.socket) -> None:
        try:
            self._handle_conn(conn)
        except Exception:  # noqa: BLE001 — a bad client must not kill serving
            pass
        finally:
            conn.close()

    def _handle_conn(self, conn: socket.socket) -> None:
        buf = b""
        while not self._shutdown:
            chunk = conn.recv(65536)
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                resp = self._dispatch(json.loads(line))
                conn.sendall((json.dumps(resp) + "\n").encode())

    @staticmethod
    def _wire_sink(spec: dict | None):
        """Build the dump's wire tee from a request's ``wire`` spec:
        ``(sink, sender, error_result)``. A connect failure reports in
        the response's wire field instead of failing the dump — the
        agent's contract is loud PVC fallback, never a lost snapshot."""
        if not spec:
            return None, None, None
        try:
            import posixpath  # noqa: PLC0415

            import jax  # noqa: PLC0415

            from grit_tpu.agent.copy import (  # noqa: PLC0415
                WireDumpSink,
                WireSender,
            )

            sender = WireSender(str(spec["endpoint"]),
                                streams=int(spec.get("streams", 2)))
            rel = posixpath.join(
                str(spec.get("prefix", "")),
                f"data-h{jax.process_index():04d}.bin")
            return WireDumpSink(sender, rel), sender, None
        except Exception as exc:  # noqa: BLE001 — reported, never raised
            return None, None, {
                "ok": False, "error": f"{type(exc).__name__}: {exc}"}

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        try:
            # Chaos seams for the toggle protocol itself: fire inside the
            # dispatch try so an injected raise travels the same channel
            # as a real one — an {"ok": false} error response the agent
            # must handle (and a hang here models a wedged workload the
            # manager watchdog's lease must catch).
            if op in ("quiesce", "dump", "resume"):
                faults.fault_point(f"device.agentlet.{op}")
            if op == "quiesce":
                want_slice = bool(req.get("slice")) \
                    and self.slice_gate is not None
                if want_slice:
                    # Arm the gate BEFORE the pause request so the very
                    # first checkpoint_point consults it; the request
                    # carries the flight dir (timeline join) and the
                    # attempt nonce (rendezvous namespace).
                    self.slice_gate.request(
                        flight_dir=req.get("flight_dir"),
                        nonce=req.get("slice_nonce"))
                deadline = time.monotonic() + float(
                    req.get("timeout", 300.0))
                with self._cond:
                    self._slice_pending = want_slice
                    self._want_pause = True
                    self._cond.notify_all()
                    # The loop parks at its next (slice: agreed) step
                    # boundary; wait for it — polling the gate too: a
                    # latched barrier failure means the loop will NEVER
                    # park, and the agent must learn that at barrier-
                    # timeout speed, not after the full quiesce timeout.
                    while not self._is_parked:
                        if want_slice \
                                and self.slice_gate.failed is not None:
                            # The request is cleared: with the gate
                            # latched the loop cannot park this round,
                            # and a pending request would ambush the
                            # NEXT attempt's reset.
                            self._want_pause = False
                            self._slice_pending = False
                            self._cond.notify_all()
                            return {"ok": False,
                                    "error": "slice quiesce barrier "
                                             f"failed: "
                                             f"{self.slice_gate.failed}"}
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            # Leave the request pending: the loop WILL
                            # park when it reaches the boundary, and the
                            # agent's error path resumes it — clearing
                            # here would instead strand a loop already
                            # past the re-check.
                            return {"ok": False,
                                    "error": "quiesce timeout"}
                        self._cond.wait(timeout=min(0.2, remaining))
                return {"ok": True, "step": int(self.step_fn())}
            if op == "dump":
                # Snapshot writes happen outside the lock (they're long),
                # so a concurrent resume must not unpark the loop mid-write:
                # mark the dump in flight and make resume wait it out.
                with self._cond:
                    # Both flags: after a resume is granted, _want_pause is
                    # already False while the loop may not have unparked yet
                    # — a dump admitted in that window would race the loop.
                    if not (self._is_parked and self._want_pause):
                        return {"ok": False, "error": "not quiesced"}
                    self._dumps_in_flight += 1
                wire_result: dict | None = None
                try:
                    directory = req["dir"]
                    wire_sink, wire_sender, wire_result = self._wire_sink(
                        req.get("wire"))
                    # _dump_lock serializes concurrent dump requests (agent +
                    # CLI can connect at once now); writes stay outside _cond.
                    with self._dump_lock:
                        try:
                            # write_snapshot also bundles this process's XLA
                            # compilation cache (hook.py COMPILE_CACHE_*).
                            write_snapshot(
                                directory,
                                self.state_fn(),
                                meta={"step": int(self.step_fn()),
                                      **self.meta_fn()},
                                base=req.get("base"),
                                hashes=bool(req.get("hashes")),
                                mirror=req.get("mirror"),
                                wire=wire_sink,
                            )
                        finally:
                            if wire_sender is not None:
                                wire_sender.close()
                    if wire_sink is not None:
                        wire_result = (
                            {"ok": True, "files": {wire_sink.rel:
                                                   wire_sink.nbytes},
                             "sent_bytes": wire_sender.sent_bytes,
                             # socketed while the dump still drained —
                             # the agent folds these into the session's
                             # overlap-fraction gauge
                             "dump_overlap_bytes":
                                 wire_sink.bytes_during_dump,
                             "send_s": round(wire_sender.send_s, 4),
                             "stall_s": round(wire_sender.stall_s, 4)}
                            if wire_sink.ok else
                            {"ok": False, "error": wire_sink.error})
                finally:
                    with self._cond:
                        self._dumps_in_flight -= 1
                        self._cond.notify_all()
                return {"ok": True, "dir": directory,
                        **({"wire": wire_result}
                           if wire_result is not None else {})}
            if op == "resume":
                reload_dir = req.get("reload")
                if reload_dir is not None:
                    # Device re-attach (the second-toggle analogue): the
                    # loop must be parked so the state object is stable
                    # while reload_fn rebinds it. The reload runs under
                    # _dump_lock (a concurrent dump must not read the
                    # pytree mid-rebind) and holds a reloads-in-flight
                    # count that a concurrent plain resume waits out
                    # (unparking the loop mid-reload would race
                    # train_step against the rebind).
                    with self._cond:
                        if not (self._is_parked and self._want_pause):
                            return {"ok": False,
                                    "error": "reload requires quiesced"}
                        if self.reload_fn is None:
                            return {"ok": False,
                                    "error": "workload has no reload_fn"}
                        self._reloads_in_flight += 1
                    try:
                        # Seed the local XLA cache from the snapshot's
                        # carried copy BEFORE reload_fn runs: a custom
                        # reload_fn may compile without ever entering
                        # restore_snapshot (which seeds for the Trainer
                        # path), and the re-attached loop's next step
                        # compile must be a cache hit either way.
                        from grit_tpu.device.hook import (  # noqa: PLC0415
                            enable_compile_cache_from_env,
                            seed_compile_cache,
                        )

                        if enable_compile_cache_from_env():
                            seed_compile_cache(reload_dir)
                        with self._dump_lock:
                            self.reload_fn(reload_dir)
                    finally:
                        with self._cond:
                            self._reloads_in_flight -= 1
                            self._cond.notify_all()
                with self._cond:
                    while (self._dumps_in_flight
                           or self._reloads_in_flight) \
                            and not self._shutdown:
                        self._cond.wait()
                    self._want_pause = False
                    self._slice_pending = False
                    self._cond.notify_all()
                if self.slice_gate is not None:
                    # Resume ends the quiesce round: the next migration
                    # attempt re-agrees from scratch (and a latched
                    # barrier failure is cleared).
                    self.slice_gate.reset()
                return {"ok": True, **(
                    {"reloaded": reload_dir} if reload_dir else {})}
            if op == "status":
                resp = {
                    "ok": True,
                    "step": int(self.step_fn()),
                    "paused": self.paused,
                    "pid": os.getpid(),
                }
                if self.slice_gate is not None:
                    resp["slice"] = {"cut": self.slice_gate.cut,
                                     "failed": self.slice_gate.failed}
                return resp
            return {"ok": False, "error": f"unknown op {op!r}"}
        except Exception as exc:  # noqa: BLE001 — report, don't crash the workload
            return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}


class ToggleClient:
    """Client side of the toggle protocol (what the node agent uses)."""

    def __init__(self, pid: int, path: str | None = None, timeout: float = 310.0):
        self.path = path or socket_path(pid)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(self.path)
        self._buf = b""

    def request(self, op: str, **fields) -> dict:
        msg = json.dumps({"op": op, **fields}) + "\n"
        self._sock.sendall(msg.encode())
        while b"\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("agentlet closed the connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(f"agentlet {op} failed: {resp.get('error')}")
        return resp

    def quiesce(self, slice_cut: bool = False,
                flight_dir: str | None = None,
                slice_nonce: str | None = None) -> int:
        """``slice_cut=True`` asks the workload to park at the SLICE'S
        agreed cut boundary (cross-host barrier through its
        SliceQuiesceGate) instead of its own next step; workloads
        without a gate ignore the extra fields, so the request stays
        compatible both ways."""
        fields: dict = {}
        if slice_cut:
            fields["slice"] = True
            if flight_dir is not None:
                fields["flight_dir"] = flight_dir
            if slice_nonce is not None:
                fields["slice_nonce"] = slice_nonce
        return int(self.request("quiesce", **fields)["step"])

    def dump(self, directory: str, base: str | None = None,
             hashes: bool = False, mirror: str | None = None,
             wire: dict | None = None) -> dict:
        """Returns the dump response — wire-mode callers read its
        ``wire`` field ({"ok", "files", ...}) to learn which bytes
        already crossed to the destination."""
        fields: dict = {"dir": directory}
        if base is not None:
            fields["base"] = base
        if hashes:
            fields["hashes"] = True
        if mirror is not None:
            fields["mirror"] = mirror
        if wire is not None:
            fields["wire"] = wire
        return self.request("dump", **fields)

    def resume(self, reload: str | None = None) -> None:
        fields: dict = {}
        if reload is not None:
            fields["reload"] = reload
        self.request("resume", **fields)

    def status(self) -> dict:
        return self.request("status")

    def close(self) -> None:
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
