"""Checkpoint agentlet — the in-process toggle endpoint.

The reference's device freeze is driven from *outside* the workload:
``cuda-checkpoint --toggle --pid`` reaches into a process via the CUDA
driver and stalls it (reference ``docs/experiments/checkpoint-restore-
tuning-job.md:126-147``). libtpu has no such externally-injectable toggle —
and mid-collective preemption would wedge the ICI mesh anyway — so the TPU
contract is cooperative: the workload links this agentlet, which serves a
tiny JSON protocol on a per-pid unix socket, and parks the training loop at
a step boundary when asked.

Protocol (newline-delimited JSON, one request per line):

    {"op": "quiesce"}                → {"ok": true, "step": N}   toggle off
      optional "dump": {"dir", "base"?, "mirror"?} — quiesce-free
      concurrent dump: start the snapshot NOW, speculatively, against a
      cloned generation while the loop is still stepping; the matching
      {"op": "dump"} for the same dir then only re-ships the validated
      diff of what the in-flight step touched (its response carries
      "speculative": {"outcome": "validated"|"degraded", ...})
    {"op": "dump", "dir": "<path>"}  → {"ok": true, "dir": ...}  HBM snapshot
      optional "speculative": true — NON-PARKING probe: snapshot a
      cloned generation without a quiesce (the loop keeps stepping);
      the standby governor's warm-round dump
      optional "base": "<path>"  — delta-dump against that committed
      snapshot (pre-copy: only chunks that changed since the base are
      written; see grit_tpu.device.snapshot)
      optional "mirror": "<path>" — stream a byte-identical committed
      copy to this (upload-destination) dir concurrently with the dump
      optional "wire": {"endpoint": "host:port", "prefix": "<rel>"} —
      wire-mode migration: stream every physically appended chunk to
      the destination's WireReceiver AS THE DUMP DRAINS (rel path
      ``<prefix>/data-h<pidx>.bin``). The response carries
      "wire": {"ok": bool, "files": {rel: nbytes}, "error": ...} so the
      agent knows which bytes already crossed (wire failures never fail
      the dump — the agent falls back to the PVC path, loudly)
    {"op": "resume"}                 → {"ok": true}              toggle on
      optional "reload": "<path>" — before unparking, reload device
      state from that committed snapshot (the TPU analogue of the
      second cuda-checkpoint toggle: after a CRIU-style process
      restore, host memory is back but HBM must be re-attached from
      the checkpoint; requires the workload to have passed reload_fn)
    {"op": "status"}                 → {"ok": true, "step": N, "paused": ...}

Socket path: ``{GRIT_TPU_SOCKET_DIR:-/tmp}/grit-tpu-{pid}.sock`` — the
node agent (or the C++ ``tpu-checkpoint`` CLI) finds a workload's endpoint
by pid, exactly how ``cuda-checkpoint`` is addressed.

Wiring: the training loop calls :meth:`Agentlet.checkpoint_point` once per
step (one dict lookup when idle). On a pending quiesce the loop drains
device work and parks there until ``resume`` (or ``shutdown``). ``dump``
executes while the loop is parked, so the state pytree is stable.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Callable

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.device.quiesce import clone_generation, quiesce
from grit_tpu.device.snapshot import (
    SpeculativeDump,
    snapshot_delta_nbytes,
    snapshot_nbytes,
    start_speculative_dump,
    validated_clean_names,
    write_snapshot,
)
from grit_tpu.obs import flight
from grit_tpu.obs.metrics import (
    SNAP_SPECULATIVE_BYTES,
    SNAP_SPECULATIVE_ROUNDS,
    SNAP_SPECULATIVE_SECONDS,
)

log = logging.getLogger(__name__)


def socket_path(pid: int | None = None) -> str:
    pid = pid if pid is not None else os.getpid()
    base = config.TPU_SOCKET_DIR.get()
    return os.path.join(base, f"grit-tpu-{pid}.sock")


class Agentlet:
    """Serve the toggle protocol for one workload process.

    Args:
      state_fn: returns the *current* migratable state pytree (a getter,
        because training steps rebind/donate the state object).
      step_fn: returns the current step (int) for status/acks.
      meta_fn: optional extra manifest metadata at dump time.
    """

    def __init__(
        self,
        state_fn: Callable[[], Any],
        step_fn: Callable[[], int] = lambda: -1,
        meta_fn: Callable[[], dict] | None = None,
        path: str | None = None,
        reload_fn: Callable[[str], Any] | None = None,
        slice_gate=None,
        quiesce_state_fn: Callable[[], Any] | None = None,
        pre_park_fn: Callable[[], None] | None = None,
    ) -> None:
        self.state_fn = state_fn
        self.step_fn = step_fn
        self.meta_fn = meta_fn or (lambda: {})
        self.reload_fn = reload_fn
        # What the park's device drain blocks on. Defaults to state_fn;
        # callers whose state_fn derives a transformed dump view (the
        # serving adapter's tagged KV grid) pass the RAW state here so
        # the quiesce doesn't materialize — and discard — a full copy.
        self.quiesce_state_fn = quiesce_state_fn or state_fn
        # Runs once per quiesce round, on the loop thread, after the
        # pause request is observed but BEFORE the device drain + park
        # (the serving adapter's request-drain policy). Hooking here —
        # not in the caller before checkpoint_point — closes the race
        # where a quiesce lands between the caller's own pending check
        # and the park, which would park without ever draining. A raise
        # aborts the park attempt loudly; the request stays pending for
        # the agent's error path.
        self.pre_park_fn = pre_park_fn
        # Gang slice migration: a SliceQuiesceGate
        # (grit_tpu.parallel.coordination) turns "park at the next step
        # boundary" into "park at the SAME agreed boundary on every
        # host" — engaged only for quiesce requests that ask for the
        # slice cut (the blackout dump; momentary pre-copy probes stay
        # per-host). None = single-host behavior, bit-identical.
        self.slice_gate = slice_gate
        self._slice_pending = False  # grit: guarded-by(_cond)
        self._explicit_path = path is not None
        self.path = path or socket_path()
        # Single condition variable guards the pause protocol. Invariants:
        # _want_pause is the *request* (set by quiesce, cleared only by
        # resume/shutdown); _parked is the loop's acknowledgment. The loop
        # stays parked exactly while _want_pause holds, so resume-then-
        # quiesce races keep it parked and a timed-out quiesce is recovered
        # by the agent's error-path resume rather than leaking a stuck loop.
        self._cond = threading.Condition()
        self._want_pause = False  # grit: guarded-by(_cond)
        self._is_parked = False  # grit: guarded-by(_cond)
        self._dumps_in_flight = 0  # grit: guarded-by(_cond)
        self._reloads_in_flight = 0  # grit: guarded-by(_cond)
        self._dump_lock = threading.Lock()  # one snapshot write at a time
        # Validated speculation (quiesce-free concurrent dump): the
        # in-flight SpeculativeDump launched at quiesce-request time, or
        # None. _spec_requested/_spec_error let the parked dump report a
        # degrade even when the launch itself failed. All three are
        # guarded by _cond (set on the quiesce connection's thread, read
        # on the dump's).
        self._speculative: SpeculativeDump | None = None  # grit: guarded-by(_cond)
        self._spec_requested = False  # grit: guarded-by(_cond)
        self._spec_error: str | None = None  # grit: guarded-by(_cond)
        # Boundary-clone handshake: with donate_argnums the dispatch
        # thread can NEVER safely read the live pytree — the in-flight
        # step deletes the donated source buffers out from under any
        # off-thread reader, and under a tight loop there is no readable
        # window at all. The loop thread at a checkpoint_point boundary
        # is the one place the generation is guaranteed alive and
        # stable, so speculation asks the loop for the clone (a cheap
        # device-to-device copy — the second half of the double-buffer)
        # and the loop hands it over without parking. All guarded by
        # _cond; the box wrapper distinguishes "no clone yet" from a
        # legitimately falsy pytree.
        self._spec_clone_pending = False  # grit: guarded-by(_cond)
        self._spec_clone_box: list | None = None  # grit: guarded-by(_cond)
        self._spec_clone_error: str | None = None  # grit: guarded-by(_cond)
        self._shutdown = False  # grit: guarded-by(_cond)
        self._started = False
        self._srv: socket.socket | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------------

    def start(self) -> "Agentlet":
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self._srv.bind(self.path)
        except OSError:
            self._srv.close()
            self._srv = None
            raise
        self._srv.listen(4)
        self._started = True
        self._thread = threading.Thread(
            target=self._serve, name="grit-agentlet", daemon=True
        )
        self._thread.start()
        # Opt-in workload-side /metrics (GRIT_WORKLOAD_METRICS_PORT):
        # the agentlet is the one component guaranteed to live in every
        # managed workload process — dump/place/codec metrics become
        # scrapeable without touching the training loop. No-op unless
        # the knob is set; never raises.
        from grit_tpu.obs.server import (  # noqa: PLC0415
            start_workload_metrics_server,
        )

        start_workload_metrics_server()
        # Workload logs carry the migration uid/role once a dump's
        # flight context exists — joinable to gritscope timelines.
        from grit_tpu.obs.logctx import install_log_correlation  # noqa: PLC0415

        install_log_correlation()
        return self

    def stop(self) -> None:
        with self._cond:
            self._shutdown = True
            self._want_pause = False
            self._cond.notify_all()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        if os.path.exists(self.path):
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __enter__(self) -> "Agentlet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- loop-side hook ---------------------------------------------------------

    # grit: loop-thread
    def checkpoint_point(self) -> None:
        """Call once per training step. Parks while a quiesce is pending.

        Also self-heals: if the server thread died — after a raw-process
        restore (minicriu's fd scope turns the listening socket into
        /dev/null; real CRIU restores unix sockets, but the engines must
        be interchangeable) the accept loop exits — rebind under the
        CURRENT pid and serve again, so a restored workload stays
        re-checkpointable (iterative migration)."""
        self._heal()
        with self._cond:
            harvest = self._spec_clone_pending
            self._spec_clone_pending = False
        if harvest:
            # Speculation wants this boundary's generation: clone it
            # here — between steps, where the donated buffers are alive
            # and stable — and keep stepping. The park (if one is
            # pending) comes on a LATER pass, after the concurrent
            # write already started against the clone.
            self._serve_boundary_clone()
        with self._cond:
            if not self._want_pause:
                return
            slice_pending = self._slice_pending
        if slice_pending and self.slice_gate is not None:
            # Cross-host quiesce barrier: agree on the max cut, run
            # forward to it, then wait (bounded) for every host. False
            # = keep training — below the cut, or the barrier failed
            # loudly (then the agent's quiesce request times out and
            # the gang aborts; this loop must never half-park).
            if not self.slice_gate.ready_to_park(int(self.step_fn())):
                return
        if self.pre_park_fn is not None:
            self.pre_park_fn()
        # Drain device work outside the lock (can take a while on big
        # state); re-check the request after — it may have been cancelled.
        quiesce(self.quiesce_state_fn())
        with self._cond:
            if not self._want_pause:
                return
            self._is_parked = True
            self._cond.notify_all()
            while self._want_pause and not self._shutdown:
                if self._cond.wait(timeout=2.0):
                    continue
                # Periodic liveness check WHILE parked: the migration
                # flow dumps the process exactly here (quiesced, then
                # CRIU'd), so a raw restore wakes this thread still
                # inside the park with a dead serve socket — without a
                # heal from inside the loop, the resume that unparks it
                # could never arrive.
                self._cond.release()
                try:
                    self._heal()
                finally:
                    self._cond.acquire()
            self._is_parked = False
            self._cond.notify_all()

    def _heal(self) -> None:
        """Restart the serve loop if its thread died (post-restore).

        One liveness check per step when healthy; a never-started
        agentlet (caller opted out of the toggle endpoint) is left
        alone. The rebind recomputes the default pid-derived socket path
        — the restored process has a NEW pid, and that pid is how the
        node agent addresses it; the old pid's stale socket file is
        removed so an agent probing it gets a clean ENOENT."""
        t = self._thread
        # gritlint: allow(lock-discipline): _shutdown is a one-way latch
        # polled here as a fast-path liveness probe on the loop thread; a
        # stale False costs one extra (idempotent) heal attempt, and the
        # authoritative shutdown signal is stop()'s socket close.
        if not self._started or self._shutdown or (
                t is not None and t.is_alive()):
            return
        try:
            if self._srv is not None:
                try:
                    self._srv.close()
                except OSError:
                    pass
                self._srv = None
            if not self._explicit_path:
                if os.path.exists(self.path):
                    try:
                        os.unlink(self.path)
                    except OSError:
                        pass
                self.path = socket_path()
            self.start()
        except OSError:
            # Socket dir gone on this host: stay unreachable but alive —
            # the next checkpoint_point retries. Close any half-created
            # socket so the retry loop cannot leak an fd per step.
            if self._srv is not None:
                try:
                    self._srv.close()
                except OSError:
                    pass
            self._srv = None
            self._thread = None

    @property
    def paused(self) -> bool:
        with self._cond:
            return self._is_parked

    @property
    def quiesce_pending(self) -> bool:
        """A quiesce request is waiting for the loop to park. The
        serving adapter's request-drain hook polls this at each batch
        boundary: a pending request switches the engine from serving to
        draining (policy-dependent) BEFORE the park."""
        with self._cond:
            return self._want_pause and not self._is_parked

    # -- server side ------------------------------------------------------------

    def _serve(self) -> None:
        # Thread-per-connection: the node agent's ToggleClient keeps its
        # connection open, and the CLI / CRIU plugin / status probes must
        # still get through (dispatch is already lock-protected).
        # gritlint: allow(lock-discipline): one-way latch polled lock-free
        # per accept round; stop() closing the listen socket is what
        # actually breaks the accept() and ends this loop.
        while not self._shutdown:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._conn_worker, args=(conn,), daemon=True
            ).start()

    # grit: dispatch-thread
    def _conn_worker(self, conn: socket.socket) -> None:
        try:
            self._handle_conn(conn)
        except Exception:  # noqa: BLE001 — a bad client must not kill serving
            pass
        finally:
            conn.close()

    # grit: dispatch-thread
    def _handle_conn(self, conn: socket.socket) -> None:
        buf = b""
        # gritlint: allow(lock-discipline): one-way latch polled lock-free
        # per request line; the connection's own EOF (recv -> b"") is the
        # authoritative end-of-service signal after stop().
        while not self._shutdown:
            chunk = conn.recv(65536)
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                resp = self._dispatch(json.loads(line))
                conn.sendall((json.dumps(resp) + "\n").encode())

    @staticmethod
    def _wire_sink(spec: dict | None):
        """Build the dump's wire tee from a request's ``wire`` spec:
        ``(sink, sender, error_result)``. A connect failure reports in
        the response's wire field instead of failing the dump — the
        agent's contract is loud PVC fallback, never a lost snapshot."""
        if not spec:
            return None, None, None
        try:
            import posixpath  # noqa: PLC0415

            import jax  # noqa: PLC0415

            from grit_tpu.agent.copy import (  # noqa: PLC0415
                WireDumpSink,
                WireSender,
            )

            sender = WireSender(str(spec["endpoint"]),
                                streams=int(spec.get("streams", 2)))
            rel = posixpath.join(
                str(spec.get("prefix", "")),
                f"data-h{jax.process_index():04d}.bin")
            return WireDumpSink(sender, rel), sender, None
        except Exception as exc:  # noqa: BLE001 — reported, never raised
            return None, None, {
                "ok": False, "error": f"{type(exc).__name__}: {exc}"}

    # grit: loop-thread
    def _serve_boundary_clone(self) -> None:
        """Loop-thread half of the handshake: clone the (stable) current
        generation — plus the step counter and meta, which can be live
        device scalars donation would delete under an off-thread reader
        — and hand the triple to the waiting dispatch thread."""
        try:
            box: list | None = [(clone_generation(self.state_fn()),
                                 int(self.step_fn()),
                                 dict(self.meta_fn()))]
            err: str | None = None
        except Exception as exc:  # noqa: BLE001 — reported to waiter
            box, err = None, f"{type(exc).__name__}: {exc}"
        with self._cond:
            self._spec_clone_box = box
            self._spec_clone_error = err
            self._cond.notify_all()

    # grit: handoff(_cond)
    def _harvest_boundary_clone(
            self, timeout_s: float) -> tuple[Any, int, dict]:
        """Dispatch-thread half: block until the loop passes a step
        boundary and hands back ``(clone, step, meta)`` for its (stable)
        state generation.

        A parked loop is already at a boundary with no step in flight,
        so that case clones directly on this thread. Raises on timeout
        (a loop that never reaches a boundary) or a failed loop-side
        clone — callers degrade to the parked path."""
        with self._cond:
            if self._is_parked and self._want_pause:
                parked = True
            else:
                parked = False
                self._spec_clone_box = None
                self._spec_clone_error = None
                self._spec_clone_pending = True
                self._cond.notify_all()
        if parked:
            return (clone_generation(self.state_fn()),
                    int(self.step_fn()), dict(self.meta_fn()))
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._spec_clone_box is None \
                    and self._spec_clone_error is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._spec_clone_pending = False
                    raise RuntimeError(
                        f"no step boundary within {timeout_s:.0f}s to "
                        "harvest the speculative clone")
                self._cond.wait(timeout=min(0.2, remaining))
            box = self._spec_clone_box
            err = self._spec_clone_error
            self._spec_clone_box = None
            self._spec_clone_error = None
        if err is not None:
            raise RuntimeError(f"boundary clone failed: {err}")
        return box[0]

    # grit: dispatch-thread
    def _speculative_probe(self, req: dict) -> dict:
        """Non-parking dump (the standby governor's probe): the whole
        snapshot is a speculative pass — harvest a boundary clone from
        the loop (which keeps stepping), then write the clone from THIS
        dispatch thread. No pause request is ever set, so the probe
        stops costing a step boundary. Committed snapshot is
        indistinguishable from a parked one (same format, hashed), so
        the rolling delta base it feeds stays valid."""
        faults.fault_point("snap.speculate")
        directory = req["dir"]
        with self._cond:
            self._dumps_in_flight += 1
        try:
            t0 = time.monotonic()
            clone, at_step, at_meta = self._harvest_boundary_clone(
                config.SNAP_SPECULATE_WAIT_S.get())
            flight.emit_near(directory, "snap.speculative.start",
                             dir=os.path.basename(directory), probe=True,
                             delta=req.get("base") is not None)
            with self._dump_lock:
                write_snapshot(
                    directory,
                    clone,
                    meta={"step": at_step, **at_meta},
                    base=req.get("base"),
                    hashes=bool(req.get("hashes")),
                    mirror=req.get("mirror"),
                    speculative=True,
                )
            del clone
            SNAP_SPECULATIVE_SECONDS.inc(time.monotonic() - t0,
                                         phase="concurrent")
            SNAP_SPECULATIVE_ROUNDS.inc(outcome="probe")
            flight.emit_near(directory, "snap.speculative.validated",
                             outcome="probe")
        finally:
            with self._cond:
                self._dumps_in_flight -= 1
                self._cond.notify_all()
        return {"ok": True, "dir": directory,
                "speculative": {"outcome": "probe"}}

    # grit: dispatch-thread
    def _consume_speculation(
        self, directory: str, req_base: str | None,
    ) -> tuple[str | None, frozenset | None, dict | None, bool]:
        """Join + validate the speculative pass for a parked dump.

        Returns ``(base, clean_names, spec_info, spec_started)``:
        validated → base is the committed spec dir and clean_names the
        proven-untouched set (the re-ship references them without device
        reads); any failure → the request's original base and no clean
        set, i.e. bit-identically the pre-speculation parked dump, plus
        a loud warning. spec_info is None when this quiesce round never
        requested speculation (plain dumps stay plain)."""
        with self._cond:
            spec = self._speculative
            self._speculative = None
            requested = self._spec_requested
            self._spec_requested = False
            why = self._spec_error or ""
            self._spec_error = None
        if not requested:
            return req_base, None, None, False
        outcome = "degraded"
        overlap_s = validate_s = 0.0
        base: str | None = req_base
        clean: frozenset | None = None
        if spec is not None:
            if not spec.join(config.SNAP_SPECULATE_WAIT_S.get()):
                why = "speculative pass still running past wait bound"
            else:
                overlap_s = spec.seconds
                if spec.error is not None:
                    why = f"speculative pass failed: {spec.error!r}"
                elif spec.final_dir != directory:
                    why = (f"speculative pass targeted "
                           f"{spec.final_dir!r}, dump asked for "
                           f"{directory!r}")
                else:
                    tv = time.monotonic()
                    names = validated_clean_names(self.state_fn(),
                                                  spec.clone)
                    validate_s = time.monotonic() - tv
                    SNAP_SPECULATIVE_SECONDS.inc(validate_s,
                                                 phase="validate")
                    if names is None:
                        why = ("state generations structurally "
                               "incomparable")
                    else:
                        clean = frozenset(names)
                        base = spec.directory
                        outcome = "validated"
            spec.release()
        if outcome != "validated":
            log.warning("speculative dump degraded to parked full path: "
                        "%s", why or "launch failed")
        info = {"outcome": outcome,
                "overlap_s": round(overlap_s, 4),
                "validate_s": round(validate_s, 4)}
        if outcome != "validated":
            info["error"] = why or "launch failed"
        return base, clean, info, spec is not None

    def _account_speculation(self, directory: str, spec_info: dict,
                             spec_started: bool) -> None:
        """Post-commit byte accounting + the validated flight marker.
        clean = bytes the re-ship referenced from the speculative pass
        (zero device reads inside the window), dirty = bytes the
        in-flight step touched. Emitted only when a speculative.start
        exists, so gritscope's dump_concurrent brackets stay paired."""
        if spec_info["outcome"] == "validated":
            try:
                total = snapshot_nbytes(directory)
                dirty = snapshot_delta_nbytes(directory)
            except (OSError, ValueError, KeyError):
                total = dirty = 0
            spec_info["clean_bytes"] = max(0, total - dirty)
            spec_info["dirty_bytes"] = dirty
            SNAP_SPECULATIVE_BYTES.inc(spec_info["clean_bytes"],
                                       outcome="clean")
            SNAP_SPECULATIVE_BYTES.inc(dirty, outcome="dirty")
        SNAP_SPECULATIVE_ROUNDS.inc(outcome=spec_info["outcome"])
        if spec_started:
            flight.emit_near(
                directory, "snap.speculative.validated",
                outcome=spec_info["outcome"],
                overlap_s=spec_info["overlap_s"],
                validate_s=spec_info["validate_s"],
                clean_bytes=spec_info.get("clean_bytes", 0),
                dirty_bytes=spec_info.get("dirty_bytes", 0))

    # grit: dispatch-thread
    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        try:
            # Chaos seams for the toggle protocol itself: fire inside the
            # dispatch try so an injected raise travels the same channel
            # as a real one — an {"ok": false} error response the agent
            # must handle (and a hang here models a wedged workload the
            # manager watchdog's lease must catch).
            if op in ("quiesce", "dump", "resume"):
                faults.fault_point(f"device.agentlet.{op}")
            if op == "quiesce":
                want_slice = bool(req.get("slice")) \
                    and self.slice_gate is not None
                # Quiesce-free concurrent dump: a request carrying a
                # "dump" sub-spec starts the snapshot NOW, against a
                # generation cloned at the loop's next step boundary,
                # while the loop is still stepping — the park that
                # follows only pays for the validated re-ship of what
                # the steps since the clone touched. Any
                # launch failure (including an armed snap.speculate
                # fault) degrades to the plain parked dump: speculation
                # must never be able to fail a quiesce.
                dump_spec = req.get("dump")
                if dump_spec and config.SNAP_SPECULATE.get():
                    with self._cond:
                        stale = self._speculative
                        self._speculative = None
                        self._spec_requested = True
                        self._spec_error = None
                    if stale is not None:
                        stale.release()
                    try:
                        faults.fault_point("snap.speculate")
                        clone, at_step, at_meta = \
                            self._harvest_boundary_clone(
                                min(float(req.get("timeout", 300.0)),
                                    config.SNAP_SPECULATE_WAIT_S.get()))
                        spec = start_speculative_dump(
                            str(dump_spec["dir"]),
                            clone,
                            already_cloned=True,
                            meta={"step": at_step, **at_meta},
                            base=dump_spec.get("base"),
                            mirror=dump_spec.get("mirror"),
                            dump_lock=self._dump_lock,
                        )
                        with self._cond:
                            self._speculative = spec
                    except Exception as exc:  # noqa: BLE001
                        with self._cond:
                            self._spec_error = \
                                f"{type(exc).__name__}: {exc}"
                        log.warning(
                            "speculative dump launch failed (%s); this "
                            "round degrades to the parked dump", exc)
                if want_slice:
                    # Arm the gate BEFORE the pause request so the very
                    # first checkpoint_point consults it; the request
                    # carries the flight dir (timeline join) and the
                    # attempt nonce (rendezvous namespace).
                    self.slice_gate.request(
                        flight_dir=req.get("flight_dir"),
                        nonce=req.get("slice_nonce"))
                deadline = time.monotonic() + float(
                    req.get("timeout", 300.0))
                with self._cond:
                    self._slice_pending = want_slice
                    self._want_pause = True
                    self._cond.notify_all()
                    # The loop parks at its next (slice: agreed) step
                    # boundary; wait for it — polling the gate too: a
                    # latched barrier failure means the loop will NEVER
                    # park, and the agent must learn that at barrier-
                    # timeout speed, not after the full quiesce timeout.
                    while not self._is_parked:
                        if want_slice \
                                and self.slice_gate.failed is not None:
                            # The request is cleared: with the gate
                            # latched the loop cannot park this round,
                            # and a pending request would ambush the
                            # NEXT attempt's reset.
                            self._want_pause = False
                            self._slice_pending = False
                            self._cond.notify_all()
                            return {"ok": False,
                                    "error": "slice quiesce barrier "
                                             f"failed: "
                                             f"{self.slice_gate.failed}"}
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            # Leave the request pending: the loop WILL
                            # park when it reaches the boundary, and the
                            # agent's error path resumes it — clearing
                            # here would instead strand a loop already
                            # past the re-check.
                            return {"ok": False,
                                    "error": "quiesce timeout"}
                        self._cond.wait(timeout=min(0.2, remaining))
                return {"ok": True, "step": int(self.step_fn())}
            if op == "dump":
                if req.get("speculative"):
                    return self._speculative_probe(req)
                # Snapshot writes happen outside the lock (they're long),
                # so a concurrent resume must not unpark the loop mid-write:
                # mark the dump in flight and make resume wait it out.
                with self._cond:
                    # Both flags: after a resume is granted, _want_pause is
                    # already False while the loop may not have unparked yet
                    # — a dump admitted in that window would race the loop.
                    if not (self._is_parked and self._want_pause):
                        return {"ok": False, "error": "not quiesced"}
                    self._dumps_in_flight += 1
                wire_result: dict | None = None
                try:
                    directory = req["dir"]
                    wire_sink, wire_sender, wire_result = self._wire_sink(
                        req.get("wire"))
                    # Validated speculation: consume the pass launched at
                    # quiesce-request time. MUST run before _dump_lock is
                    # taken — the speculative thread writes under that
                    # lock, so joining inside it would deadlock.
                    base, clean, spec_info, spec_started = \
                        self._consume_speculation(directory,
                                                  req.get("base"))
                    # _dump_lock serializes concurrent dump requests (agent +
                    # CLI can connect at once now); writes stay outside _cond.
                    with self._dump_lock:
                        try:
                            # write_snapshot also bundles this process's XLA
                            # compilation cache (hook.py COMPILE_CACHE_*).
                            write_snapshot(
                                directory,
                                self.state_fn(),
                                meta={"step": int(self.step_fn()),
                                      **self.meta_fn()},
                                base=base,
                                hashes=bool(req.get("hashes")),
                                mirror=req.get("mirror"),
                                wire=wire_sink,
                                clean_names=clean,
                            )
                        finally:
                            if wire_sender is not None:
                                wire_sender.close()
                    if spec_info is not None:
                        self._account_speculation(
                            directory, spec_info, spec_started)
                    if wire_sink is not None:
                        wire_result = (
                            {"ok": True, "files": {wire_sink.rel:
                                                   wire_sink.nbytes},
                             "sent_bytes": wire_sender.sent_bytes,
                             # socketed while the dump still drained —
                             # the agent folds these into the session's
                             # overlap-fraction gauge
                             "dump_overlap_bytes":
                                 wire_sink.bytes_during_dump,
                             "send_s": round(wire_sender.send_s, 4),
                             "stall_s": round(wire_sender.stall_s, 4)}
                            if wire_sink.ok else
                            {"ok": False, "error": wire_sink.error})
                finally:
                    with self._cond:
                        self._dumps_in_flight -= 1
                        self._cond.notify_all()
                return {"ok": True, "dir": directory,
                        **({"wire": wire_result}
                           if wire_result is not None else {}),
                        **({"speculative": spec_info}
                           if spec_info is not None else {})}
            if op == "resume":
                reload_dir = req.get("reload")
                if reload_dir is not None:
                    # Device re-attach (the second-toggle analogue): the
                    # loop must be parked so the state object is stable
                    # while reload_fn rebinds it. The reload runs under
                    # _dump_lock (a concurrent dump must not read the
                    # pytree mid-rebind) and holds a reloads-in-flight
                    # count that a concurrent plain resume waits out
                    # (unparking the loop mid-reload would race
                    # train_step against the rebind).
                    with self._cond:
                        if not (self._is_parked and self._want_pause):
                            return {"ok": False,
                                    "error": "reload requires quiesced"}
                        if self.reload_fn is None:
                            return {"ok": False,
                                    "error": "workload has no reload_fn"}
                        self._reloads_in_flight += 1
                    try:
                        # Seed the local XLA cache from the snapshot's
                        # carried copy BEFORE reload_fn runs: a custom
                        # reload_fn may compile without ever entering
                        # restore_snapshot (which seeds for the Trainer
                        # path), and the re-attached loop's next step
                        # compile must be a cache hit either way.
                        from grit_tpu.device.hook import (  # noqa: PLC0415
                            enable_compile_cache_from_env,
                            seed_compile_cache,
                        )

                        if enable_compile_cache_from_env():
                            seed_compile_cache(reload_dir)
                        with self._dump_lock:
                            self.reload_fn(reload_dir)
                    finally:
                        with self._cond:
                            self._reloads_in_flight -= 1
                            self._cond.notify_all()
                with self._cond:
                    while (self._dumps_in_flight
                           or self._reloads_in_flight) \
                            and not self._shutdown:
                        self._cond.wait()
                    self._want_pause = False
                    self._slice_pending = False
                    # Resume ends the speculation window: an unconsumed
                    # pass (quiesce aborted before its dump, error-path
                    # resume) is abandoned and its clone's HBM freed.
                    stale_spec = self._speculative
                    self._speculative = None
                    self._spec_requested = False
                    self._spec_error = None
                    self._cond.notify_all()
                if stale_spec is not None:
                    stale_spec.release()
                if self.slice_gate is not None:
                    # Resume ends the quiesce round: the next migration
                    # attempt re-agrees from scratch (and a latched
                    # barrier failure is cleared).
                    self.slice_gate.reset()
                return {"ok": True, **(
                    {"reloaded": reload_dir} if reload_dir else {})}
            if op == "status":
                resp = {
                    "ok": True,
                    "step": int(self.step_fn()),
                    "paused": self.paused,
                    "pid": os.getpid(),
                }
                if self.slice_gate is not None:
                    resp["slice"] = {"cut": self.slice_gate.cut,
                                     "failed": self.slice_gate.failed}
                return resp
            return {"ok": False, "error": f"unknown op {op!r}"}
        except Exception as exc:  # noqa: BLE001 — report, don't crash the workload
            return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}


class ToggleClient:
    """Client side of the toggle protocol (what the node agent uses)."""

    def __init__(self, pid: int, path: str | None = None, timeout: float = 310.0):
        self.path = path or socket_path(pid)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(self.path)
        self._buf = b""

    def request(self, op: str, **fields) -> dict:
        msg = json.dumps({"op": op, **fields}) + "\n"
        self._sock.sendall(msg.encode())
        while b"\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("agentlet closed the connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(f"agentlet {op} failed: {resp.get('error')}")
        return resp

    def quiesce(self, slice_cut: bool = False,
                flight_dir: str | None = None,
                slice_nonce: str | None = None,
                dump_spec: dict | None = None) -> int:
        """``slice_cut=True`` asks the workload to park at the SLICE'S
        agreed cut boundary (cross-host barrier through its
        SliceQuiesceGate) instead of its own next step; workloads
        without a gate ignore the extra fields, so the request stays
        compatible both ways.

        ``dump_spec`` ({"dir", "base"?, "mirror"?}) pre-announces the
        dump this quiesce is for: the workload starts it speculatively
        against a cloned generation BEFORE parking, and the later
        ``dump`` for the same dir only re-ships the validated diff
        (quiesce-free concurrent dump). Ignored when the workload's
        GRIT_SNAP_SPECULATE is off; a failed launch degrades silently
        to the plain parked dump, so passing it is always safe."""
        fields: dict = {}
        if slice_cut:
            fields["slice"] = True
            if flight_dir is not None:
                fields["flight_dir"] = flight_dir
            if slice_nonce is not None:
                fields["slice_nonce"] = slice_nonce
        if dump_spec is not None:
            fields["dump"] = dump_spec
        return int(self.request("quiesce", **fields)["step"])

    def dump(self, directory: str, base: str | None = None,
             hashes: bool = False, mirror: str | None = None,
             wire: dict | None = None, speculative: bool = False) -> dict:
        """Returns the dump response — wire-mode callers read its
        ``wire`` field ({"ok", "files", ...}) to learn which bytes
        already crossed to the destination.

        ``speculative=True`` is the NON-PARKING probe: the workload
        snapshots a cloned generation without ever being asked to park
        (no quiesce needed, no step boundary cost) — the standby
        governor's warm-round dump."""
        fields: dict = {"dir": directory}
        if base is not None:
            fields["base"] = base
        if hashes:
            fields["hashes"] = True
        if mirror is not None:
            fields["mirror"] = mirror
        if wire is not None:
            fields["wire"] = wire
        if speculative:
            fields["speculative"] = True
        return self.request("dump", **fields)

    def resume(self, reload: str | None = None) -> None:
        fields: dict = {}
        if reload is not None:
            fields["reload"] = reload
        self.request("resume", **fields)

    def status(self) -> dict:
        return self.request("status")

    def close(self) -> None:
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
