"""TPU device hooks for the node agent and the shim.

Bridges the container-runtime layer to the device layer: the agent's
checkpoint driver calls :class:`TpuDeviceCheckpointHook` inside the pause
window (the slot where the reference relies on CRIU's ``cuda_plugin.so``),
and the shim injects ``GRIT_TPU_RESTORE_DIR`` on restore-mode creates —
together they play the role of the two ``cuda-checkpoint`` toggles.

The dump side talks to the workload's agentlet over the per-pid socket.
The restore side is necessarily cooperative too: the restored workload
re-runs its entry point, finds ``GRIT_TPU_RESTORE_DIR`` set (injected by
the shim from the checkpoint annotation), and reloads state before its
first step — see :func:`restore_dir_from_env`.
"""

from __future__ import annotations

import logging
import os
import uuid

from grit_tpu.api import config
from grit_tpu.device.agentlet import ToggleClient, socket_path
from grit_tpu.obs import flight

HBM_SUBDIR = "hbm"
RESTORE_ENV = config.TPU_RESTORE_DIR.name

log = logging.getLogger(__name__)


def _namespace_pid(host_pid: int) -> int:
    """Translate a host pid to the workload's in-namespace pid.

    The agentlet names its socket with the pid the workload *sees*
    (``os.getpid()`` inside the container's pid namespace); the runtime
    reports host pids. ``/proc/<host>/status`` ``NSpid:`` lists the pid in
    every namespace, innermost last.
    """
    try:
        with open(f"/proc/{host_pid}/status") as f:
            for line in f:
                if line.startswith("NSpid:"):
                    return int(line.split()[-1])
    except (OSError, ValueError, IndexError):
        pass
    return host_pid


def _agentlet_pid(host_pid: int) -> int:
    """Socket-naming pid for a workload: prefer the host pid (no pid
    namespace / shared socket dir), fall back to the namespace pid."""
    if os.path.exists(socket_path(host_pid)):
        return host_pid
    ns = _namespace_pid(host_pid)
    return ns if os.path.exists(socket_path(ns)) else host_pid


class TpuDeviceCheckpointHook:
    """Agent-side: quiesce the workload via its agentlet and dump HBM.

    ``dump`` leaves the snapshot in ``<dest_dir>/hbm/``; the workload stays
    quiesced until ``resume`` (leave-running checkpoint) or process kill
    (migration).
    """

    def __init__(self, timeout: float = 310.0) -> None:
        self.timeout = timeout
        self._clients: dict[int, ToggleClient] = {}

    def _client(self, pid: int) -> ToggleClient:
        if pid not in self._clients:
            self._clients[pid] = ToggleClient(
                _agentlet_pid(pid), timeout=self.timeout
            )
        return self._clients[pid]

    def dump(self, pid: int, dest_dir: str, base: str | None = None,
             mirror: str | None = None,
             wire: dict | None = None) -> dict | None:
        """``mirror`` is the *container-level* upload destination dir; the
        HBM snapshot streams a committed copy into ``<mirror>/hbm`` while
        it dumps (the upload pass then skips those bytes). ``wire``
        (``{"endpoint", "prefix"}``) additionally streams every chunk to
        the destination's WireReceiver as the dump drains; the returned
        dict is the agentlet's wire outcome (``{"ok", "files", ...}``),
        None when no wire was requested."""
        c = self._client(pid)
        # Quiesce is the blackout's opening phase — and on a busy host
        # often its longest unattributed wait (the workload must reach a
        # step boundary to answer the toggle), which is exactly why the
        # flight recorder brackets it explicitly.
        flight.emit("quiesce.start", dir=dest_dir, workload_pid=pid)
        ok = False
        # Pre-announce the dump on the quiesce itself: the agentlet
        # starts it speculatively against a cloned generation BEFORE the
        # park, so the later c.dump() only re-ships the validated diff of
        # what the in-flight step touched (quiesce-free concurrent dump).
        # The workload degrades to the plain parked dump on any
        # speculation failure, so the spec rides along unconditionally
        # when the knob is on.
        dump_spec = None
        if config.SNAP_SPECULATE.get():
            dump_spec = {"dir": os.path.join(dest_dir, HBM_SUBDIR)}
            if base is not None:
                dump_spec["base"] = base
            if mirror is not None:
                dump_spec["mirror"] = os.path.join(mirror, HBM_SUBDIR)
        try:
            if int(config.SLICE_HOSTS.get()) > 1:
                # Gang slice migration: the blackout quiesce must park
                # every host at the SAME agreed step boundary (the
                # workload's SliceQuiesceGate runs the bounded cross-
                # host barrier). Momentary pre-copy probes (predump)
                # stay per-host — only the final cut must be gang-
                # consistent.
                c.quiesce(slice_cut=True, flight_dir=dest_dir,
                          slice_nonce=str(config.SLICE_NONCE.get()) or "0",
                          dump_spec=dump_spec)
            else:
                c.quiesce(dump_spec=dump_spec)
            ok = True
        finally:
            # Closed on failure too: an unterminated quiesce interval
            # would be extended over the abort/resume recovery tail.
            flight.emit("quiesce.end", dir=dest_dir, workload_pid=pid,
                        ok=ok)
        # Agent-side dump bracket: the workload's agentlet emits its own
        # dump.start/end from inside write_snapshot, but the RPC dispatch
        # and response windows around it are blackout too — the two
        # process-paired intervals union in the attribution.
        flight.emit("dump.start", dir=dest_dir, workload_pid=pid)
        resp = None
        try:
            resp = c.dump(
                os.path.join(dest_dir, HBM_SUBDIR), base=base,
                mirror=(os.path.join(mirror, HBM_SUBDIR)
                        if mirror is not None else None),
                wire=wire,
            )
        finally:
            flight.emit("dump.end", dir=dest_dir, workload_pid=pid,
                        ok=resp is not None)
        return resp.get("wire") if wire is not None else None

    def predump(self, pid: int, dest_dir: str,
                mirror: str | None = None,
                base: str | None = None) -> None:
        """Pre-copy pass: momentary quiesce at the next step boundary, HBM
        dump into ``<dest_dir>/hbm``, immediate resume — the workload
        keeps training while the dump ships to the PVC. ``base`` names the
        rolling pre-copy base a convergence *round* deltas against (the
        first pass dumps full). The blackout dump passes the rolling base
        as its own ``base`` and writes only the final delta."""
        hbm_dir = os.path.join(dest_dir, HBM_SUBDIR)
        hbm_mirror = (os.path.join(mirror, HBM_SUBDIR)
                      if mirror is not None else None)
        with ToggleClient(_agentlet_pid(pid), timeout=self.timeout) as c:
            if config.SNAP_SPECULATE.get():
                # Non-parking probe: the agentlet snapshots a cloned
                # generation from its dispatch thread — no quiesce, no
                # resume, the loop never stops stepping, so a governed
                # standby round stops costing a step boundary. Any
                # failure falls back, loudly, to the parked pass below
                # (same committed layout either way).
                try:
                    c.dump(hbm_dir, hashes=True, base=base,
                           mirror=hbm_mirror, speculative=True)
                    return
                except (RuntimeError, ConnectionError, OSError) as exc:
                    log.warning(
                        "speculative predump probe failed (%s); falling "
                        "back to the parked pre-copy pass", exc)
            # quiesce inside the try: a quiesce timeout leaves the pause
            # request pending (agentlet semantics), so the loop WILL park
            # at its next boundary — without the finally-resume the live
            # pass would strand a workload that was meant to keep training.
            try:
                c.quiesce()
                # hashes: the live pass runs OUTSIDE the blackout, so it
                # pays the sha256 pass; the blackout delta (and every
                # later round) then matches by hash instead of reading
                # the base back from disk.
                c.dump(hbm_dir, hashes=True, base=base, mirror=hbm_mirror)
            finally:
                c.resume()

    def resume(self, pid: int) -> None:
        c = self._clients.pop(pid, None)
        if c is None:
            c = ToggleClient(_agentlet_pid(pid), timeout=self.timeout)
        try:
            c.resume()
        finally:
            c.close()

    def reattach(self, pid: int, snapshot_dir: str) -> None:
        """Device re-attach after a PROCESS restore — the TPU analogue of
        the reference's second ``cuda-checkpoint --toggle``
        (checkpoint-restore-tuning-job.md:145-149): CRIU put host memory
        back, but HBM contents live in the checkpoint's device snapshot;
        the (healed) agentlet reloads them while still parked, then
        unparks. ``pid`` is the RESTORED process."""
        with ToggleClient(_agentlet_pid(pid), timeout=self.timeout) as c:
            c.resume(reload=os.path.join(snapshot_dir, HBM_SUBDIR))

    @staticmethod
    def workload_has_agentlet(pid: int) -> bool:
        return os.path.exists(socket_path(_agentlet_pid(pid)))


class AutoDeviceHook:
    """Per-pid dispatch: TPU toggle path when the workload runs an
    agentlet, no-op otherwise (CPU-only pods — BASELINE config 1 — need no
    device hook, mirroring how the reference only engages the CUDA plugin
    for GPU processes)."""

    def __init__(self, timeout: float = 310.0) -> None:
        self._tpu = TpuDeviceCheckpointHook(timeout=timeout)
        self._skipped: set[int] = set()

    def dump(self, pid: int, dest_dir: str, base: str | None = None,
             mirror: str | None = None,
             wire: dict | None = None) -> dict | None:
        if TpuDeviceCheckpointHook.workload_has_agentlet(pid):
            return self._tpu.dump(pid, dest_dir, base=base, mirror=mirror,
                                  wire=wire)
        else:
            # Loud skip: a TPU pod whose agentlet is missing/crashed would
            # otherwise produce a "successful" checkpoint with no HBM state.
            self._skipped.add(pid)
            log.warning(
                "no agentlet socket for pid %d (looked for %s and ns-pid "
                "variant) — skipping device dump; if this pod holds TPU "
                "state the checkpoint is incomplete",
                pid, socket_path(pid),
            )
            return None

    def predump(self, pid: int, dest_dir: str,
                mirror: str | None = None,
                base: str | None = None) -> None:
        if TpuDeviceCheckpointHook.workload_has_agentlet(pid):
            self._tpu.predump(pid, dest_dir, mirror=mirror, base=base)
        # CPU-only pods have no HBM to pre-copy: silently nothing to do —
        # the blackout dump path (CRIU) still covers their full state.

    def resume(self, pid: int) -> None:
        if pid in self._skipped:
            self._skipped.discard(pid)
            return
        # Delegate unconditionally: the inner hook reuses its cached client
        # connection, so a socket unlinked while the workload was parked
        # (tmp cleanup, agentlet stop race) still gets its resume.
        self._tpu.resume(pid)


# Restore side: there is deliberately NO push-style restore hook. The shim
# cannot (and must not) inject buffers into a process's HBM from outside —
# shardings/topology may differ on the destination host. The single restore
# path is: shim.create injects RESTORE_ENV into the container env
# (grit_tpu/runtime/shim.py), and the workload's Trainer/engine calls
# restore_dir_from_env() before its first step.



# -- persistent compilation cache, carried with the checkpoint ----------------
#
# The restore-side blackout is dominated by XLA recompilation (bench.py
# breakdown), and a fresh destination node has a cold jit cache. Because a
# migration lands on identical accelerator topology (the same constraint
# the reference has for GPUs), XLA cache keys match across the move — so
# the snapshot carries the source's persistent compilation cache and the
# restored workload seeds its local cache from it before the first
# compile. No CUDA-world analogue exists; this is TPU/XLA-native headroom.

COMPILE_CACHE_SUBDIR = "compile-cache"


def enable_compile_cache_from_env() -> str | None:
    """Opt into JAX's persistent compilation cache when the pod/operator
    set ``GRIT_TPU_COMPILE_CACHE``. Returns the cache dir, or None."""

    d = config.TPU_COMPILE_CACHE.get()
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    import jax  # noqa: PLC0415

    jax.config.update("jax_compilation_cache_dir", d)
    # Cache everything: migration cares about total recompile time, not
    # only the slowest kernels (flag names vary across jax versions).
    for key, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(key, value)
        except Exception:  # noqa: BLE001 - older jax: defaults still cache
            pass
    return d


def _copy_missing(src_dir: str, dst_dir: str) -> int:
    import shutil  # noqa: PLC0415

    copied = 0
    for root, _dirs, files in os.walk(src_dir):
        rel_root = os.path.relpath(root, src_dir)
        for name in files:
            dst = os.path.join(dst_dir, rel_root, name)
            if os.path.exists(dst):
                continue
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            # Atomic per file: a kill mid-copy must not leave a truncated
            # cache entry that the exists() check above would then pin
            # forever (and future dumps would propagate). The random
            # suffix makes concurrent multihost writers on a shared PVC
            # collision-free (pids alone repeat across hosts) — same
            # content, last rename wins.
            tmp = f"{dst}.tmp-{uuid.uuid4().hex[:12]}"
            shutil.copyfile(os.path.join(root, name), tmp)
            os.replace(tmp, dst)
            copied += 1
    return copied


def save_compile_cache(snapshot_dir: str) -> int:
    """Bundle this process's compilation cache into a snapshot dir
    (called by the agentlet after the HBM dump). Returns files copied."""

    src = config.TPU_COMPILE_CACHE.get()
    if not src or not os.path.isdir(src):
        return 0
    return _copy_missing(src, os.path.join(snapshot_dir, COMPILE_CACHE_SUBDIR))


def seed_compile_cache(snapshot_dir: str) -> int:
    """Pre-seed the local compilation cache from a restored snapshot —
    call before the first jit so the step compile is a cache hit."""

    local = config.TPU_COMPILE_CACHE.get()
    carried = os.path.join(snapshot_dir, COMPILE_CACHE_SUBDIR)
    if not local or not os.path.isdir(carried):
        return 0
    os.makedirs(local, exist_ok=True)
    return _copy_missing(carried, local)


def restore_dir_from_env() -> str | None:
    """Workload-side helper: the HBM snapshot dir to restore from, if any.

    Checks ``GRIT_TPU_RESTORE_DIR`` (set by the shim on restore-mode
    creates) and returns it only when it holds a committed snapshot.
    """
    d = config.TPU_RESTORE_DIR.get()
    if not d:
        return None
    from grit_tpu.device.snapshot import snapshot_exists

    return d if snapshot_exists(d) else None
