"""Flash attention Pallas TPU kernel — causal self-attention, GQA-aware.

Canonical TPU formulation: the grid is (batch, q_head, q_tile, kv_tile) and
iterates **sequentially** on-core, so the online-softmax accumulators live
in VMEM scratch that persists across the innermost (kv_tile) grid axis —
no atomics, no cross-core reduction. Each (q_tile, kv_tile) step is one
MXU-shaped ``(BQ, hd) @ (hd, BK)`` product; causality skips whole tiles
above the diagonal (``pl.when``), masking only the diagonal tile.

GQA costs nothing here: the kv BlockSpec's index_map points q-head ``h`` at
kv-head ``h // group_size``, so grouped heads re-read the same kv tiles
straight from VMEM instead of materializing repeated heads in HBM (which is
what the XLA fallback's einsum reshape avoids too, but the kernel also
avoids the (B, KVH, G, Sq, Skv) score relayout).

Accumulation is float32 throughout (scores, running max/sum, output acc);
only the final normalized tile is cast back to the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def _diag_mask():
    """Boolean (BLOCK_Q, BLOCK_K) lower-triangle mask for the diagonal
    tile — the ONE causal mask rule, shared by forward and both backward
    kernels (fwd masks scores to -inf pre-exp; bwd masks probs to 0)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 1)
    return cols <= rows


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
            scale):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(kj <= qi)  # tiles strictly above the diagonal are skipped
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)          # (BQ, hd)
        k = k_ref[0, 0].astype(jnp.float32)          # (BK, hd)
        v = v_ref[0, 0].astype(jnp.float32)          # (BK, hd)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                     # (BQ, BK)

        @pl.when(kj == qi)
        def _mask_diag():
            _online_update(jnp.where(_diag_mask(), s, _NEG_INF), v,
                           m_scr, l_scr, acc_scr)

        @pl.when(kj < qi)
        def _full():
            _online_update(s, v, m_scr, l_scr, acc_scr)

    @pl.when(kj == pl.num_programs(3) - 1)
    def _finalize():
        out = acc_scr[:] / l_scr[:]
        o_ref[0, 0] = out.astype(o_ref.dtype)
        # Row logsumexp (m + log l): the only forward residual the fused
        # backward needs — O(S) instead of the O(S²) probs.
        lse_ref[0, 0] = m_scr[:] + jnp.log(l_scr[:])


def _online_update(s, v, m_scr, l_scr, acc_scr):
    m_prev = m_scr[:]                                 # (BQ, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)                            # (BQ, BK)
    alpha = jnp.exp(m_prev - m_new)                   # (BQ, 1)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scr[:] = m_new


@functools.partial(jax.jit, static_argnames=("interpret", "return_lse"))
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, interpret: bool = False,
    return_lse: bool = False,
):
    """Causal self-attention. q: (B, S, H, hd); k/v: (B, S, KVH, hd).

    Requires S % 128 == 0 and hd % 128 == 0 (the dispatcher in
    :mod:`grit_tpu.ops.attention` falls back to XLA otherwise).

    ``return_lse=True`` additionally returns the per-row logsumexp
    ``(B, H, S, 1)`` float32 — the forward residual the fused Pallas
    backward consumes.
    """
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    groups = H // KVH
    scale = 1.0 / (hd ** 0.5)

    # (B, H, S, hd) layout: heads become a grid axis, seq is contiguous.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, S // BLOCK_Q, S // BLOCK_K)
    out, lse = pl.pallas_call(
        functools.partial(_kernel, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, BLOCK_Q, hd), lambda b, h, i, j: (b, h, i, 0)
            ),
            # kv index clamps to the diagonal: above-diagonal steps (j > i)
            # are compute-skipped by pl.when, and mapping them to the same
            # block as j == i means Pallas re-uses the resident VMEM block
            # instead of streaming K/V tiles that would be discarded —
            # halves KV HBM traffic for causal attention.
            pl.BlockSpec(
                (1, 1, BLOCK_K, hd),
                lambda b, h, i, j, g=groups: (b, h // g, jnp.minimum(j, i), 0),
            ),
            pl.BlockSpec(
                (1, 1, BLOCK_K, hd),
                lambda b, h, i, j, g=groups: (b, h // g, jnp.minimum(j, i), 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, BLOCK_Q, hd), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, BLOCK_Q, 1), lambda b, h, i, j: (b, h, i, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, 1), jnp.float32),
            pltpu.VMEM((BLOCK_Q, 1), jnp.float32),
            pltpu.VMEM((BLOCK_Q, hd), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out.transpose(0, 2, 1, 3)
    if return_lse:
        return out, lse
    return out


# -- fused backward -----------------------------------------------------------
#
# FlashAttention-2 style: with S = scale·QKᵀ (masked), P = exp(S − L) where
# L is the forward's row logsumexp, and D = rowsum(dO ⊙ O):
#   dV = Pᵀ @ dO
#   dS = P ⊙ (dO @ Vᵀ − D)
#   dQ = scale · dS @ K         dK = scale · dSᵀ @ Q
# Two kernels: dQ accumulates over kv tiles (innermost axis j ≤ i); dK/dV
# accumulate over q tiles (innermost axis i ≥ j). Both recompute P from
# q/k/L tiles — the O(S²) probs never exist in HBM, which is the whole
# point of replacing the XLA-reference backward.


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(kj <= qi)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)            # (BQ, hd)
        k = k_ref[0, 0].astype(jnp.float32)            # (BK, hd)
        v = v_ref[0, 0].astype(jnp.float32)            # (BK, hd)
        do = do_ref[0, 0].astype(jnp.float32)          # (BQ, hd)
        lse = lse_ref[0, 0]                            # (BQ, 1)
        delta = delta_ref[0, 0]                        # (BQ, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                       # (BQ, BK)
        p = jnp.exp(s - lse)

        @pl.when(kj == qi)
        def _mask_diag():
            _dq_update(jnp.where(_diag_mask(), p, 0.0), do, v, delta, k,
                       dq_scr, scale)

        @pl.when(kj < qi)
        def _full():
            _dq_update(p, do, v, delta, k, dq_scr, scale)

    @pl.when(kj == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dq_update(p, do, v, delta, k, dq_scr, scale):
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                   # (BQ, BK)
    ds = p * (dp - delta)
    dq_scr[:] = dq_scr[:] + scale * jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale):
    kj = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(qi >= kj)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)            # (BQ, hd)
        k = k_ref[0, 0].astype(jnp.float32)            # (BK, hd)
        v = v_ref[0, 0].astype(jnp.float32)            # (BK, hd)
        do = do_ref[0, 0].astype(jnp.float32)          # (BQ, hd)
        lse = lse_ref[0, 0]                            # (BQ, 1)
        delta = delta_ref[0, 0]                        # (BQ, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse)

        @pl.when(qi == kj)
        def _mask_diag():
            _dkv_update(jnp.where(_diag_mask(), p, 0.0), q, do, v, delta,
                        dk_scr, dv_scr, scale)

        @pl.when(qi > kj)
        def _full():
            _dkv_update(p, q, do, v, delta, dk_scr, dv_scr, scale)

    @pl.when(qi == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _dkv_update(p, q, do, v, delta, dk_scr, dv_scr, scale):
    # dV += Pᵀ @ dO
    dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                                   # (BQ, BK)
    ds = p * (dp - delta)
    # dK += scale · dSᵀ @ Q
    dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_attention_bwd(
    q: jax.Array, k: jax.Array, v: jax.Array, lse: jax.Array,
    do: jax.Array, out: jax.Array, *, interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused causal-attention backward. Public layouts: q/do/out
    (B, S, H, hd); k/v (B, S, KVH, hd); ``lse`` (B, H, S, 1) from
    ``flash_attention(..., return_lse=True)``. Returns (dq, dk, dv) in
    the primal layouts/dtypes. GQA: per-q-head dk/dv partials reduce over
    each kv head's group outside the kernel."""
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    groups = H // KVH
    scale = 1.0 / (hd ** 0.5)

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    # D = rowsum(dO ⊙ O): cheap XLA pass, (B, H, S, 1) like lse.
    delta = jnp.sum(
        dot.astype(jnp.float32) * out.transpose(0, 2, 1, 3).astype(jnp.float32),
        axis=-1, keepdims=True,
    )

    q_spec = pl.BlockSpec((1, 1, BLOCK_Q, hd), lambda b, h, i, j: (b, h, i, 0))
    row_spec = pl.BlockSpec((1, 1, BLOCK_Q, 1), lambda b, h, i, j: (b, h, i, 0))
    kv_clamp = pl.BlockSpec(
        (1, 1, BLOCK_K, hd),
        lambda b, h, i, j, g=groups: (b, h // g, jnp.minimum(j, i), 0),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        grid=(B, H, S // BLOCK_Q, S // BLOCK_K),
        in_specs=[q_spec, kv_clamp, kv_clamp, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((BLOCK_Q, hd), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # dk/dv grid: kv tile outer, q tile innermost (scratch accumulates
    # over the q axis). Above-diagonal q tiles are compute-skipped and
    # their q-side loads clamped onto the diagonal block (same VMEM-reuse
    # trick as the forward's kv clamp).
    q_clamp = pl.BlockSpec(
        (1, 1, BLOCK_Q, hd),
        lambda b, h, j, i: (b, h, jnp.maximum(i, j), 0),
    )
    row_clamp = pl.BlockSpec(
        (1, 1, BLOCK_Q, 1),
        lambda b, h, j, i: (b, h, jnp.maximum(i, j), 0),
    )
    kv_spec = pl.BlockSpec(
        (1, 1, BLOCK_K, hd),
        lambda b, h, j, i, g=groups: (b, h // g, j, 0),
    )
    kv_out_spec = pl.BlockSpec(
        (1, 1, BLOCK_K, hd), lambda b, h, j, i: (b, h, j, 0)
    )
    # Without GQA there is no cross-head reduction: emit dk/dv in the
    # primal dtype straight from the kernel instead of fp32 partials
    # (halves the backward's dk/dv HBM writes on the common bf16 path).
    part_dtype = jnp.float32 if groups > 1 else k.dtype
    dkh, dvh = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, hd), part_dtype),
            jax.ShapeDtypeStruct((B, H, S, hd), part_dtype),
        ],
        grid=(B, H, S // BLOCK_K, S // BLOCK_Q),
        in_specs=[q_clamp, kv_spec, kv_spec, q_clamp, row_clamp, row_clamp],
        out_specs=[kv_out_spec, kv_out_spec],
        interpret=interpret,
        scratch_shapes=[
            pltpu.VMEM((BLOCK_K, hd), jnp.float32),
            pltpu.VMEM((BLOCK_K, hd), jnp.float32),
        ],
    )(qt, kt, vt, dot, lse, delta)

    if groups > 1:
        # GQA reduction in fp32: grouped q heads share a kv head.
        dk = dkh.reshape(B, KVH, groups, S, hd).sum(axis=2).astype(k.dtype)
        dv = dvh.reshape(B, KVH, groups, S, hd).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dkh, dvh
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )