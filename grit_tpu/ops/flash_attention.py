"""Flash attention Pallas TPU kernel — causal self-attention, GQA-aware.

Canonical TPU formulation: the grid is (batch, q_head, q_tile, kv_tile) and
iterates **sequentially** on-core, so the online-softmax accumulators live
in VMEM scratch that persists across the innermost (kv_tile) grid axis —
no atomics, no cross-core reduction. Each (q_tile, kv_tile) step is one
MXU-shaped ``(BQ, hd) @ (hd, BK)`` product; causality skips whole tiles
above the diagonal (``pl.when``), masking only the diagonal tile.

GQA costs nothing here: the kv BlockSpec's index_map points q-head ``h`` at
kv-head ``h // group_size``, so grouped heads re-read the same kv tiles
straight from VMEM instead of materializing repeated heads in HBM (which is
what the XLA fallback's einsum reshape avoids too, but the kernel also
avoids the (B, KVH, G, Sq, Skv) score relayout).

Accumulation is float32 throughout (scores, running max/sum, output acc);
only the final normalized tile is cast back to the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(kj <= qi)  # tiles strictly above the diagonal are skipped
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)          # (BQ, hd)
        k = k_ref[0, 0].astype(jnp.float32)          # (BK, hd)
        v = v_ref[0, 0].astype(jnp.float32)          # (BK, hd)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                     # (BQ, BK)

        @pl.when(kj == qi)
        def _mask_diag():
            rows = jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (BLOCK_Q, BLOCK_K), 1)
            s_masked = jnp.where(cols <= rows, s, _NEG_INF)
            _online_update(s_masked, v, m_scr, l_scr, acc_scr)

        @pl.when(kj < qi)
        def _full():
            _online_update(s, v, m_scr, l_scr, acc_scr)

    @pl.when(kj == pl.num_programs(3) - 1)
    def _finalize():
        out = acc_scr[:] / l_scr[:]
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _online_update(s, v, m_scr, l_scr, acc_scr):
    m_prev = m_scr[:]                                 # (BQ, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)                            # (BQ, BK)
    alpha = jnp.exp(m_prev - m_new)                   # (BQ, 1)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scr[:] = m_new


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """Causal self-attention. q: (B, S, H, hd); k/v: (B, S, KVH, hd).

    Requires S % 128 == 0 and hd % 128 == 0 (the dispatcher in
    :mod:`grit_tpu.ops.attention` falls back to XLA otherwise).
    """
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    groups = H // KVH
    scale = 1.0 / (hd ** 0.5)

    # (B, H, S, hd) layout: heads become a grid axis, seq is contiguous.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, S // BLOCK_Q, S // BLOCK_K)
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, BLOCK_Q, hd), lambda b, h, i, j: (b, h, i, 0)
            ),
            # kv index clamps to the diagonal: above-diagonal steps (j > i)
            # are compute-skipped by pl.when, and mapping them to the same
            # block as j == i means Pallas re-uses the resident VMEM block
            # instead of streaming K/V tiles that would be discarded —
            # halves KV HBM traffic for causal attention.
            pl.BlockSpec(
                (1, 1, BLOCK_K, hd),
                lambda b, h, i, j, g=groups: (b, h // g, jnp.minimum(j, i), 0),
            ),
            pl.BlockSpec(
                (1, 1, BLOCK_K, hd),
                lambda b, h, i, j, g=groups: (b, h // g, jnp.minimum(j, i), 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, BLOCK_Q, hd), lambda b, h, i, j: (b, h, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, 1), jnp.float32),
            pltpu.VMEM((BLOCK_Q, 1), jnp.float32),
            pltpu.VMEM((BLOCK_Q, hd), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)