"""TPU ops — Pallas kernels with XLA fallbacks.

Hot-path ops for the in-tree workloads. Every op has a pure-XLA reference
implementation (used on CPU and as the correctness oracle) and, where it
pays, a Pallas TPU kernel selected at dispatch time.
"""
