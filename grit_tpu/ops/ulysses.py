"""Ulysses attention — all-to-all sequence parallelism.

The second of the two context-parallel schemes (the first is
:mod:`grit_tpu.ops.ring_attention`): instead of rotating K/V blocks around
the mesh axis, one ``all_to_all`` re-partitions the activations from
sequence-sharded to **head**-sharded, every chip runs ordinary full-sequence
causal attention for its subset of heads, and a second ``all_to_all``
restores sequence sharding (the DeepSpeed-Ulysses layout dance, built here
from ``lax.all_to_all`` under ``shard_map``).

Trade-offs vs the ring, so callers can pick per workload:

- communication: Ulysses moves each activation twice through ICI all-to-all
  (volume O(B·S·H·hd/N) per chip, latency two collectives); the ring does
  N-1 neighbor ``ppermute`` hops overlapped with compute. All-to-all is
  better at small N / short hops; the ring wins when N is large or overlap
  hides the transfer.
- constraints: Ulysses needs ``n_kv_heads % N == 0`` (heads are the sharded
  resource during attention); the ring only needs ``S % N == 0``.
- kernels: each Ulysses chip sees a plain dense/flash attention over the
  full sequence, so the Pallas kernel applies unchanged
  (:func:`grit_tpu.ops.attention.causal_attention` dispatch included);
  the ring re-implements online softmax at the mesh level.

Reference analogue: none (SURVEY §2.4 — the reference has no model code);
this is part of the "long-context is first-class" surface.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from grit_tpu.ops.attention import causal_attention
from grit_tpu.parallel.compat import shard_map


def _ulysses_local(q, k, v, *, axis_name: str):
    """Per-shard body. Local shapes q: (B, S/N, H, hd), k/v: (B, S/N, KVH, hd)
    → out (B, S/N, H, hd)."""
    # seq-sharded → head-sharded: split heads N ways, gather the sequence.
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # (B, S, H/N, hd): plain causal attention over the full sequence for
    # this chip's heads — the flash kernel dispatch applies as-is.
    out = causal_attention(q, k, v)
    # head-sharded → seq-sharded.
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "seq",
) -> jax.Array:
    """Causal self-attention with the sequence sharded over ``mesh[axis]``.

    q: (B, S, H, hd), k/v: (B, S, KVH, hd), S and both head counts divisible
    by the axis size. Returns output with the same sequence sharding —
    drop-in interchangeable with :func:`ring_attention`.
    """
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"sequence {q.shape[1]} not divisible by {axis}={n}")
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            f"ulysses shards heads during attention: heads {q.shape[2]}/"
            f"kv heads {k.shape[2]} must divide by {axis}={n} "
            "(use ring_attention when they don't)"
        )
    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(_ulysses_local, axis_name=axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
