"""Mixture-of-Experts layer with expert parallelism (GShard-style).

TPU-native formulation: top-k routing (k=1 Switch-style, k=2
Mixtral-style) with a static per-expert capacity, and dispatch/combine
as dense one-hot einsums — fully static shapes, so XLA tiles the expert
matmuls onto the MXU and inserts the all-to-alls itself when the expert
dimension is sharded (``with_sharding_constraint`` over the ``expert``
mesh axis). No sparse scatter/gather, no data-dependent shapes:
dropped-token masking is a multiply. Lower-k slots have dispatch
priority (GShard): a token's second choice only takes capacity first
choices left unused.

Pieces:
- :func:`init_moe_params` — router + per-expert MLP weights (leading
  expert axis, shardable over ``expert``).
- :func:`moe_mlp` — the layer; returns ``(y, aux_loss)`` where aux is the
  standard load-balancing loss (mean expert fraction × mean router
  probability × E).
- :func:`expert_shardings` — NamedShardings for the param tree.

Reference has no model/parallelism layer at all (SURVEY §2.4); this is
part of the first-class distributed surface, the ``ep`` axis of
``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EXPERT_AXIS = "expert"


def init_moe_params(
    key: jax.Array,
    dim: int,
    hidden: int,
    n_experts: int,
    dtype=jnp.float32,
) -> dict[str, jax.Array]:
    k_r, k_in, k_out = jax.random.split(key, 3)
    scale_in = 1.0 / math.sqrt(dim)
    scale_out = 1.0 / math.sqrt(hidden)
    return {
        "router": (jax.random.normal(k_r, (dim, n_experts)) * scale_in
                   ).astype(dtype),
        "w_in": (jax.random.normal(k_in, (n_experts, dim, hidden))
                 * scale_in).astype(dtype),
        "w_out": (jax.random.normal(k_out, (n_experts, hidden, dim))
                  * scale_out).astype(dtype),
    }


def expert_shardings(mesh: Mesh, axis: str = EXPERT_AXIS) -> dict[str, Any]:
    """Param shardings: experts sharded, router replicated."""

    return {
        "router": NamedSharding(mesh, P()),
        "w_in": NamedSharding(mesh, P(axis)),
        "w_out": NamedSharding(mesh, P(axis)),
    }


def moe_mlp(
    params: dict[str, jax.Array],
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    mesh: Mesh | None = None,
    axis: str = EXPERT_AXIS,
    top_k: int = 1,
    token_mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k MoE feed-forward over tokens ``x`` of shape ``(T, D)``.

    Returns ``(y, aux_loss)``; tokens routed beyond an expert's capacity
    contribute zero output (standard GShard token dropping — the residual
    connection around the layer carries them through). ``top_k=1`` is the
    Switch formulation (gate = raw router probability); ``top_k>1`` is
    Mixtral's (gates renormalized over the selected experts, so the layer
    output is a convex combination of its experts).

    ``token_mask`` (T,) bool: masked-out tokens (bucket padding, released
    serving slots) are excluded from routing entirely — they consume no
    expert capacity, contribute zero output, and don't skew the aux loss.
    Without it, garbage rows would compete with real tokens for capacity
    and an active sequence's output could change when unrelated slots
    join or leave (the batching-invisibility invariant).
    """

    tokens, _dim = x.shape
    n_experts = params["router"].shape[1]
    if not 1 <= top_k <= n_experts:
        raise ValueError(f"top_k={top_k} out of range for {n_experts} experts")
    capacity = max(1, int(math.ceil(
        tokens * top_k / n_experts * capacity_factor)))

    # Routing math stays f32 regardless of the activation dtype: the
    # position cumsum is integer bookkeeping, and bf16 cannot represent
    # integers above 256 — two tokens would silently share one capacity
    # slot at llama-scale T (advisor finding).
    logits = (x.astype(jnp.float32)
              @ params["router"].astype(jnp.float32))  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topk_probs, topk_idx = jax.lax.top_k(probs, top_k)  # (T, k) each
    if top_k > 1:
        gates = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)
    else:
        gates = topk_probs

    # Slot j's positions start after the tokens slots < j actually KEPT in
    # each expert's queue (lower slots have priority; offsetting by kept
    # counts rather than routed counts wastes no capacity on drops).
    mask_f = (jnp.ones((tokens,), jnp.float32) if token_mask is None
              else token_mask.astype(jnp.float32))
    dispatch = jnp.zeros((tokens, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((tokens, n_experts, capacity), jnp.float32)
    kept_per_expert = jnp.zeros((n_experts,), jnp.float32)
    onehot0 = None
    for j in range(top_k):
        onehot = jax.nn.one_hot(topk_idx[:, j], n_experts, dtype=jnp.float32)
        onehot = onehot * mask_f[:, None]  # masked rows route nowhere
        if j == 0:
            onehot0 = onehot
        position = (jnp.cumsum(onehot, axis=0) - 1.0
                    + kept_per_expert[None, :])        # (T, E)
        keep = (position < capacity).astype(jnp.float32) * onehot
        kept_per_expert = kept_per_expert + jnp.sum(keep, axis=0)
        # one_hot of an out-of-capacity index is the zero vector, so the
        # keep mask and the position encoding agree on drops.
        pos_onehot = jax.nn.one_hot(
            position.astype(jnp.int32), capacity, dtype=jnp.float32)
        slot = keep[:, :, None] * pos_onehot           # (T, E, C)
        dispatch = dispatch + slot
        combine = combine + slot * gates[:, j][:, None, None]
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    xe = jnp.einsum("tec,td->ecd", dispatch, x)        # (E, C, D)
    if mesh is not None and axis in mesh.axis_names:
        # Shard the expert dimension: XLA materializes the all-to-all
        # between token-sharded and expert-sharded layouts.
        xe = jax.lax.with_sharding_constraint(
            xe, NamedSharding(mesh, P(axis)))
    # Expert weights cast to the activation dtype so the dominant FLOPs
    # run at bf16 MXU rate, matching the dense path's convention.
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe,
                               params["w_in"].astype(x.dtype)))
    ye = jnp.einsum("ech,ehd->ecd", h,
                    params["w_out"].astype(x.dtype))   # (E, C, D)
    if mesh is not None and axis in mesh.axis_names:
        ye = jax.lax.with_sharding_constraint(
            ye, NamedSharding(mesh, P(axis)))
    y = jnp.einsum("tec,ecd->td", combine, ye)         # (T, D)

    # Load-balancing aux loss (Shazeer/GShard): encourages uniform
    # routing; scaled so a perfectly uniform router scores 1.0. First-
    # choice fractions, per the GShard top-2 formulation; statistics run
    # over unmasked tokens only.
    denom = jnp.maximum(jnp.sum(mask_f), 1.0)
    fraction = jnp.sum(onehot0, axis=0) / denom        # (E,)
    mean_prob = jnp.sum(probs * mask_f[:, None], axis=0) / denom
    aux = jnp.sum(fraction * mean_prob) * n_experts

    return y, aux
