"""Mixture-of-Experts layer with expert parallelism (GShard-style).

TPU-native formulation: routing is top-1 with a static per-expert
capacity, and dispatch/combine are dense one-hot einsums — fully static
shapes, so XLA tiles the expert matmuls onto the MXU and inserts the
all-to-alls itself when the expert dimension is sharded
(``with_sharding_constraint`` over the ``expert`` mesh axis). No sparse
scatter/gather, no data-dependent shapes: dropped-token masking is a
multiply.

Pieces:
- :func:`init_moe_params` — router + per-expert MLP weights (leading
  expert axis, shardable over ``expert``).
- :func:`moe_mlp` — the layer; returns ``(y, aux_loss)`` where aux is the
  standard load-balancing loss (mean expert fraction × mean router
  probability × E).
- :func:`expert_shardings` — NamedShardings for the param tree.

Reference has no model/parallelism layer at all (SURVEY §2.4); this is
part of the first-class distributed surface, the ``ep`` axis of
``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EXPERT_AXIS = "expert"


def init_moe_params(
    key: jax.Array,
    dim: int,
    hidden: int,
    n_experts: int,
    dtype=jnp.float32,
) -> dict[str, jax.Array]:
    k_r, k_in, k_out = jax.random.split(key, 3)
    scale_in = 1.0 / math.sqrt(dim)
    scale_out = 1.0 / math.sqrt(hidden)
    return {
        "router": (jax.random.normal(k_r, (dim, n_experts)) * scale_in
                   ).astype(dtype),
        "w_in": (jax.random.normal(k_in, (n_experts, dim, hidden))
                 * scale_in).astype(dtype),
        "w_out": (jax.random.normal(k_out, (n_experts, hidden, dim))
                  * scale_out).astype(dtype),
    }


def expert_shardings(mesh: Mesh, axis: str = EXPERT_AXIS) -> dict[str, Any]:
    """Param shardings: experts sharded, router replicated."""

    return {
        "router": NamedSharding(mesh, P()),
        "w_in": NamedSharding(mesh, P(axis)),
        "w_out": NamedSharding(mesh, P(axis)),
    }


def moe_mlp(
    params: dict[str, jax.Array],
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    mesh: Mesh | None = None,
    axis: str = EXPERT_AXIS,
) -> tuple[jax.Array, jax.Array]:
    """Top-1 MoE feed-forward over tokens ``x`` of shape ``(T, D)``.

    Returns ``(y, aux_loss)``; tokens routed beyond an expert's capacity
    contribute zero output (standard GShard token dropping — the residual
    connection around the layer carries them through).
    """

    tokens, _dim = x.shape
    n_experts = params["router"].shape[1]
    capacity = max(1, int(math.ceil(
        tokens / n_experts * capacity_factor)))

    # Routing math stays f32 regardless of the activation dtype: the
    # position cumsum is integer bookkeeping, and bf16 cannot represent
    # integers above 256 — two tokens would silently share one capacity
    # slot at llama-scale T (advisor finding).
    logits = (x.astype(jnp.float32)
              @ params["router"].astype(jnp.float32))  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_of = jnp.argmax(probs, axis=-1)             # (T,)
    gate = jnp.take_along_axis(probs, expert_of[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert_of, n_experts, dtype=jnp.float32)
    # Position of each token within its expert's queue; tokens past
    # capacity are dropped (masked to zero contribution).
    position = jnp.cumsum(onehot, axis=0) - 1.0        # (T, E)
    keep = (position < capacity).astype(jnp.float32) * onehot
    pos_onehot = jax.nn.one_hot(
        position.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = (keep[:, :, None] * pos_onehot).astype(x.dtype)  # (T, E, C)
    combine = (dispatch.astype(jnp.float32)
               * gate[:, None, None]).astype(x.dtype)  # (T, E, C)

    xe = jnp.einsum("tec,td->ecd", dispatch, x)        # (E, C, D)
    if mesh is not None and axis in mesh.axis_names:
        # Shard the expert dimension: XLA materializes the all-to-all
        # between token-sharded and expert-sharded layouts.
        xe = jax.lax.with_sharding_constraint(
            xe, NamedSharding(mesh, P(axis)))
    # Expert weights cast to the activation dtype so the dominant FLOPs
    # run at bf16 MXU rate, matching the dense path's convention.
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe,
                               params["w_in"].astype(x.dtype)))
    ye = jnp.einsum("ech,ehd->ecd", h,
                    params["w_out"].astype(x.dtype))   # (E, C, D)
    if mesh is not None and axis in mesh.axis_names:
        ye = jax.lax.with_sharding_constraint(
            ye, NamedSharding(mesh, P(axis)))
    y = jnp.einsum("tec,ecd->td", combine, ye)         # (T, D)

    # Load-balancing aux loss (Shazeer/GShard): encourages uniform
    # routing; scaled so a perfectly uniform router scores 1.0.
    fraction = jnp.mean(onehot, axis=0)                # (E,)
    mean_prob = jnp.mean(probs, axis=0)                # (E,)
    aux = jnp.sum(fraction * mean_prob) * n_experts

    return y, aux
