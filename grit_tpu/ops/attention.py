"""Causal (flash) attention with GQA — Pallas TPU kernel + XLA fallback.

Signature covers both training (self-attention, ``q_len == kv_len``) and
serving decode (``q`` is the new suffix attending into a longer KV cache):

- ``q``: (B, Sq, n_heads, hd)
- ``k``/``v``: (B, Skv, n_kv_heads, hd) — GQA: ``n_heads % n_kv_heads == 0``
- ``q_offset``: absolute position of ``q[:, 0]`` within the KV axis
  (0 for training; cache length for decode).
- ``kv_len``: number of valid KV entries (≤ Skv); entries beyond are
  masked (the cache is allocated at ``max_seq_len``).

Mask rule: query at absolute position ``a = q_offset + i`` may attend key
``j`` iff ``j <= a`` and ``j < kv_len``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_differentiable(q, k, v, interpret=False):
    """Flash forward with a FUSED Pallas backward.

    The Pallas kernel has no autodiff rule, so without this wrapper any
    training loss through the flash path fails at trace time. The
    backward recomputes attention probabilities tile-by-tile from the
    forward's O(S) logsumexp residual (FlashAttention-2 formulation) in
    two Pallas kernels — the O(S²) probability matrix never exists in
    HBM in either direction, unlike the earlier XLA-reference backward
    that rematerialized it.
    """
    from grit_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, interpret=interpret)


def _flash_fwd(q, k, v, interpret):
    from grit_tpu.ops.flash_attention import flash_attention

    out, lse = flash_attention(q, k, v, interpret=interpret,
                               return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(interpret, res, g):
    from grit_tpu.ops.flash_attention import flash_attention_bwd

    q, k, v, out, lse = res
    return flash_attention_bwd(q, k, v, lse, g, out, interpret=interpret)


_flash_differentiable.defvjp(_flash_fwd, _flash_bwd)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_offset: jax.Array | int = 0,
    kv_len: jax.Array | None = None,
) -> jax.Array:
    """Dispatch: Pallas flash kernel on TPU for the training shape, XLA
    reference otherwise (CPU, decode path, ragged cases)."""
    if _use_flash(q, k, q_offset, kv_len):
        return _flash_differentiable(q, k, v)
    return attention_reference(q, k, v, q_offset=q_offset, kv_len=kv_len)


def _use_flash(q, k, q_offset, kv_len) -> bool:
    if kv_len is not None or not isinstance(q_offset, int) or q_offset != 0:
        return False
    if q.shape[1] != k.shape[1]:
        return False
    try:
        if jax.devices()[0].platform != "tpu":
            return False
    except RuntimeError:
        return False
    # Flash tiles want MXU/VPU-aligned shapes; fall back otherwise.
    return q.shape[1] % 128 == 0 and q.shape[-1] % 128 == 0


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_offset: jax.Array | int = 0,
    kv_len: jax.Array | None = None,
) -> jax.Array:
    """``q_offset`` and ``kv_len`` may be scalars or per-batch ``(B,)``
    arrays — the ragged case continuous batching needs, where every
    sequence in the batch sits at its own position in the KV cache."""
    B, Sq, H, hd = q.shape
    _, Skv, KVH, _ = k.shape
    groups = H // KVH
    # (B, KVH, groups, Sq, hd) x (B, KVH, Skv, hd) — GQA without
    # materializing repeated KV heads.
    qg = q.reshape(B, Sq, KVH, groups, hd).transpose(0, 2, 3, 1, 4)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    scores = jnp.einsum(
        "bkgqh,bkjh->bkgqj", qg, kt, preferred_element_type=jnp.float32
    ) / jnp.sqrt(hd).astype(jnp.float32)

    offset = jnp.asarray(q_offset)
    offset_b = jnp.broadcast_to(offset.reshape(-1, 1), (B, 1))  # (B, 1)
    abs_q = jnp.arange(Sq)[None, :] + offset_b                  # (B, Sq)
    key_pos = jnp.arange(Skv)                                   # (Skv,)
    mask = key_pos[None, None, :] <= abs_q[:, :, None]          # (B, Sq, Skv)
    if kv_len is not None:
        kv_len_b = jnp.broadcast_to(jnp.asarray(kv_len).reshape(-1, 1), (B, 1))
        mask = mask & (key_pos[None, None, :] < kv_len_b[:, :, None])
    scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqj,bkjh->bkgqh", probs, vt)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, hd)
