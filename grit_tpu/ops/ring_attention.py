"""Ring attention — causal attention over a sequence sharded across chips.

Long-context support: when one chip cannot hold S×S attention (or even the
sequence itself), the sequence axis is sharded over a mesh axis and K/V
blocks rotate around the ring via ``ppermute`` while every chip keeps only
its local Q block and online-softmax accumulators. Peak memory per chip is
O(S/N · hd) instead of O(S²); the K/V transfer rides ICI neighbor links
(the ``ppermute`` pattern XLA lowers to ICI hops, not all-to-all).

Causality at block granularity makes half the ring steps free: a chip
skips K/V blocks from later sequence positions entirely, applies the
triangular mask only on its own (diagonal) block, and attends fully to
earlier blocks — the same skip/diag/full trichotomy as the flash kernel's
tile loop (:mod:`grit_tpu.ops.flash_attention`), lifted to the mesh level.

Composability: within each ring step the block attention is plain XLA ops,
so the Pallas flash kernel can be substituted per-block on TPU; the
all-gather-free structure is what matters at the mesh level.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grit_tpu.parallel.compat import pvary, shard_map

_NEG_INF = -1e30


def _block_attention(q, k, v, m, l, acc, mask_mode, q_offset, kv_offset):
    """One online-softmax update of local q against one K/V block.

    mask_mode: 0 = skip (kv entirely in the future), 1 = diagonal
    (elementwise causal mask), 2 = full (kv entirely in the past).
    All in f32; shapes q: (B, Sq, H, hd), k/v: (B, Skv, KVH, hd).
    """
    B, Sq, H, hd = q.shape
    Skv, KVH = k.shape[1], k.shape[2]
    groups = H // KVH

    qg = q.reshape(B, Sq, KVH, groups, hd).transpose(0, 2, 3, 1, 4)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    s = jnp.einsum(
        "bkgqh,bkjh->bkgqj", qg, kt, preferred_element_type=jnp.float32
    ) / jnp.sqrt(hd).astype(jnp.float32)

    rows = (jnp.arange(Sq) + q_offset)[:, None]
    cols = (jnp.arange(Skv) + kv_offset)[None, :]
    elementwise = cols <= rows                      # (Sq, Skv)
    keep = jnp.where(
        mask_mode == 0,
        jnp.zeros_like(elementwise),
        jnp.where(mask_mode == 1, elementwise, jnp.ones_like(elementwise)),
    )
    s = jnp.where(keep[None, None, None], s, _NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bkgqj,bkjh->bkgqh", p, vt, preferred_element_type=jnp.float32
    )
    # A fully-masked block contributes nothing; keep old stats then.
    skip = mask_mode == 0
    return (
        jnp.where(skip, m, m_new),
        jnp.where(skip, l, l_new),
        jnp.where(skip, acc, acc_new),
    )


def _ring_body(axis_name, n_shards, local_len, carry, r):
    q, k, v, m, l, acc, my_idx = carry
    kv_idx = (my_idx - r) % n_shards
    mask_mode = jnp.where(
        kv_idx > my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2)
    )
    m, l, acc = _block_attention(
        q, k, v, m, l, acc, mask_mode,
        q_offset=my_idx * local_len, kv_offset=kv_idx * local_len,
    )
    # Rotate K/V to the next chip (neighbor exchange — ICI-friendly).
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    k = lax.ppermute(k, axis_name, perm)
    v = lax.ppermute(v, axis_name, perm)
    return (q, k, v, m, l, acc, my_idx), None


def _ring_attention_local(q, k, v, *, axis_name, n_shards):
    """Per-shard body (runs under shard_map). q/k/v: local (B, s, H, hd)."""
    B, s_local, H, hd = q.shape
    KVH = k.shape[2]
    groups = H // KVH
    my_idx = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32)
    m = jnp.full((B, KVH, groups, s_local, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, KVH, groups, s_local, 1), jnp.float32)
    acc = jnp.zeros((B, KVH, groups, s_local, hd), jnp.float32)
    # The accumulators start as replicated constants but the scan body makes
    # them device-varying; mark them varying up front so the carry types
    # match (newer shard_map tracks varying manual axes explicitly).
    m, l, acc = (pvary(x, (axis_name,)) for x in (m, l, acc))

    body = partial(_ring_body, axis_name, n_shards, s_local)
    (qf, k, v, m, l, acc, _), _ = lax.scan(
        body,
        (qf, k.astype(jnp.float32), v.astype(jnp.float32), m, l, acc, my_idx),
        jnp.arange(n_shards),
    )
    out = acc / l
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, s_local, H, hd)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "data",
) -> jax.Array:
    """Causal self-attention with the sequence sharded over ``mesh[axis]``.

    q/k/v: (B, S, H, hd) with S divided across the axis; S % axis_size == 0.
    Returns output with the same sequence sharding.
    """
    n = mesh.shape[axis]
    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis, n_shards=n),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
