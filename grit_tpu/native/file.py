"""ctypes bindings for the native file data plane (``native/gritio/
gritio_file.cc``) — the dump→place half of the gritio split.

PR 10 made the *wire* plane native; BENCH_r09's profiler showed the
*file* legs were still Python frame loops (``prof_place_python_share``
1.0, ``prof_dump_python_share`` 0.45). This module is the same split
applied to disk: Python stays the control plane (the codec's adaptive
per-chunk sampling decision, sidecar/journal/commit writing, fault
points, stage gating) while the byte loops move into C —

- **drain**: the snapshot mirror's chunk loop runs in a C worker that
  fuses per-block CRC32-of-raw, zero-block elision and zlib compression
  with the ratio raw-ship rule into one pass, appending container
  payloads through the O_DIRECT double-buffered writer; block records
  surface back so Python writes the byte-identical ``.gritc`` sidecar;
- **place**: container block records (Python parses the sidecar) are
  batch-read (io_uring where the kernel has one, concurrent preads
  otherwise), decompressed, CRC-verified and copied into the caller's
  buffer in one GIL-released call;
- **batched raw reads**: one chunk range split into queue-depth
  segment reads with the manifest CRC (crc32 or crc32c) folded after
  assembly.

Degrade contract (the wire plane's, verbatim): when the library is
absent/stale or ``GRIT_IO_NATIVE=0``, every leg keeps the pure-Python
loop and the degrade is LOUD — logged once per reason, counted in
``grit_io_degrade_total``, and stamped on the migration timeline as an
``io.degrade`` flight event by the call sites that own a flight dir. A
silent fallback would masquerade as the 10x-slower plane this module
exists to retire.

jax-free on purpose: the agent layer (``grit_tpu.codec``) imports this.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading

from grit_tpu import native
from grit_tpu.api import config
from grit_tpu.obs.metrics import IO_DEGRADE, IO_NATIVE_BYTES, IO_READ_BATCHES

log = logging.getLogger(__name__)

#: Codec ids on the C ABI ↔ grit_tpu.codec names.
CODEC_IDS = {"none": 0, "zlib": 1, "zero": 2}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}

#: ABI version this wrapper speaks; a .so answering anything else is
#: treated as absent (stale builds must degrade, not misread records).
ABI_VERSION = 1

#: Compression block size — must match grit_tpu.codec.BLOCK_BYTES so
#: native and Python containers are interchangeable at rest.
BLOCK_BYTES = 4 * 1024 * 1024

# Error codes beyond -errno (keep in sync with gritio_file.cc).
_ERR_CODEC = -9001
_ERR_SIZE = -9002
_ERR_CRC = -9003
_ERR_SHORT = -9004
_ERR_COVER = -9005
_ERR_ZLIB = -9006
_ERR_STATE = -9007
_DATA_ERRS = {
    _ERR_CODEC: "unknown codec id in a block record",
    _ERR_SIZE: "decompressed size mismatch",
    _ERR_CRC: "CRC-of-raw mismatch after decode (corrupt in transit)",
    _ERR_SHORT: "short read of a payload range",
    _ERR_COVER: "block records do not cover the requested range",
    _ERR_ZLIB: "zlib decode/encode failure (corrupt payload)",
}


class NativeDataError(RuntimeError):
    """The native plane decoded corrupt data (CRC/size/coverage) — the
    same class of failure the Python plane raises CodecError for.
    Callers MUST propagate this as a torn transfer, never retry it on
    the Python plane (the bytes are bad on disk, not the engine)."""


class NativePlaneError(RuntimeError):
    """A mechanical native-plane failure (errno-class). Callers degrade
    to the Python plane LOUDLY (record_degrade + io.degrade event)."""


class BlockRecStruct(ctypes.Structure):
    """Mirror of ``BlockRec`` in gritio_file.cc (40 bytes)."""

    _fields_ = [
        ("codec", ctypes.c_int32),
        ("crc_raw", ctypes.c_uint32),
        ("raw_off", ctypes.c_int64),
        ("raw_n", ctypes.c_int64),
        ("comp_off", ctypes.c_int64),
        ("comp_n", ctypes.c_int64),
    ]


_lock = threading.Lock()
_LIB = None
_TRIED = False


def _configure(lib: ctypes.CDLL) -> bool:
    try:
        lib.gritio_file_abi.restype = ctypes.c_int
        if lib.gritio_file_abi() != ABI_VERSION:
            return False
    except AttributeError:
        return False
    lib.gritio_uring_available.restype = ctypes.c_int
    lib.gritio_drain_open.restype = ctypes.c_void_p
    lib.gritio_drain_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.gritio_drain_put.restype = ctypes.c_int
    lib.gritio_drain_put.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.gritio_drain_flush.restype = ctypes.c_int
    lib.gritio_drain_flush.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.gritio_drain_error.restype = ctypes.c_int
    lib.gritio_drain_error.argtypes = [ctypes.c_void_p]
    lib.gritio_drain_records.restype = ctypes.c_int64
    lib.gritio_drain_records.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.gritio_drain_stats.restype = ctypes.c_int
    lib.gritio_drain_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gritio_drain_close.restype = ctypes.c_int
    lib.gritio_drain_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.gritio_drain_abandon.restype = None
    lib.gritio_drain_abandon.argtypes = [ctypes.c_void_p]
    lib.gritio_place_container.restype = ctypes.c_int
    lib.gritio_place_container.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.gritio_read_batched.restype = ctypes.c_int64
    lib.gritio_read_batched.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.gritio_sha256_available.restype = ctypes.c_int
    lib.gritio_sha256_hex.restype = ctypes.c_int
    lib.gritio_sha256_hex.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
    ]
    return True


def _load() -> ctypes.CDLL | None:
    """The shared libgritio handle with file-plane symbols, or None
    (absent library, or one predating the file plane / stale ABI)."""
    global _LIB, _TRIED
    with _lock:
        if _TRIED:
            return _LIB
        _TRIED = True
        lib = native.load()
        if lib is not None and _configure(lib):
            _LIB = lib
        return _LIB


def enabled() -> bool:
    """True when the native file plane will be used: the master knobs
    (``GRIT_IO_NATIVE``, ``GRIT_TPU_NATIVE``) are on AND the library
    carries the file-plane ABI."""
    if not config.IO_NATIVE.get():
        return False
    return _load() is not None


def unavailable_reason() -> str | None:
    """Why :func:`enabled` is False — 'disabled' (knob off) or
    'unavailable' (library absent/stale) — or None when enabled. The
    loud half of the degrade contract keys its events off this."""
    if not config.IO_NATIVE.get():
        return "disabled"
    return None if _load() is not None else "unavailable"


def uring_available() -> bool:
    lib = _load()
    return bool(lib is not None and lib.gritio_uring_available())


_degrade_logged: set[str] = set()


def record_degrade(reason: str, detail: str = "") -> None:
    """Count (every time) and log (once per reason) a leg falling back
    to the Python plane. Call sites that own a flight dir additionally
    stamp the ``io.degrade`` event on the migration timeline."""
    IO_DEGRADE.inc(reason=reason)
    if reason not in _degrade_logged:
        _degrade_logged.add(reason)
        log.warning(
            "native file plane degrading to the Python byte loops "
            "(reason=%s%s) — see GRIT_IO_NATIVE / native/gritio",
            reason, f": {detail}" if detail else "")


def _reset_for_tests() -> None:
    global _LIB, _TRIED
    with _lock:
        _LIB = None
        _TRIED = False
    _degrade_logged.clear()


def _depth() -> int:
    return max(1, int(config.IO_PLACE_DEPTH.get()))


def _allow_uring() -> int:
    return 1 if config.IO_URING.get() else 0


def _raise_errno(code: int, what: str) -> None:
    if code in _DATA_ERRS:
        raise NativeDataError(f"{what}: {_DATA_ERRS[code]}")
    raise NativePlaneError(f"{what}: errno {-code}")


class NativeDrain:
    """One dump mirror's native drain session (container or raw tee).

    ``put`` enqueues a chunk into the C worker (bounded in bytes by
    ``max_inflight``; the copy happens under a released GIL) with the
    chunk's adaptive codec decision — the *decision* stays Python
    (``codec.decide_codec``), the work moves native. ``finish_records``
    returns the accumulated block records for the sidecar; ``close``
    joins the worker and commits the file; ``abandon`` is the
    never-hang-the-dump teardown."""

    def __init__(self, path: str, stream_codec: str, *,
                 max_inflight_bytes: int, min_ratio: float,
                 block_bytes: int = BLOCK_BYTES) -> None:
        lib = _load()
        if lib is None:
            raise NativePlaneError("native file plane not available")
        if stream_codec not in ("none", "zlib"):
            raise NativePlaneError(
                f"native drain does not own codec {stream_codec!r}")
        self._lib = lib
        self.stream_codec = stream_codec
        self._h = lib.gritio_drain_open(
            path.encode(), CODEC_IDS[stream_codec], block_bytes,
            max_inflight_bytes, int(min_ratio * 1000))
        if not self._h:
            raise NativePlaneError(f"gritio_drain_open failed for {path}")

    def put(self, view, chunk_codec: str) -> None:
        """Enqueue one chunk (uint8 ndarray / buffer). Blocks while the
        in-flight byte budget is full; raises on a latched drain error
        (the mirror then self-abandons, exactly like a dead tee)."""
        ptr, nbytes, _keep = native._as_pointer(view)
        while True:
            rc = self._lib.gritio_drain_put(
                self._h, ptr, nbytes, CODEC_IDS.get(chunk_codec, 0), 1000)
            if rc == 0:
                IO_NATIVE_BYTES.inc(nbytes, plane="drain")
                return
            if rc == 1:  # budget full, drain healthy — wait on. A real
                # -ETIMEDOUT (a failing filesystem's latched errno) stays
                # negative and raises below: a dead mirror must abandon,
                # never busy-spin the dump.
                continue
            _raise_errno(rc, "native drain put")

    def flush(self, timeout_s: float) -> bool:
        """Wait for the queue to drain; False on timeout (the caller
        abandons — the mirror contract is never hang the dump)."""
        rc = self._lib.gritio_drain_flush(self._h, int(timeout_s * 1000))
        if rc == -110:
            return False
        if rc != 0:
            _raise_errno(rc, "native drain")
        return True

    def records(self) -> list[tuple[str, int, int, int, int, int]]:
        """Accumulated block records as ``(codec, raw_off, raw_n,
        comp_off, comp_n, crc_raw)`` tuples — sidecar order."""
        n = int(self._lib.gritio_drain_records(self._h, None, 0))
        if n == 0:
            return []
        buf = (BlockRecStruct * n)()
        got = int(self._lib.gritio_drain_records(self._h, buf, n))
        out = []
        for i in range(min(n, got)):
            r = buf[i]
            out.append((CODEC_NAMES.get(r.codec, "?"), r.raw_off, r.raw_n,
                        r.comp_off, r.comp_n, r.crc_raw))
        return out

    def stats(self) -> tuple[int, int]:
        raw = ctypes.c_int64(0)
        comp = ctypes.c_int64(0)
        self._lib.gritio_drain_stats(self._h, ctypes.byref(raw),
                                     ctypes.byref(comp))
        return raw.value, comp.value

    def close(self, fsync: bool = False) -> None:
        if not self._h:
            return
        h, self._h = self._h, None
        rc = self._lib.gritio_drain_close(h, 1 if fsync else 0)
        if rc != 0:
            _raise_errno(rc, "native drain close")

    def abandon(self) -> None:
        if not self._h:
            return
        h, self._h = self._h, None
        self._lib.gritio_drain_abandon(h)


def place_container(path: str, records, offset: int, nbytes: int, *,
                    verify_algo: str | None = None):
    """Decode raw range ``[offset, offset+nbytes)`` out of a container.

    ``records`` is the covering block set in raw-offset order — the
    ``grit_tpu.codec.BlockRecord`` objects the (Python-parsed) sidecar
    index yields. Returns ``(uint8 ndarray, crc_or_None)`` where the crc
    is of the returned range per ``verify_algo`` ("crc32" | "crc32c").
    Raises :class:`NativeDataError` on corrupt data (terminal — the same
    bytes fail the Python plane too) and :class:`NativePlaneError` on
    mechanical failures (the caller degrades loudly)."""
    import numpy as np  # noqa: PLC0415 — keep module import-light

    lib = _load()
    if lib is None:
        raise NativePlaneError("native file plane not available")
    recs = (BlockRecStruct * len(records))()
    for i, r in enumerate(records):
        cid = CODEC_IDS.get(r.codec)
        if cid is None:
            raise NativePlaneError(
                f"native place does not own codec {r.codec!r}")
        recs[i].codec = cid
        recs[i].crc_raw = r.crc_raw
        recs[i].raw_off = r.raw_off
        recs[i].raw_n = r.raw_n
        recs[i].comp_off = r.comp_off
        recs[i].comp_n = r.comp_n
    out = np.empty(nbytes, dtype=np.uint8)
    want = {"crc32": 1, "crc32c": 2}.get(verify_algo or "", 0)
    c32 = ctypes.c_uint32(0)
    c32c = ctypes.c_uint32(0)
    engine = ctypes.c_int32(0)
    rc = lib.gritio_place_container(
        path.encode(), recs, len(records), offset, nbytes,
        ctypes.c_void_p(out.ctypes.data), _depth(), _allow_uring(), want,
        ctypes.byref(c32), ctypes.byref(c32c), ctypes.byref(engine))
    if rc != 0:
        _raise_errno(rc, f"native place {path}@{offset}")
    IO_NATIVE_BYTES.inc(nbytes, plane="place")
    if engine.value:
        IO_READ_BATCHES.inc(
            engine="io_uring" if engine.value == 1 else "preadv")
    crc = {1: c32.value, 2: c32c.value}.get(want)
    return out, crc


def sha256_hex(view) -> str | None:
    """SHA-256 hex digest of a contiguous buffer through the system
    libcrypto on a C worker thread (the delta-match identity of
    write_snapshot's hashed bases), or None when the plane/libcrypto is
    unavailable — callers keep hashlib. Byte-for-byte the same digest
    either way; only where the CPU burns changes."""
    lib = _load()
    if lib is None or not lib.gritio_sha256_available():
        return None
    ptr, nbytes, _keep = native._as_pointer(view)
    out = ctypes.create_string_buffer(65)
    if lib.gritio_sha256_hex(ptr, nbytes, out) != 0:
        return None
    return out.value.decode()


def read_batched(path: str, offset: int, dst, *,
                 verify_algo: str | None = None,
                 segment_bytes: int = 32 * 1024 * 1024) -> int | None:
    """Fill the writable uint8 ndarray ``dst`` from ``path[offset:]``
    via queue-depth segment reads; returns the CRC of the bytes per
    ``verify_algo`` (None → no checksum pass). Short reads raise
    :class:`NativeDataError` — never silent zeros."""
    import numpy as np  # noqa: PLC0415

    lib = _load()
    if lib is None:
        raise NativePlaneError("native file plane not available")
    if not (isinstance(dst, np.ndarray) and dst.dtype == np.uint8
            and dst.flags.c_contiguous and dst.flags.writeable):
        raise ValueError("read_batched requires a writable uint8 array")
    want = {"crc32": 1, "crc32c": 2}.get(verify_algo or "", 0)
    c32 = ctypes.c_uint32(0)
    c32c = ctypes.c_uint32(0)
    engine = ctypes.c_int32(0)
    n = lib.gritio_read_batched(
        path.encode(), offset, ctypes.c_void_p(dst.ctypes.data),
        dst.nbytes, segment_bytes, _depth(), _allow_uring(), want,
        ctypes.byref(c32), ctypes.byref(c32c), ctypes.byref(engine))
    if n < 0:
        _raise_errno(int(n), f"native read {path}@{offset}")
    if n != dst.nbytes:
        raise NativeDataError(
            f"native read short: {n} of {dst.nbytes} bytes")
    IO_NATIVE_BYTES.inc(dst.nbytes, plane="read")
    if engine.value:
        IO_READ_BATCHES.inc(
            engine="io_uring" if engine.value == 1 else "preadv")
    return {1: c32.value, 2: c32c.value}.get(want)
