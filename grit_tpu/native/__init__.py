"""ctypes bindings for the native IO library (``native/gritio``).

The native pieces mirror where the reference leans on native code: its
bulk-data and device paths are C/C++ binaries (CRIU, cuda-checkpoint)
orchestrated from managed code (SURVEY §2.3). Here the split is the same —
Python orchestrates; `libgritio.so` moves bytes (O_DIRECT double-buffered
writes, hardware CRC32C).

Everything degrades gracefully: if the library isn't built (or
``GRIT_TPU_NATIVE=0``), pure-Python fallbacks are used.
"""

from __future__ import annotations

import ctypes
import os

_LIB = None
_TRIED = False


def _lib_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "native", "build", "libgritio.so")


def load() -> ctypes.CDLL | None:
    """Load (once) and return the native library, or None."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    from grit_tpu.api import config  # noqa: PLC0415 — keep module import-light

    if not config.TPU_NATIVE.get():
        return None
    path = _lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.gritio_writer_open.restype = ctypes.c_void_p
    lib.gritio_writer_open.argtypes = [ctypes.c_char_p]
    lib.gritio_writer_append.restype = ctypes.c_int64
    lib.gritio_writer_append.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.gritio_writer_close.restype = ctypes.c_int
    lib.gritio_writer_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.gritio_read_file.restype = ctypes.c_int64
    lib.gritio_read_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.gritio_copy_file.restype = ctypes.c_int64
    lib.gritio_copy_file.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.gritio_crc32c.restype = ctypes.c_uint32
    lib.gritio_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32]
    lib.gritio_has_hw_crc.restype = ctypes.c_int
    _LIB = lib
    return _LIB


def available() -> bool:
    return load() is not None


class NativeWriter:
    """Streaming file writer over the O_DIRECT double-buffered native path."""

    def __init__(self, path: str) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native gritio library not available")
        self._lib = lib
        self._h = lib.gritio_writer_open(path.encode())
        if not self._h:
            raise OSError(f"gritio_writer_open failed for {path}")
        self.offset = 0

    def append(self, data) -> tuple[int, int]:
        """Write ``data`` (buffer protocol); returns (offset, crc32c)."""
        ptr, nbytes, _keep = _as_pointer(data)
        crc = ctypes.c_uint32(0)
        n = self._lib.gritio_writer_append(
            self._h, ptr, nbytes, ctypes.byref(crc)
        )
        if n < 0:
            raise OSError(f"gritio append failed: errno {-n}")
        off = self.offset
        self.offset += nbytes
        return off, crc.value

    def close(self, fsync: bool = True) -> None:
        if self._h:
            err = self._lib.gritio_writer_close(self._h, 1 if fsync else 0)
            self._h = None
            if err < 0:
                raise OSError(f"gritio close failed: errno {-err}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_range(path: str, offset: int, nbytes: int) -> tuple[bytes, int]:
    """Read a byte range; returns (data, crc32c)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gritio library not available")
    buf = ctypes.create_string_buffer(nbytes)
    crc = ctypes.c_uint32(0)
    n = lib.gritio_read_file(path.encode(), offset, buf, nbytes, ctypes.byref(crc))
    if n < 0:
        raise OSError(f"gritio read failed: errno {-n}")
    return buf.raw[:n], crc.value


def read_into_parallel(path: str, offset: int, dst, *, workers: int = 6,
                       block: int = 32 * 1024 * 1024) -> None:
    """Fill ``dst`` from ``path[offset:offset+dst.nbytes]`` using several
    concurrent range reads.

    The virtio/cloud disks this runs on are queue-depth machines: one
    sequential read stream measured 0.13 GB/s where four concurrent
    streams measured 2.2 GB/s (17×). Each worker preads directly into
    its slice of ``dst`` (the C call releases the GIL), so this costs no
    extra copies. No checksum — callers verify the assembled buffer in
    one :func:`crc32c` pass.
    """
    import numpy as np
    from concurrent.futures import ThreadPoolExecutor

    if not (isinstance(dst, np.ndarray) and dst.dtype == np.uint8
            and dst.flags.c_contiguous and dst.flags.writeable):
        raise ValueError("read_into_parallel requires a writable uint8 array")
    n = dst.nbytes
    if n <= block or workers <= 1:
        read_into(path, offset, dst)
        return
    ranges = [(off, min(off + block, n)) for off in range(0, n, block)]
    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = [
            pool.submit(read_into, path, offset + a, dst[a:b])
            for a, b in ranges
        ]
        for f in futures:
            f.result()


def read_into(path: str, offset: int, dst) -> int:
    """Read ``dst.nbytes`` bytes at ``offset`` directly into the writable
    contiguous ndarray ``dst`` (single native pass: pread + CRC folded, no
    intermediate ``bytes`` allocation — the restore hot path). Returns the
    crc32c of the bytes read."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gritio library not available")
    import numpy as np

    if not (isinstance(dst, np.ndarray) and dst.flags.c_contiguous
            and dst.flags.writeable):
        raise ValueError("read_into requires a writable C-contiguous ndarray")
    crc = ctypes.c_uint32(0)
    n = lib.gritio_read_file(
        path.encode(), offset, ctypes.c_void_p(dst.ctypes.data), dst.nbytes,
        ctypes.byref(crc),
    )
    if n < 0:
        raise OSError(f"gritio read failed: errno {-n}")
    if n != dst.nbytes:
        raise OSError(f"gritio short read: {n} of {dst.nbytes} bytes")
    return crc.value


def _as_pointer(data) -> tuple[ctypes.c_void_p, int, object]:
    """Zero-copy (void*, nbytes, keepalive) view of a contiguous buffer.

    The keepalive object must stay referenced for the duration of the C
    call. ndarrays are addressed directly (covers dtypes like bfloat16
    that the buffer protocol rejects)."""
    import numpy as np

    if isinstance(data, np.ndarray):
        arr = np.ascontiguousarray(data)
        return ctypes.c_void_p(arr.ctypes.data), arr.nbytes, arr
    arr = np.frombuffer(memoryview(data).cast("B"), dtype=np.uint8)
    return ctypes.c_void_p(arr.ctypes.data), arr.nbytes, (arr, data)


def crc32c(data, seed: int = 0) -> int:
    lib = load()
    if lib is None:
        return _crc32c_sw(data, seed)
    ptr, nbytes, _keep = _as_pointer(data)
    return lib.gritio_crc32c(ptr, nbytes, seed)


def copy_file(src: str, dst: str, fsync: bool = True) -> tuple[int, int]:
    """Native streaming copy; returns (bytes, crc32c)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gritio library not available")
    crc = ctypes.c_uint32(0)
    n = lib.gritio_copy_file(
        src.encode(), dst.encode(), 1 if fsync else 0, ctypes.byref(crc)
    )
    if n < 0:
        raise OSError(f"gritio copy failed: errno {-n}")
    return n, crc.value


def copy_file_fast(src: str, dst: str, fsync: bool = True,
                   *, window: int = 256 * 1024 * 1024,
                   read_workers: int = 4,
                   with_crc: bool = True) -> tuple[int, int]:
    """Large-file copy built for queue-depth disks: concurrent range
    reads fill a window (QD1 0.13 GB/s → QD4 2.2 GB/s measured on the
    bench host's virtio disk), the O_DIRECT writer drains it, and the
    stream CRC chains window to window. Returns (bytes, crc32c) with the
    same contract as :func:`copy_file`; ``with_crc=False`` skips the
    checksum pass (returns crc 0) — callers that don't verify shouldn't
    pay a full extra sweep over every byte on the blackout host."""
    import numpy as np
    from concurrent.futures import ThreadPoolExecutor

    nbytes = os.path.getsize(src)
    bufs = [np.empty(min(window, max(nbytes, 1)), dtype=np.uint8)
            for _ in range(2)]
    crc = 0
    w = NativeWriter(dst)
    try:
        with ThreadPoolExecutor(max_workers=1) as ahead:
            # Double-buffered: window k+1's parallel read overlaps the
            # CRC+O_DIRECT write of window k (both sides release the GIL).
            def start_read(off):
                n = min(window, nbytes - off)
                view = bufs[(off // window) % 2][:n]
                read_into_parallel(src, off, view, workers=read_workers)
                return view

            pending = ahead.submit(start_read, 0) if nbytes else None
            off = 0
            while off < nbytes:
                view = pending.result()
                nxt = off + view.nbytes
                pending = (ahead.submit(start_read, nxt)
                           if nxt < nbytes else None)
                if with_crc:
                    crc = crc32c(view, crc)
                w.append(view)
                off = nxt
    finally:
        w.close(fsync=fsync)
    return nbytes, crc


_SW_TABLE: list[int] | None = None


def _crc32c_sw(data, seed: int = 0) -> int:
    """Pure-Python CRC32C (Castagnoli) — fallback for verify paths when the
    native library is absent. Slow; only used on small metadata."""
    global _SW_TABLE
    if _SW_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
            table.append(c)
        _SW_TABLE = table
    crc = seed ^ 0xFFFFFFFF
    for b in memoryview(data).cast("B"):
        crc = (crc >> 8) ^ _SW_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF
