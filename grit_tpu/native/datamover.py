"""Native-backed tree copy — drop-in engine for the agent data mover.

Same contract as :func:`grit_tpu.agent.copy.transfer_data` (walk the tree,
copy every file, preserve modes, raise listing all failures) but each file
streams through the O_DIRECT writer with hardware CRC32C. The reference's
equivalent is a 10-goroutine buffered copy (copy.go:17-64) that tops out at
page-cache speed; page-cache bypass matters here because checkpoint images
are written once and immediately shipped — caching them evicts the very
pages the still-running workload needs.
"""

from __future__ import annotations

import os
import time

from grit_tpu import native


def available() -> bool:
    return native.available()


_VERIFY_CHUNK = 64 << 20


def _file_crc(path: str, nbytes: int) -> int:
    """Chained CRC32C of a file, read in bounded chunks."""
    crc = 0
    off = 0
    while off < nbytes:
        data, _ = native.read_range(path, off, min(_VERIFY_CHUNK, nbytes - off))
        if not data:
            break
        crc = native.crc32c(data, crc)
        off += len(data)
    return crc


def transfer_data(src_dir: str, dst_dir: str, workers: int = 10,
                  verify: bool = False):
    """Copy ``src_dir`` → ``dst_dir`` via the native streaming path.

    ``workers`` is accepted for interface parity; files are processed
    one at a time, but large files use a handful of concurrent RANGE
    reads internally (``copy_file_fast``) — cloud disks serve parallel
    reads an order of magnitude faster than one stream, and those
    reader threads are GIL-free pread waits, not CPU the quiescing
    runtime would miss.

    ``verify=True`` re-reads each destination file and compares its CRC32C
    against the source-stream CRC computed during the copy (end-to-end
    check through the page cache and disk, analogous to the Python
    engine's sha256 pass).
    """
    from grit_tpu.agent.copy import TransferStats, _iter_files

    if not os.path.isdir(src_dir):
        raise FileNotFoundError(f"source dir {src_dir} does not exist")
    os.makedirs(dst_dir, exist_ok=True)
    stats = TransferStats()
    start = time.monotonic()
    for src, rel in _iter_files(src_dir):
        dst = os.path.join(dst_dir, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        try:
            if os.path.getsize(src) >= (64 << 20):
                # Queue-depth copy: concurrent range reads + O_DIRECT
                # write. One sequential stream reads this host's disk at
                # 0.13 GB/s; four concurrent streams at 2.2 — the
                # difference between a 33 s and a ~4 s stage leg for the
                # 2.39 GB flagship snapshot. The CRC pass (a second full
                # sweep) is only paid when the caller verifies.
                n, crc = native.copy_file_fast(src, dst, with_crc=verify)
            else:
                n, crc = native.copy_file(src, dst)
            if verify and _file_crc(dst, n) != crc:
                stats.errors.append(f"{dst}: checksum mismatch")
                continue
            stats.bytes += n
            stats.files += 1
        except OSError as exc:
            stats.errors.append(f"{src}: {exc}")
    stats.seconds = time.monotonic() - start
    if stats.errors:
        raise RuntimeError("transfer failed: " + "; ".join(stats.errors))
    return stats
