"""ctypes bindings for the native wire data plane (``native/gritio/
gritio_wire.cc``).

The split mirrors the rest of the native lane: Python stays the control
plane (endpoint rendezvous, frame headers, codec decisions, journal and
commit handshake, fault points) while payload bytes move natively —
ring-buffer send workers with the frame CRC fused into the staging copy,
``sendfile(2)`` for prestaged/tree files, and receive-side frame decode
→ CRC verify → ``pwrite`` straight into the stage file, with only
``(rel, offset, length, crc-ok)`` completions surfacing into Python.

Everything degrades loudly: when ``libgritio.so`` is absent (or
``GRIT_WIRE_NATIVE=0`` / ``GRIT_TPU_NATIVE=0``) :func:`enabled` is
False, the caller keeps the pure-Python frame loop, and the degrade is
logged ONCE per process — a silent fallback would masquerade as the
20x-slower plane the rewrite exists to retire.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from dataclasses import dataclass

from grit_tpu import native
from grit_tpu.api import config

log = logging.getLogger(__name__)

#: Ring depth per send worker — matches the Python plane's
#: _WIRE_QUEUE_FRAMES bound (source memory stays bounded either way).
RING_SLOTS = 4

# Completion kinds posted by the native receive session.
EV_DATA = 1         # frame decoded, verified, applied natively
EV_BLOB = 2         # control/codec frame passed through verbatim
EV_CONN_CLOSED = 3  # clean EOF at a frame boundary
EV_CONN_ERROR = 4   # torn frame / socket error / stage-write failure


class WireEventStruct(ctypes.Structure):
    """Mirror of ``WireEventOut`` in gritio_wire.cc."""

    _fields_ = [
        ("kind", ctypes.c_int32),
        ("conn", ctypes.c_int32),
        ("crc_ok", ctypes.c_int32),
        ("is_file", ctypes.c_int32),
        ("off", ctypes.c_int64),
        ("n", ctypes.c_int64),
        ("size", ctypes.c_int64),
        ("blob_len", ctypes.c_int64),
        ("rel", ctypes.c_char * 1024),
        ("err", ctypes.c_char * 256),
    ]


@dataclass
class WireEvent:
    kind: int
    conn: int
    crc_ok: bool
    is_file: bool
    off: int
    n: int
    size: int | None
    rel: str
    err: str
    blob: bytes | None


_WIRE_LIB = None
_WIRE_TRIED = False
_DEGRADE_LOGGED = False


def _load() -> ctypes.CDLL | None:
    """The base gritio CDLL with the wire symbol table attached (once),
    or None when the library or the wire symbols are absent."""
    global _WIRE_LIB, _WIRE_TRIED
    if _WIRE_TRIED:
        return _WIRE_LIB
    _WIRE_TRIED = True
    lib = native.load()
    if lib is None:
        return None
    try:
        lib.gritio_wire_crc32.restype = ctypes.c_uint32
        lib.gritio_wire_crc32.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32]
        lib.gritio_wire_file_crc32.restype = ctypes.c_int64
        lib.gritio_wire_file_crc32.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.gritio_wire_sender_create.restype = ctypes.c_void_p
        lib.gritio_wire_sender_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_double]
        lib.gritio_wire_sender_stage.restype = ctypes.c_int
        lib.gritio_wire_sender_stage.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.gritio_wire_sender_commit.restype = ctypes.c_int
        lib.gritio_wire_sender_commit.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int32]
        lib.gritio_wire_sender_send.restype = ctypes.c_int
        lib.gritio_wire_sender_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64]
        lib.gritio_wire_sender_send_file.restype = ctypes.c_int
        lib.gritio_wire_sender_send_file.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64]
        lib.gritio_wire_sender_flush.restype = ctypes.c_int
        lib.gritio_wire_sender_flush.argtypes = [
            ctypes.c_void_p, ctypes.c_int]
        lib.gritio_wire_sender_error.restype = ctypes.c_int
        lib.gritio_wire_sender_error.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_sender_sent_bytes.restype = ctypes.c_int64
        lib.gritio_wire_sender_sent_bytes.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_sender_send_seconds.restype = ctypes.c_double
        lib.gritio_wire_sender_send_seconds.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_sender_stall_seconds.restype = ctypes.c_double
        lib.gritio_wire_sender_stall_seconds.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_sender_abort.restype = None
        lib.gritio_wire_sender_abort.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_sender_destroy.restype = None
        lib.gritio_wire_sender_destroy.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_recv_create.restype = ctypes.c_void_p
        lib.gritio_wire_recv_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p]
        lib.gritio_wire_recv_add_conn.restype = ctypes.c_int
        lib.gritio_wire_recv_add_conn.argtypes = [
            ctypes.c_void_p, ctypes.c_int]
        lib.gritio_wire_recv_next.restype = ctypes.c_int
        lib.gritio_wire_recv_next.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(WireEventStruct)]
        lib.gritio_wire_recv_take_blob.restype = ctypes.c_int64
        lib.gritio_wire_recv_take_blob.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        lib.gritio_wire_recv_close_rel.restype = ctypes.c_int
        lib.gritio_wire_recv_close_rel.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.gritio_wire_recv_bytes.restype = ctypes.c_int64
        lib.gritio_wire_recv_bytes.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_recv_abort.restype = None
        lib.gritio_wire_recv_abort.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_recv_shutdown.restype = None
        lib.gritio_wire_recv_shutdown.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_recv_quiesce.restype = None
        lib.gritio_wire_recv_quiesce.argtypes = [ctypes.c_void_p]
        lib.gritio_wire_recv_destroy.restype = None
        lib.gritio_wire_recv_destroy.argtypes = [ctypes.c_void_p]
    except AttributeError:
        # A stale pre-wire libgritio.so: same loud degrade as absence.
        return None
    _WIRE_LIB = lib
    return _WIRE_LIB


def available() -> bool:
    """Whether the native wire symbols are loadable (env-independent)."""
    return _load() is not None


def enabled() -> bool:
    """Whether the native plane should engage: GRIT_WIRE_NATIVE on AND
    the library present. A requested-but-unavailable plane logs the
    degrade once per process — loud, never silent."""
    global _DEGRADE_LOGGED
    if not config.WIRE_NATIVE.get():
        return False
    if _load() is None:
        if not _DEGRADE_LOGGED:
            _DEGRADE_LOGGED = True
            log.warning(
                "GRIT_WIRE_NATIVE is on but the native wire plane is "
                "unavailable (libgritio.so missing, stale, or "
                "GRIT_TPU_NATIVE=0) — degrading to the pure-Python "
                "frame loop (expect wire python-share to rise)")
        return False
    return True


def crc32(data, seed: int = 0) -> int:
    """zlib-compatible CRC32 via the native slice-by-8 path."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native wire plane not available")
    ptr, nbytes, _keep = native._as_pointer(data)
    return lib.gritio_wire_crc32(ptr, nbytes, seed)


def file_crc32(path: str, offset: int, nbytes: int) -> int:
    """zlib CRC32 of ``path[offset:offset+nbytes]`` — computed by a
    native pread loop, so the bytes never surface in Python. Raises
    OSError on IO failure or a short file."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native wire plane not available")
    crc = ctypes.c_uint32(0)
    n = lib.gritio_wire_file_crc32(path.encode(), offset, nbytes,
                                   ctypes.byref(crc))
    if n < 0:
        raise OSError(f"wire file crc failed for {path}: errno {-n}")
    if n != nbytes:
        raise OSError(
            f"{path} shrank mid-crc ({n}/{nbytes} bytes at {offset})")
    return crc.value


class SendWorker:
    """One native ring-buffer send worker bound to one (blocking) stream
    socket. The ring bounds in-flight frames exactly like the Python
    plane's per-stream queue; a full ring blocks the producer.

    The producer calls (stage/commit/send/send_file/flush) are owned by
    the session's caller and always precede ``WireSender.close()``'s
    destroy, so they stay lock-free — holding a lock across a
    ring-full block would stall the stats readers for the backpressure
    duration. The short counter reads CAN outlive close's bounded pacer
    join (a straggling pacer sweep), so they and :meth:`destroy` share
    one lock under which destroy nulls the handle: a read racing — or
    following — the destroy returns 0 instead of passing a freed
    ``Sender*`` into C."""

    def __init__(self, sock, slot_bytes: int,
                 timeout: float = 120.0) -> None:
        lib = _load()
        if lib is None:
            raise OSError("native wire plane not available")
        self._lib = lib
        self._lock = threading.Lock()
        # The native worker uses raw send(2)/sendfile(2): a Python-level
        # socket timeout would flip the fd non-blocking under it, so the
        # handoff pins blocking mode (the worker keeps its own progress
        # deadline; Python re-arms the timeout for the commit-ack read
        # after flush, when the ring is empty).
        sock.setblocking(True)
        self._h = lib.gritio_wire_sender_create(
            sock.fileno(), RING_SLOTS, slot_bytes, timeout)
        if not self._h:
            raise OSError("gritio_wire_sender_create failed")
        self.slot_bytes = slot_bytes

    def _check(self, rc: int, what: str) -> None:
        if rc < 0:
            raise OSError(f"native wire {what} failed: errno {-rc}")

    def _handle(self):
        if not self._h:
            raise OSError("native wire sender already destroyed")
        return self._h

    def stage(self, payload) -> tuple[int, int]:
        """Copy ``payload`` into a ring slot with the frame CRC fused
        into the copy; returns (slot, crc). Blocks while the ring is
        full (bounded backpressure)."""
        ptr, nbytes, _keep = native._as_pointer(payload)
        crc = ctypes.c_uint32(0)
        slot = self._lib.gritio_wire_sender_stage(
            self._handle(), ptr, nbytes, ctypes.byref(crc))
        self._check(slot, "stage")
        return slot, crc.value

    def commit(self, slot: int, header: bytes) -> None:
        self._check(
            self._lib.gritio_wire_sender_commit(
                self._handle(), slot, header, len(header)),
            "commit")

    def send(self, header: bytes, payload=b"") -> None:
        ptr, nbytes, _keep = native._as_pointer(payload) \
            if len(payload) else (None, 0, None)
        self._check(
            self._lib.gritio_wire_sender_send(
                self._handle(), header, len(header), ptr, nbytes),
            "send")

    def send_file(self, header: bytes, path: str, offset: int,
                  nbytes: int) -> None:
        """Queue a file-segment frame; the worker ships the payload via
        sendfile(2) — the bytes never enter userspace."""
        self._check(
            self._lib.gritio_wire_sender_send_file(
                self._handle(), header, len(header), path.encode(),
                offset, nbytes),
            "send_file")

    def flush(self, timeout: float) -> None:
        self._check(
            self._lib.gritio_wire_sender_flush(
                self._handle(), int(timeout * 1000)),
            "flush")

    def error(self) -> int:
        with self._lock:
            return self._lib.gritio_wire_sender_error(self._h) \
                if self._h else 0

    def sent_bytes(self) -> int:
        with self._lock:
            return self._lib.gritio_wire_sender_sent_bytes(self._h) \
                if self._h else 0

    def send_seconds(self) -> float:
        with self._lock:
            return self._lib.gritio_wire_sender_send_seconds(self._h) \
                if self._h else 0.0

    def stall_seconds(self) -> float:
        with self._lock:
            return self._lib.gritio_wire_sender_stall_seconds(self._h) \
                if self._h else 0.0

    def abort(self) -> None:
        """Abandon queued frames and sever the socket: an error-path
        teardown must not park :meth:`destroy`'s join behind up to a
        ring of unsent segments pushed at a wedged peer (up to
        ``timeout_s`` EACH, unbounded against a trickling one). The
        native-startup fallback must NOT call this — its sockets are
        handed back to the Python frame loop."""
        with self._lock:
            if self._h:
                self._lib.gritio_wire_sender_abort(self._h)

    def destroy(self) -> None:
        with self._lock:
            if self._h:
                self._lib.gritio_wire_sender_destroy(self._h)
                self._h = None


class RecvSession:
    """Native receive session: per-connection reader threads decode,
    verify and apply raw frames, posting completions a single Python
    pump thread consumes via :meth:`next`.

    Lifetime contract: the pump thread owns BOTH :meth:`next` and
    :meth:`destroy` (its drain loop ends after the receiver's
    close/_fail set the stop flag, then its finally destroys), so those
    two never race each other and stay lock-free — holding a lock
    across ``next``'s blocked C-side wait would starve every other
    caller for the duration of each empty-queue timeout. What CAN race
    destroy are the short calls from other threads (close/_fail's
    shutdown/abort/quiesce, the accept loop's add_conn, bookkeeping's
    close_rel/recv_bytes): each takes one lock that :meth:`destroy`
    nulls the handle under, so a call racing — or following — the
    destroy degrades to a no-op instead of passing a freed ``Recv*``
    into C. None of the locked calls blocks on the pump consuming
    (``closing`` releases the C-side completion bound before reader
    joins), so no lock hold is unbounded."""

    def __init__(self, dst_dir: str, sidecar_suffix: str) -> None:
        lib = _load()
        if lib is None:
            raise OSError("native wire plane not available")
        self._lib = lib
        self._lock = threading.Lock()
        os.makedirs(dst_dir, exist_ok=True)
        self._h = lib.gritio_wire_recv_create(
            dst_dir.encode(), sidecar_suffix.encode())
        if not self._h:
            raise OSError("gritio_wire_recv_create failed")

    def add_conn(self, sock) -> int:
        sock.setblocking(True)
        with self._lock:
            if not self._h:
                raise OSError(
                    "native wire receive session already closed")
            conn = self._lib.gritio_wire_recv_add_conn(self._h,
                                                       sock.fileno())
        if conn < 0:
            raise OSError(f"wire recv add_conn failed: errno {-conn}")
        return conn

    def next(self, timeout_ms: int = 200) -> WireEvent | None:
        """Pop one completion (None on timeout). Single consumer by
        contract — the blob parked by a passthrough event is fetched
        before the following call. Pump-thread-only, like
        :meth:`destroy`: deliberately lock-free (see the class
        docstring)."""
        if not self._h:
            return None
        ev = WireEventStruct()
        rc = self._lib.gritio_wire_recv_next(self._h, timeout_ms,
                                             ctypes.byref(ev))
        if rc == 0:
            return None
        blob = None
        if ev.blob_len > 0:
            buf = ctypes.create_string_buffer(ev.blob_len)
            got = self._lib.gritio_wire_recv_take_blob(
                self._h, buf, ev.blob_len)
            blob = buf.raw[:got] if got >= 0 else b""
        return WireEvent(
            kind=ev.kind, conn=ev.conn, crc_ok=bool(ev.crc_ok),
            is_file=bool(ev.is_file), off=ev.off, n=ev.n,
            size=ev.size if ev.size >= 0 else None,
            rel=ev.rel.decode("utf-8", "replace"),
            err=ev.err.decode("utf-8", "replace"), blob=blob)

    def close_rel(self, rel: str) -> None:
        with self._lock:
            if self._h:
                self._lib.gritio_wire_recv_close_rel(self._h,
                                                     rel.encode())

    def recv_bytes(self) -> int:
        with self._lock:
            return self._lib.gritio_wire_recv_bytes(self._h) \
                if self._h else 0

    def abort(self) -> None:
        """Poison: no further stage writes from frames still in flight."""
        with self._lock:
            if self._h:
                self._lib.gritio_wire_recv_abort(self._h)

    def shutdown(self) -> None:
        """Sever every connection; reader threads exit via completions."""
        with self._lock:
            if self._h:
                self._lib.gritio_wire_recv_shutdown(self._h)

    def quiesce(self) -> None:
        """Shutdown + JOIN the reader threads: on return, no stage
        write is in flight or can ever start — the guarantee the PVC
        fallback needs before it restages the directory."""
        with self._lock:
            if self._h:
                self._lib.gritio_wire_recv_quiesce(self._h)

    def destroy(self) -> None:
        with self._lock:
            if self._h:
                self._lib.gritio_wire_recv_destroy(self._h)
                self._h = None
