"""Parameter/activation sharding rules — keypath-pattern → PartitionSpec.

Partitioning is expressed as ordered ``(regex, PartitionSpec)`` rules
matched against pytree keypaths (e.g. ``"layers/3/attn/wq"``), the idiomatic
JAX alternative to hand-placing every tensor: models declare one rule table,
``shard_tree`` applies it under any mesh, and the same table drives both
fresh init and snapshot restore (sharding descriptors recorded by
:mod:`grit_tpu.device.snapshot` are re-realized against the *current* mesh).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


@dataclass
class ShardingRules:
    """Ordered first-match rule table."""

    rules: list[tuple[str, PartitionSpec]] = field(default_factory=list)
    default: PartitionSpec = PartitionSpec()

    def spec_for(self, path_str: str) -> PartitionSpec:
        for pattern, spec in self.rules:
            if re.search(pattern, path_str):
                return spec
        return self.default

    def tree_specs(self, tree) -> object:
        """Pytree of PartitionSpecs matching ``tree``'s structure."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = [self.spec_for(_path_str(p)) for p, _ in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)

    def tree_shardings(self, tree, mesh: Mesh) -> object:
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.tree_specs(tree),
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )


def spec_for(rules: ShardingRules, tree) -> object:
    return rules.tree_specs(tree)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_tree(tree, mesh: Mesh, rules: ShardingRules):
    """Place every leaf of ``tree`` on ``mesh`` per the rule table."""
    shardings = rules.tree_shardings(tree, mesh)
    return jax.tree.map(jax.device_put, tree, shardings)
