"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

TPU-native formulation (scaling-book "pipelining" recipe, not a port of
any GPU framework): stage parameters are stacked on a leading axis and
sharded over the ``pipe`` mesh axis; the whole schedule is ONE
``shard_map``-ed ``lax.scan`` in which every device runs its stage each
tick and hands its activation to the successor with a single
``lax.ppermute`` ring hop per tick — the collective rides nearest-neighbor
ICI. No host control flow, no per-stage dispatch: the compiler sees a
static loop of ``num_microbatches + num_stages - 1`` ticks.

Differentiable end-to-end: ``ppermute``'s transpose is the reverse
permute, so ``jax.grad`` through :func:`pipeline_apply` yields exact
gradients (asserted against the serial reference in
``tests/test_pipeline.py``), making it usable directly inside a training
step (the driver's pp axis — ``__graft_entry__.dryrun_multichip``).

Reference has no analogue (single-GPU scope; SURVEY §2.4's explicit
absence statement): this module is part of the "distributed is
first-class" surface of the TPU build.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grit_tpu.parallel.compat import pvary, shard_map

PIPE_AXIS = "pipe"

# StageFn: (stage_params, activation) -> activation. Applied by every
# pipeline stage to its resident microbatch each tick.
StageFn = Callable[[Any, jax.Array], jax.Array]


def stack_stage_params(per_stage: list[Any]) -> Any:
    """Stack a list of per-stage param pytrees on a new leading axis —
    the axis :func:`pipeline_apply` shards over ``pipe``."""

    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def _spmd_pipeline(
    stage_fn: StageFn,
    n_stages: int,
    params_local: Any,   # this stage's params (leading axis stripped)
    x_mb: jax.Array,     # (M, ...) microbatches, replicated across pipe
) -> jax.Array:
    """Per-device body (inside shard_map over the pipe axis)."""

    stage = lax.axis_index(PIPE_AXIS)
    n_mb = x_mb.shape[0]
    ticks = n_mb + n_stages - 1

    def tick(carry, t):
        held = carry  # activation received from predecessor last tick
        # Stage 0 injects microbatch t (while t < n_mb); other stages
        # compute on what arrived. During bubble ticks the math runs on
        # placeholder values and is masked out at collection.
        inject = x_mb[jnp.minimum(t, n_mb - 1)]
        act_in = jnp.where(stage == 0, inject, held)
        act_out = stage_fn(params_local, act_in)
        # Last stage emits microbatch (t - n_stages + 1) at tick t.
        emit = act_out
        # Ring hop: successor receives our activation next tick.
        nxt = lax.ppermute(
            act_out, PIPE_AXIS,
            [(i, (i + 1) % n_stages) for i in range(n_stages)],
        )
        return nxt, emit

    # Initial carry must be marked pipe-varying (the loop makes it so via
    # ppermute; newer shard_map tracks varying manual axes explicitly).
    init = pvary(jnp.zeros_like(x_mb[0]), (PIPE_AXIS,))
    _, emitted = lax.scan(tick, init, jnp.arange(ticks))

    # emitted[t] on the LAST stage is microbatch t - (n_stages - 1);
    # select the valid window. Other stages' emissions are discarded by
    # the caller's out_specs (last-stage rows only).
    y = lax.dynamic_slice_in_dim(emitted, n_stages - 1, n_mb, axis=0)
    return y


def pipeline_apply(
    stage_fn: StageFn,
    stacked_params: Any,
    x_mb: jax.Array,
    *,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
) -> jax.Array:
    """Run microbatches through the stage pipeline.

    Args:
      stage_fn: one pipeline stage, ``(params, act) -> act`` with
        activation shape preserved (stages must agree on the interface
        shape, the usual transformer-block contract).
      stacked_params: pytree whose leaves carry a leading stage axis of
        size ``mesh.shape[axis]`` (see :func:`stack_stage_params`).
      x_mb: ``(num_microbatches, mb, ...)`` input microbatches.
      mesh: mesh containing ``axis``.

    Returns ``(num_microbatches, mb, ...)`` outputs of the final stage.
    """

    n_stages = mesh.shape[axis]
    other_axes = tuple(a for a in mesh.axis_names if a != axis)

    def body(params_stacked_local, x_local):
        # shard_map gives each device a leading stage axis of size 1.
        params_local = jax.tree.map(
            lambda a: jnp.squeeze(a, axis=0), params_stacked_local
        )
        y = _spmd_pipeline(stage_fn, n_stages, params_local, x_local)
        # Only the last stage's output is meaningful; zero the rest so
        # the psum-gather below is exact (out_specs replicates over pipe).
        stage = lax.axis_index(axis)
        y = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
        return lax.psum(y, axis)

    # Only the pipe axis is manual inside the body; other mesh axes (data,
    # expert, ...) stay automatic so stage_fn can carry its own shardings
    # (e.g. an expert-parallel MoE) and XLA partitions them as usual.
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(*([None] * x_mb.ndim))),
        out_specs=P(*([None] * x_mb.ndim)),
        axis_names={axis},
    )(stacked_params, x_mb)


def stage_sharding(mesh: Mesh, axis: str = PIPE_AXIS) -> NamedSharding:
    """Sharding for stacked stage params (leading axis over ``pipe``)."""

    return NamedSharding(mesh, P(axis))


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """Split a global batch ``(B, ...)`` into ``(M, B//M, ...)``."""

    if x.shape[0] % n_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {n_microbatches} microbatches"
        )
    return x.reshape(n_microbatches, x.shape[0] // n_microbatches,
                     *x.shape[1:])


def pipeline_loss(
    stage_fn: StageFn,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x_mb: jax.Array,
    y_mb: jax.Array,
    *,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
) -> jax.Array:
    """Mean loss over microbatches through the pipeline (differentiable —
    use inside ``jax.value_and_grad`` for the training step)."""

    out = pipeline_apply(stage_fn, stacked_params, x_mb, mesh=mesh, axis=axis)
    return jnp.mean(jax.vmap(loss_fn)(out, y_mb))
