"""Slice coordination — consistent multi-host cuts and mesh re-init.

The genuinely new component relative to the reference (SURVEY §2.4, §7-H):
GRIT checkpoints one single-GPU pod, so "consistency" is just CRIU freezing
one process tree. A v5e-16 job is N host processes driving one ICI mesh —
freezing host A mid-`psum` while host B runs on wedges the slice. The
TPU-native contract:

1. **Cut agreement** — all hosts exchange their current step and agree on
   ``max(steps)`` as the cut; everyone runs forward to it (never backward —
   steps already taken can't be unwound) and stops at that boundary.
2. **Quiesce** — each host drains its local dispatch queue
   (:func:`grit_tpu.device.quiesce`). Because every host stopped at the
   same step boundary, no collective is in flight anywhere on the slice.
3. **Snapshot** — each host dumps only the shards it owns;
   :func:`grit_tpu.device.snapshot.write_snapshot`'s barrier/merge
   protocol produces one manifest (process 0 commits).
4. **Restore / mesh re-init** — restarted processes (possibly different
   host ordinals, possibly a different host count) rebuild the mesh from
   the live topology and read shards by *global index*, so host-ordinal
   remapping is automatic; the rendezvous barrier gates the first step so
   no host races ahead while others still load.

Transport is pluggable: :class:`LocalRendezvous` (in-process, for tests and
single-host multi-chip) and :class:`MultihostRendezvous` (backed by JAX's
distributed runtime / ``multihost_utils`` when ``jax.distributed`` is
initialized — the analogue of the reference's implicit reliance on the
Kubernetes control plane for cross-node rendezvous, SURVEY §5).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Protocol

import jax

from grit_tpu.device import quiesce, restore_snapshot, write_snapshot


class Rendezvous(Protocol):
    """Minimal cross-host primitives the coordinator needs.

    ``rank`` is the caller's process index; transports where the runtime
    already knows the caller's identity (jax.distributed) may ignore it.
    """

    def barrier(self, name: str) -> None: ...

    def allgather(self, name: str, value: Any, rank: int) -> list[Any]: ...


class LocalRendezvous:
    """In-process rendezvous for N simulated hosts (threads)."""

    def __init__(self, world_size: int) -> None:
        self.world_size = world_size
        self._barriers: dict[str, threading.Barrier] = {}
        self._values: dict[str, dict[int, Any]] = {}
        self._lock = threading.Lock()
        self._counter: dict[str, int] = {}

    def _barrier_for(self, name: str) -> threading.Barrier:
        with self._lock:
            if name not in self._barriers:
                self._barriers[name] = threading.Barrier(self.world_size)
            return self._barriers[name]

    def barrier(self, name: str) -> None:
        self._barrier_for(name).wait()

    def allgather(self, name: str, value: Any, rank: int) -> list[Any]:
        with self._lock:
            self._values.setdefault(name, {})[rank] = value
        self.barrier(name + "/gathered")
        out = [self._values[name][k] for k in sorted(self._values[name])]
        self.barrier(name + "/read")
        return out


class MultihostRendezvous:
    """Real multi-host rendezvous over JAX's distributed runtime.

    Requires ``jax.distributed.initialize`` to have run (GKE sets the
    coordinator address via the JobSet env). Uses
    ``multihost_utils.sync_global_devices`` (barrier via a trivial psum
    across all hosts' devices) and ``broadcast_one_to_all``/process-allgather
    for value exchange.
    """

    def __init__(self) -> None:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        self._mh = multihost_utils

    def barrier(self, name: str) -> None:
        self._mh.sync_global_devices(name)

    def allgather(self, name: str, value: Any, rank: int) -> list[Any]:
        import numpy as np  # noqa: PLC0415

        del rank  # the distributed runtime knows the caller's identity
        arr = self._mh.process_allgather(np.asarray(value))
        return list(arr)


@dataclass
class SliceCoordinator:
    """Drives consistent-cut snapshots for one host of a slice."""

    rendezvous: Rendezvous
    process_index: int | None = None
    process_count: int | None = None
    _seq: int = field(default=0)

    def _pidx(self) -> int:
        return (
            self.process_index
            if self.process_index is not None
            else jax.process_index()
        )

    def _pcount(self) -> int:
        return (
            self.process_count
            if self.process_count is not None
            else jax.process_count()
        )

    def agree_cut_step(self, current_step: int) -> int:
        """All hosts exchange steps; the cut is the max (run-forward rule)."""
        self._seq += 1
        name = f"grit/cut/{self._seq}"
        steps = self.rendezvous.allgather(name, int(current_step), self._pidx())
        return max(int(s) for s in steps)

    def snapshot(
        self,
        directory: str,
        state: Any,
        *,
        step_fn: Callable[[], Any] | None = None,
        current_step: int | None = None,
        meta: dict | None = None,
        base: str | None = None,
        hashes: bool = False,
        mirror: str | None = None,
    ) -> str:
        """Consistent-cut snapshot across all hosts.

        ``state`` is the pytree to dump, or a **callable returning it** —
        required whenever ``step_fn`` rebinds the state object rather than
        mutating it in place (the Trainer does: its step donates the old
        state's buffers, so a pre-loop reference would dump deleted
        arrays). With ``step_fn``/``current_step`` the host first runs
        forward to the agreed cut step.

        ``base``: delta-dump against an earlier coordinated snapshot (the
        multi-host pre-copy pass); every host delta-checks only the shards
        it owns, so the skip work parallelizes like the dump itself.

        ``mirror``: streaming-upload destination — every host tees its
        own shard file while dumping, and process 0 seals the mirror only
        after ALL hosts dropped their mirror-ok markers (the barrier
        orders marker writes before the commit check).
        """
        if current_step is not None and step_fn is not None:
            cut = self.agree_cut_step(current_step)
            while current_step < cut:
                step_fn()
                current_step += 1
            if meta is None:
                meta = {"step": cut}
        if callable(state):
            state = state()
        quiesce(state)
        self._seq += 1
        name = f"grit/snap/{self._seq}"
        return write_snapshot(
            directory,
            state,
            meta=meta,
            barrier=lambda: self.rendezvous.barrier(name),
            process_index=self._pidx(),
            process_count=self._pcount(),
            base=base,
            hashes=hashes,
            mirror=mirror,
        )

    def restore(self, directory: str, **kwargs) -> Any:
        """Barriered restore: no host starts stepping until all loaded."""
        state = restore_snapshot(directory, **kwargs)
        self._seq += 1
        self.rendezvous.barrier(f"grit/restored/{self._seq}")
        return state
