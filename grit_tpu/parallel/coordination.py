"""Slice coordination — consistent multi-host cuts and mesh re-init.

The genuinely new component relative to the reference (SURVEY §2.4, §7-H):
GRIT checkpoints one single-GPU pod, so "consistency" is just CRIU freezing
one process tree. A v5e-16 job is N host processes driving one ICI mesh —
freezing host A mid-`psum` while host B runs on wedges the slice. The
TPU-native contract:

1. **Cut agreement** — all hosts exchange their current step and agree on
   ``max(steps)`` as the cut; everyone runs forward to it (never backward —
   steps already taken can't be unwound) and stops at that boundary.
2. **Quiesce** — each host drains its local dispatch queue
   (:func:`grit_tpu.device.quiesce`). Because every host stopped at the
   same step boundary, no collective is in flight anywhere on the slice.
3. **Snapshot** — each host dumps only the shards it owns;
   :func:`grit_tpu.device.snapshot.write_snapshot`'s barrier/merge
   protocol produces one manifest (process 0 commits).
4. **Restore / mesh re-init** — restarted processes (possibly different
   host ordinals, possibly a different host count) rebuild the mesh from
   the live topology and read shards by *global index*, so host-ordinal
   remapping is automatic; the rendezvous barrier gates the first step so
   no host races ahead while others still load.

Transport is pluggable: :class:`LocalRendezvous` (in-process, for tests and
single-host multi-chip) and :class:`MultihostRendezvous` (backed by JAX's
distributed runtime / ``multihost_utils`` when ``jax.distributed`` is
initialized — the analogue of the reference's implicit reliance on the
Kubernetes control plane for cross-node rendezvous, SURVEY §5).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Protocol

import jax

from grit_tpu.device import quiesce, restore_snapshot, write_snapshot

log = logging.getLogger(__name__)


class BarrierTimeout(RuntimeError):
    """A bounded rendezvous wait expired: some host of the slice never
    arrived. Deliberately loud — a partial barrier must fail the leg
    (and through it the gang) rather than park a subset of the slice
    against a host that will never come."""


class Rendezvous(Protocol):
    """Minimal cross-host primitives the coordinator needs.

    ``rank`` is the caller's process index; transports where the runtime
    already knows the caller's identity (jax.distributed) may ignore it.
    ``timeout`` bounds the wait where the transport can (raise
    :class:`BarrierTimeout` on expiry); transports that cannot bound a
    collective (jax.distributed) document that they ignore it.
    """

    def barrier(self, name: str, timeout: float | None = None) -> None: ...

    def allgather(self, name: str, value: Any, rank: int,
                  timeout: float | None = None) -> list[Any]: ...


class LocalRendezvous:
    """In-process rendezvous for N simulated hosts (threads)."""

    def __init__(self, world_size: int) -> None:
        self.world_size = world_size
        self._barriers: dict[str, threading.Barrier] = {}
        self._values: dict[str, dict[int, Any]] = {}
        self._lock = threading.Lock()
        self._counter: dict[str, int] = {}

    def _barrier_for(self, name: str) -> threading.Barrier:
        with self._lock:
            if name not in self._barriers:
                self._barriers[name] = threading.Barrier(self.world_size)
            return self._barriers[name]

    def barrier(self, name: str, timeout: float | None = None) -> None:
        try:
            self._barrier_for(name).wait(timeout=timeout)
        except threading.BrokenBarrierError:
            # Broken by a peer's timeout or by ours: either way the
            # slice never fully arrived here.
            raise BarrierTimeout(
                f"barrier {name!r}: not all {self.world_size} host(s) "
                f"arrived within {timeout}s") from None

    def allgather(self, name: str, value: Any, rank: int,
                  timeout: float | None = None) -> list[Any]:
        with self._lock:
            self._values.setdefault(name, {})[rank] = value
        self.barrier(name + "/gathered", timeout=timeout)
        out = [self._values[name][k] for k in sorted(self._values[name])]
        self.barrier(name + "/read", timeout=timeout)
        return out


class FileRendezvous:
    """Cross-process rendezvous over a shared directory.

    The no-``jax.distributed`` transport: N workload processes on a
    shared filesystem (one node's simulated slice, or pods sharing the
    checkpoint PVC) rendezvous through per-rank marker files. Every
    wait is bounded (``GRIT_SLICE_BARRIER_TIMEOUT_S`` unless the call
    narrows it) and expiry raises :class:`BarrierTimeout` loudly.

    Layout: ``<dir>/<name>/arrive-<rank>`` markers for barriers,
    ``<dir>/<name>/value-<rank>.json`` for allgather payloads. Marker
    writes are atomic (tmp + rename) so a reader never sees a torn
    value. Names must be unique per use — the :class:`SliceCoordinator`
    already sequences them.
    """

    def __init__(self, directory: str, rank: int, world_size: int) -> None:
        self.directory = directory
        self.rank = int(rank)
        self.world_size = int(world_size)

    def _default_timeout(self) -> float:
        from grit_tpu.api import config  # noqa: PLC0415

        return float(config.SLICE_BARRIER_TIMEOUT_S.get())

    def _poll_s(self) -> float:
        from grit_tpu.api import config  # noqa: PLC0415

        return max(0.01, float(config.SLICE_POLL_S.get()))

    @staticmethod
    def _safe(name: str) -> str:
        return name.replace(os.sep, "_").replace("..", "_")

    def _write(self, name: str, fname: str, payload: str) -> str:
        d = os.path.join(self.directory, self._safe(name))
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, fname)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return d

    def _wait(self, d: str, prefix: str, timeout: float | None,
              name: str) -> list[str]:
        deadline = time.monotonic() + (
            timeout if timeout is not None else self._default_timeout())
        poll = self._poll_s()
        while True:
            try:
                # Atomic-rename writers: a .tmp- twin mid-write is not
                # an arrival.
                have = sorted(f for f in os.listdir(d)
                              if f.startswith(prefix) and ".tmp-" not in f)
            except OSError:
                have = []
            if len(have) >= self.world_size:
                return have
            if time.monotonic() > deadline:
                raise BarrierTimeout(
                    f"barrier {name!r}: {len(have)}/{self.world_size} "
                    f"host(s) arrived before the deadline")
            time.sleep(poll)

    def barrier(self, name: str, timeout: float | None = None) -> None:
        d = self._write(name, f"arrive-{self.rank:04d}", str(self.rank))
        self._wait(d, "arrive-", timeout, name)

    def allgather(self, name: str, value: Any, rank: int,
                  timeout: float | None = None) -> list[Any]:
        d = self._write(name, f"value-{rank:04d}.json", json.dumps(value))
        files = self._wait(d, "value-", timeout, name)
        out = []
        for fname in files:
            with open(os.path.join(d, fname)) as f:
                out.append(json.load(f))
        return out


class MultihostRendezvous:
    """Real multi-host rendezvous over JAX's distributed runtime.

    Requires ``jax.distributed.initialize`` to have run (GKE sets the
    coordinator address via the JobSet env). Uses
    ``multihost_utils.sync_global_devices`` (barrier via a trivial psum
    across all hosts' devices) and ``broadcast_one_to_all``/process-allgather
    for value exchange. ``timeout`` is accepted but NOT enforceable —
    XLA collectives cannot be cancelled — so the distributed runtime's
    own initialization timeout is the effective bound; callers that need
    a hard bound (the quiesce gate) get it from the agent-side quiesce
    timeout instead.
    """

    def __init__(self) -> None:
        from jax.experimental import multihost_utils  # noqa: PLC0415

        self._mh = multihost_utils

    def barrier(self, name: str, timeout: float | None = None) -> None:
        del timeout  # unenforceable on an XLA collective; see docstring
        self._mh.sync_global_devices(name)

    def allgather(self, name: str, value: Any, rank: int,
                  timeout: float | None = None) -> list[Any]:
        import numpy as np  # noqa: PLC0415

        del rank, timeout  # the distributed runtime knows the caller
        arr = self._mh.process_allgather(np.asarray(value))
        return list(arr)


@dataclass
class SliceCoordinator:
    """Drives consistent-cut snapshots for one host of a slice."""

    rendezvous: Rendezvous
    process_index: int | None = None
    process_count: int | None = None
    _seq: int = field(default=0)

    def _pidx(self) -> int:
        return (
            self.process_index
            if self.process_index is not None
            else jax.process_index()
        )

    def _pcount(self) -> int:
        return (
            self.process_count
            if self.process_count is not None
            else jax.process_count()
        )

    def agree_cut_step(self, current_step: int) -> int:
        """All hosts exchange steps; the cut is the max (run-forward rule)."""
        self._seq += 1
        name = f"grit/cut/{self._seq}"
        steps = self.rendezvous.allgather(name, int(current_step), self._pidx())
        return max(int(s) for s in steps)

    def snapshot(
        self,
        directory: str,
        state: Any,
        *,
        step_fn: Callable[[], Any] | None = None,
        current_step: int | None = None,
        meta: dict | None = None,
        base: str | None = None,
        hashes: bool = False,
        mirror: str | None = None,
    ) -> str:
        """Consistent-cut snapshot across all hosts.

        ``state`` is the pytree to dump, or a **callable returning it** —
        required whenever ``step_fn`` rebinds the state object rather than
        mutating it in place (the Trainer does: its step donates the old
        state's buffers, so a pre-loop reference would dump deleted
        arrays). With ``step_fn``/``current_step`` the host first runs
        forward to the agreed cut step.

        ``base``: delta-dump against an earlier coordinated snapshot (the
        multi-host pre-copy pass); every host delta-checks only the shards
        it owns, so the skip work parallelizes like the dump itself.

        ``mirror``: streaming-upload destination — every host tees its
        own shard file while dumping, and process 0 seals the mirror only
        after ALL hosts dropped their mirror-ok markers (the barrier
        orders marker writes before the commit check).
        """
        if current_step is not None and step_fn is not None:
            cut = self.agree_cut_step(current_step)
            while current_step < cut:
                step_fn()
                current_step += 1
            if meta is None:
                meta = {"step": cut}
        if callable(state):
            state = state()
        quiesce(state)
        self._seq += 1
        name = f"grit/snap/{self._seq}"
        return write_snapshot(
            directory,
            state,
            meta=meta,
            barrier=lambda: self.rendezvous.barrier(name),
            process_index=self._pidx(),
            process_count=self._pcount(),
            base=base,
            hashes=hashes,
            mirror=mirror,
        )

    def restore(self, directory: str, **kwargs) -> Any:
        """Barriered restore: no host starts stepping until all loaded."""
        state = restore_snapshot(directory, **kwargs)
        self._seq += 1
        self.rendezvous.barrier(f"grit/restored/{self._seq}")
        return state


class SliceQuiesceGate:
    """The cross-host quiesce barrier, as the agentlet sees it.

    Single-host quiesce parks the training loop at its NEXT step
    boundary — on a slice that tears collectives: host A parked at step
    12 while host B runs to 13 leaves B blocked in a psum A will never
    join, and a dump taken there is gang-inconsistent. The gate turns
    "next boundary" into "the SAME agreed boundary on every host":

    1. on the first :meth:`ready_to_park` after a quiesce request, all
       hosts allgather their current step and agree on ``max`` (the
       run-forward rule — steps already taken can't be unwound);
    2. hosts below the cut keep stepping (``ready_to_park`` → False);
    3. at the cut, each host enters a BOUNDED barrier
       (``GRIT_SLICE_BARRIER_TIMEOUT_S``) — only when every host
       arrived does the gate let the loop park, so no dump anywhere on
       the slice can capture a torn collective;
    4. a barrier timeout (a host died pre-cut, a wedged peer) fails
       LOUDLY: the gate latches failed, the loop keeps training, the
       agent's quiesce times out, and the gang aborts — the failure
       mode is a failed migration, never a half-parked slice.

    Wired into :class:`grit_tpu.device.agentlet.Agentlet` via its
    ``slice_gate`` argument; the agent's quiesce request carries the
    flight dir so the barrier bracket lands on the migration timeline.
    """

    def __init__(self, coordinator: SliceCoordinator,
                 timeout_s: float | None = None) -> None:
        self.coordinator = coordinator
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._cut: int | None = None
        self._passed = False
        self.failed: str | None = None
        self._flight_dir: str | None = None
        self._nonce = "0"
        # Quiesce-round generation within one nonce: scopes rendezvous
        # names per ROUND, because FileRendezvous arrivals persist on
        # disk — a second quiesce under the same nonce reading round
        # 1's complete value set would compute a stale cut on one host
        # and a fresh one on another (the torn cut the gate exists to
        # prevent). reset() — which every host's resume runs, success
        # or abort — advances it in lockstep; a host that missed a
        # round desyncs and fails LOUDLY at the bounded wait instead.
        self._gen = 0

    def timeout_s(self) -> float:
        if self._timeout_s is not None:
            return self._timeout_s
        from grit_tpu.api import config  # noqa: PLC0415

        return float(config.SLICE_BARRIER_TIMEOUT_S.get())

    @property
    def cut(self) -> int | None:
        with self._lock:
            return self._cut

    def request(self, flight_dir: str | None = None,
                nonce: str | None = None) -> None:
        """Arm for one quiesce round (called by the agentlet when the
        quiesce op arrives). ``flight_dir`` joins the barrier bracket to
        the migration's flight log. ``nonce`` scopes this ATTEMPT's
        rendezvous names: a retried gang must never meet a failed
        attempt's leftover arrivals (the agents stamp the same attempt
        number on every host, so the gang agrees on the namespace)."""
        with self._lock:
            if flight_dir:
                self._flight_dir = flight_dir
            if nonce is not None and nonce != self._nonce:
                # Fresh attempt: clear a latched failure and the stale
                # cut so the new gang re-agrees from scratch (and the
                # round generation restarts — a new nonce is a new
                # namespace).
                self._nonce = str(nonce)
                self._gen = 0
                self._cut = None
                self._passed = False
                self.failed = None

    def reset(self) -> None:
        """Forget the agreed cut (called on resume): the next quiesce
        re-agrees — and a latched barrier failure is cleared, so a
        later migration attempt starts fresh. Advances the round
        generation so the next round's rendezvous names never meet
        this round's persisted arrivals."""
        with self._lock:
            self._gen += 1
            self._cut = None
            self._passed = False
            self.failed = None
            self._flight_dir = None

    def ready_to_park(self, step: int) -> bool:
        """Whether the loop may park at this step boundary. False while
        the slice has not yet agreed, this host is below the cut, or the
        barrier failed (then the loop keeps training and the quiesce
        request times out loudly on the agent side)."""
        from grit_tpu import faults  # noqa: PLC0415
        from grit_tpu.obs import flight  # noqa: PLC0415
        from grit_tpu.obs.metrics import SLICE_BARRIER_SECONDS  # noqa: PLC0415

        with self._lock:
            if self.failed is not None:
                return False
            if self._passed:
                return True
            cut = self._cut
            nonce = f"{self._nonce}.g{self._gen}"
        rdv = self.coordinator.rendezvous
        try:
            if cut is None:
                # Cut agreement is bounded like the barrier: a host
                # whose agent died BEFORE quiescing it would otherwise
                # pin every peer's training thread in the gather forever
                # — unresumable even by abort.
                steps = rdv.allgather(
                    f"grit/q{nonce}/cut", int(step),
                    self.coordinator._pidx(), timeout=self.timeout_s())
                cut = max(int(s) for s in steps)
                with self._lock:
                    self._cut = cut
            if int(step) < cut:
                return False  # run forward to the agreed boundary
            t0 = time.monotonic()
            if self._flight_dir:
                flight.emit_near(self._flight_dir, "slice.barrier.start",
                                 step=int(step), cut=cut)
            ok = False
            try:
                faults.fault_point("slice.barrier")
                rdv.barrier(f"grit/q{nonce}/barrier-{cut}",
                            timeout=self.timeout_s())
                ok = True
            finally:
                wait_s = time.monotonic() - t0
                if self._flight_dir:
                    flight.emit_near(self._flight_dir, "slice.barrier.end",
                                     cut=cut, ok=ok,
                                     wait_s=round(wait_s, 4))
                SLICE_BARRIER_SECONDS.set(wait_s)
        except Exception as exc:  # noqa: BLE001 — latch, never kill the loop
            with self._lock:
                self.failed = f"{type(exc).__name__}: {exc}"
            log.error(
                "slice quiesce barrier failed at cut %s: %s — this host "
                "will NOT park (the agent's quiesce request times out "
                "and the gang aborts)", cut, exc)
            return False
        with self._lock:
            self._passed = True
        return True
