"""Device-mesh construction for v5e-style topologies.

Axis convention (scaling-book style), outermost→innermost:

- ``data``  — pure data parallelism; gradients all-reduced (rides DCN
  between slices, ICI within one).
- ``fsdp``  — data parallelism with sharded parameters/optimizer state
  (ZeRO-3); params all-gathered per layer, grads reduce-scattered. Kept
  innermost-but-one so the gather/scatter traffic rides ICI.
- ``model`` — tensor parallelism (megatron-style); activations
  all-reduced. Innermost axis: highest-bandwidth ICI neighbors.

All three axes always exist (size 1 when unused) so partition specs and
checkpointed sharding descriptors stay stable as a job is re-laid-out —
restoring a dp=8 snapshot onto a dp=4×fsdp=2 mesh is a sharding change,
not a format change.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
AXES = (DATA_AXIS, FSDP_AXIS, MODEL_AXIS)


@dataclass(frozen=True)
class MeshSpec:
    """Logical slice decomposition. ``data = -1`` absorbs leftover devices."""

    data: int = -1
    fsdp: int = 1
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int]:
        data, fsdp, model = self.data, self.fsdp, self.model
        fixed = fsdp * model
        if data == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fsdp*model={fixed}"
                )
            data = n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{fsdp}x{model} != {n_devices} devices"
            )
        return data, fsdp, model


def build_mesh(
    spec: MeshSpec | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a ``Mesh`` with axes (data, fsdp, model) over ``devices``.

    Device order follows ``jax.devices()`` which on TPU enumerates in
    physical torus order — adjacent mesh coordinates are ICI neighbors, so
    the innermost (model) axis gets the cheapest collectives.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    shape = spec.resolve(len(devices))
    return Mesh(np.array(devices).reshape(shape), AXES)
