"""Parallelism layer — device meshes, sharding rules, slice coordination.

The reference has **no** parallelism or collective-communication code
(SURVEY §2.4: single-GPU pod scope, verified absent). This package is the
genuinely new component the TPU build needs: the workloads being
checkpointed are sharded JAX programs on v5e slices, so the framework must
(a) define the meshes/shardings those workloads run under, and (b) cut a
*consistent* snapshot across every host of a slice — no torn ICI
collectives — and re-establish the mesh on restore
(:mod:`grit_tpu.parallel.coordination`).
"""

from grit_tpu.parallel.mesh import MeshSpec, build_mesh
from grit_tpu.parallel.pipeline import (
    microbatch,
    pipeline_apply,
    pipeline_loss,
    stack_stage_params,
)
from grit_tpu.parallel.sharding import (
    ShardingRules,
    named_sharding,
    shard_tree,
    spec_for,
)

__all__ = [
    "MeshSpec",
    "build_mesh",
    "ShardingRules",
    "microbatch",
    "named_sharding",
    "pipeline_apply",
    "pipeline_loss",
    "shard_tree",
    "spec_for",
    "stack_stage_params",
]
