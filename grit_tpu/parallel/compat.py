"""jax API compatibility shims.

The repo targets current jax, where ``jax.shard_map`` is public API; the
bench/CI containers sometimes pin an older 0.4.x where it lives at
``jax.experimental.shard_map.shard_map`` and expresses partially-manual
meshes through an ``auto=`` complement instead of ``axis_names=``. One
wrapper keeps every call site on the modern keyword signature.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None):
    """``jax.shard_map`` with a fallback for jax builds that predate it.

    ``axis_names``: the set of mesh axes manual inside ``f`` (None → all
    of them), translated to the legacy API's ``auto`` complement when
    falling back.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm  # noqa: PLC0415

    kw = {}
    if axis_names is not None:
        auto = frozenset(set(mesh.axis_names) - set(axis_names))
        if auto:
            kw["auto"] = auto
    # The legacy replication checker miscounts scan carries that psum
    # (its own error message suggests check_rep=False as the workaround);
    # the modern path above keeps full checking.
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False, **kw)


def pvary(x, axis_names):
    """Mark ``x`` varying over the manual ``axis_names`` — newer
    shard_map tracks varying manual axes explicitly via ``lax.pcast``;
    legacy builds have no tracking, so this is a no-op there."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, tuple(axis_names), to="varying")
