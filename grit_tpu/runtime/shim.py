"""Shim task service semantics model.

The SPAWNABLE implementation containerd runs is the C++ daemon in
``native/shim/`` (``containerd-shim-grit-tpu-v1``, tested end-to-end over
its TTRPC socket in ``tests/test_shim_binary.py``). This module is the
same state machine as testable in-process Python against
:class:`~grit_tpu.cri.runtime.FakeRuntime` — the harness the e2e
migration suite composes without needing root/runc — and serves as the
behavior spec the binary mirrors.

Parity with ``cmd/containerd-shim-grit-v1/``:

- ``CheckpointOpts`` — annotation keys + path helpers + the
  container-type=="container" gate (``runc/checkpoint_util.go:11-78``).
- ``ShimTaskService.create`` — reads the OCI-spec annotations; if
  ``grit.dev/checkpoint`` is present *and* the checkpoint dir exists, the
  create is rewritten into a restore (``runc/container.go:63-77``), the
  rootfs diff is applied before start (``container.go:139-172``), and the
  init process enters the created-checkpoint state
  (``process/init.go:129-131,187-209``).
- ``ShimTaskService.start`` — created-checkpoint start executes the restore
  (``process/init_state.go:147-192``), with the TPU device hook reattaching
  HBM state where the reference's CRIU+cuda plugin resumes the GPU.
- ``ShimTaskService.checkpoint`` — forwards a dump request
  (``task/service.go:549-558`` → ``runc/container.go:530-552`` →
  ``process/init.go:425-452``), salvaging the criu work-dir log on failure.

The process-lifecycle bookkeeping (state transitions, exit events) mirrors
the init-process state machine (``process/init_state.go:31-415``) in
simplified form; console/IO plumbing is containerd-generic, not GRIT logic,
and stays with the runtime adapter.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from typing import Protocol

from grit_tpu.api.constants import (
    CHECKPOINT_DATA_PATH_ANNOTATION,
    RESTORE_NAME_ANNOTATION,
)
from grit_tpu.cri.runtime import (
    CONTAINER_TYPE_ANNOTATION,
    Container,
    FakeRuntime,
    OciSpec,
    SimProcess,
    TaskState,
)
from grit_tpu.metadata import CHECKPOINT_DIRECTORY, ROOTFS_DIFF_TAR
from grit_tpu.obs import flight


class InitState(str, enum.Enum):
    """process/init_state.go:31-415 states."""

    CREATED = "created"
    CREATED_CHECKPOINT = "createdCheckpoint"
    RUNNING = "running"
    PAUSED = "paused"
    STOPPED = "stopped"
    DELETED = "deleted"


@dataclass
class CheckpointOpts:
    """Restore parameters recovered from OCI-spec annotations
    (reference checkpoint_util.go:11-34)."""

    checkpoint_path: str = ""  # value of grit.dev/checkpoint
    restore_name: str = ""

    @classmethod
    def from_spec(cls, spec: OciSpec) -> "CheckpointOpts | None":
        # Only workload containers are rewritten — never the sandbox/pause
        # container (reference checkpoint_util.go:65-68).
        if spec.annotations.get(CONTAINER_TYPE_ANNOTATION, "container") != "container":
            return None
        path = spec.annotations.get(CHECKPOINT_DATA_PATH_ANNOTATION, "")
        if not path:
            return None
        return cls(
            checkpoint_path=path,
            restore_name=spec.annotations.get(RESTORE_NAME_ANNOTATION, ""),
        )

    def container_checkpoint_dir(self, container_name: str) -> str:
        """``<grit.dev/checkpoint>/<container-name>/`` holds this container's
        image (layout in :mod:`grit_tpu.metadata`)."""

        return os.path.join(self.checkpoint_path, container_name)


class DeviceRestoreHook(Protocol):
    """Reattach accelerator state after process restore — the role the second
    ``cuda-checkpoint --toggle`` plays in the reference (SURVEY §5)."""

    def load(self, pid: int, src_dir: str) -> None: ...


class NoopDeviceRestoreHook:
    def load(self, pid: int, src_dir: str) -> None:  # noqa: ARG002
        return


@dataclass
class ShimEvent:
    """TaskCreate/TaskStart/TaskCheckpointed/TaskExit forwarding analogue
    (reference service.go:784-794)."""

    type: str
    container_id: str
    detail: str = ""


@dataclass
class _Entry:
    container: Container
    state: InitState
    restore_from: str = ""  # checkpoint dir when created via restore


class ShimTaskService:
    """TTRPC Task service surface (the subset carrying GRIT behavior)."""

    def __init__(self, runtime: FakeRuntime,
                 device_hook: DeviceRestoreHook | None = None) -> None:
        self.runtime = runtime
        self.device_hook = device_hook or NoopDeviceRestoreHook()
        self._entries: dict[str, _Entry] = {}
        self.events: list[ShimEvent] = []

    # -- Create (service.go:223-262 → runc.NewContainer container.go:51-204) ----

    def create(
        self,
        sandbox_id: str,
        container_id: str,
        name: str,
        spec: OciSpec,
        process: SimProcess | None = None,
    ) -> _Entry:
        container = Container(id=container_id, sandbox_id=sandbox_id, name=name,
                              spec=spec)
        self.runtime.add_container(container, process=process, running=False)

        opts = CheckpointOpts.from_spec(spec)
        restore_from = ""
        if opts is not None:
            ckpt_dir = opts.container_checkpoint_dir(name)
            image_dir = os.path.join(ckpt_dir, CHECKPOINT_DIRECTORY)
            # The rewrite only happens when the image actually exists —
            # otherwise fall through to a cold create (container.go:63-77).
            if os.path.isdir(image_dir):
                restore_from = ckpt_dir
                # Apply the rootfs rw-layer diff before start
                # (container.go:139-172).
                diff_path = os.path.join(ckpt_dir, ROOTFS_DIFF_TAR)
                if os.path.exists(diff_path):
                    with open(diff_path, "rb") as f:
                        self.runtime.apply_rootfs_diff(container_id, f.read())
                # Inject the HBM snapshot location into the container env
                # so the workload's Trainer/engine restores device state
                # before its first step (the TPU path is cooperative —
                # grit_tpu/device/hook.py).
                from grit_tpu.device.hook import HBM_SUBDIR, RESTORE_ENV

                hbm_dir = os.path.join(ckpt_dir, HBM_SUBDIR)
                if os.path.isdir(hbm_dir):
                    spec.env[RESTORE_ENV] = hbm_dir

        state = InitState.CREATED_CHECKPOINT if restore_from else InitState.CREATED
        entry = _Entry(container=container, state=state, restore_from=restore_from)
        self._entries[container_id] = entry
        self.events.append(ShimEvent("TaskCreate", container_id,
                                     "restore" if restore_from else "create"))
        return entry

    # -- Start (service.go:270-348; createdCheckpointState.Start
    #    init_state.go:147-192) ------------------------------------------------

    def start(self, container_id: str) -> None:
        entry = self._entries[container_id]
        if entry.state == InitState.CREATED_CHECKPOINT:
            image_dir = os.path.join(entry.restore_from, CHECKPOINT_DIRECTORY)
            # The shim joins the migration's flight log through the stage
            # dir it restores from (the restore agent created the log at
            # that root) — the CRIU-restore phase of the blackout.
            flight.emit_near(entry.restore_from, "criu.restore.start",
                             container=container_id)
            task = self.runtime.restore_task(container_id, image_dir)
            # Reattach device state (HBM) — second toggle analogue.
            self.device_hook.load(task.pid, entry.restore_from)
            flight.emit_near(entry.restore_from, "criu.restore.end",
                             container=container_id)
            entry.state = InitState.RUNNING
            self.events.append(ShimEvent("TaskStart", container_id, "restored"))
            return
        if entry.state != InitState.CREATED:
            raise RuntimeError(f"cannot start container in state {entry.state}")
        task = self.runtime.get_task(container_id)
        task.state = TaskState.RUNNING
        entry.state = InitState.RUNNING
        self.events.append(ShimEvent("TaskStart", container_id, "cold"))

    # -- Pause / Resume ---------------------------------------------------------

    def pause(self, container_id: str) -> None:
        self.runtime.pause(container_id)
        self._entries[container_id].state = InitState.PAUSED

    def resume(self, container_id: str) -> None:
        self.runtime.resume(container_id)
        self._entries[container_id].state = InitState.RUNNING

    # -- Checkpoint (service.go:549-558 → init.go:425-452) ----------------------

    def checkpoint(self, container_id: str, image_path: str, work_dir: str,
                   leave_running: bool = True) -> None:
        entry = self._entries[container_id]
        was_running = entry.state == InitState.RUNNING
        if was_running:
            self.pause(container_id)
        try:
            self.runtime.checkpoint_task(container_id, image_path, work_dir)
        except Exception as exc:
            # Salvage the criu dump log for diagnosis (init.go:445-449).
            log = os.path.join(work_dir, "dump.log")
            detail = ""
            if os.path.exists(log):
                with open(log) as f:
                    detail = f.read()[-2048:]
            raise RuntimeError(f"checkpoint failed: {exc}; criu log: {detail}") from exc
        finally:
            if leave_running and was_running:
                self.resume(container_id)
        if not leave_running:
            self.kill(container_id)
        self.events.append(ShimEvent("TaskCheckpointed", container_id))

    # -- Kill / Delete ----------------------------------------------------------

    def kill(self, container_id: str) -> None:
        self.runtime.kill_task(container_id)
        self._entries[container_id].state = InitState.STOPPED
        self.events.append(ShimEvent("TaskExit", container_id))

    def delete(self, container_id: str) -> None:
        entry = self._entries[container_id]
        if entry.state not in (InitState.STOPPED, InitState.CREATED,
                               InitState.CREATED_CHECKPOINT):
            raise RuntimeError(f"cannot delete container in state {entry.state}")
        entry.state = InitState.DELETED
        self.events.append(ShimEvent("TaskDelete", container_id))

    def state(self, container_id: str) -> InitState:
        return self._entries[container_id].state
