"""CRI interceptor: PullImage gate + container-log splice.

Parity: reference ``contrib/containerd/grit-interceptor.diff`` — the 121-line
patch into containerd's CRI server:

- ``intercept_pull_image`` — if the sandbox carries ``grit.dev/checkpoint``,
  block image pull by polling (1 s period) for the agent's
  ``download-state`` sentinel, bounded by the context deadline or 10 min
  (diff:140-172, hook :185-194). This is the synchronization holding pod
  start until restore data is fully staged on the node.
- ``intercept_create_container`` — pre-seed the kubelet container log from
  ``<ckpt>/<container>/container.log`` so ``kubectl logs`` is continuous
  across the migration (diff:81-119, hook :34-45).

Deployment note: on real nodes this logic is carried by the rebased
containerd patch in ``deploy/containerd/``; this module is the same logic as
a testable unit, and serves as the reference implementation for the patch.
"""

from __future__ import annotations

import os
import shutil
import time
from collections.abc import Callable

from grit_tpu.api.constants import CHECKPOINT_DATA_PATH_ANNOTATION
from grit_tpu.metadata import CONTAINER_LOG_FILE, sentinel_path

POLL_INTERVAL_SECONDS = 1.0  # diff:140-172 polls at 1 s
DEFAULT_TIMEOUT_SECONDS = 600.0  # ctx deadline fallback: 10 min


class DownloadTimeout(Exception):
    pass


class CriInterceptor:
    def __init__(
        self,
        poll_interval: float = POLL_INTERVAL_SECONDS,
        timeout: float = DEFAULT_TIMEOUT_SECONDS,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.poll_interval = poll_interval
        self.timeout = timeout
        self._sleep = sleep
        self._clock = clock

    # -- PullImage gate ---------------------------------------------------------

    def intercept_pull_image(self, sandbox_annotations: dict[str, str]) -> None:
        """Block until the restore agent's sentinel exists; no-op for pods
        without the checkpoint annotation."""

        ckpt_path = sandbox_annotations.get(CHECKPOINT_DATA_PATH_ANNOTATION, "")
        if not ckpt_path:
            return
        deadline = self._clock() + self.timeout
        sentinel = sentinel_path(ckpt_path)
        while not os.path.exists(sentinel):
            if self._clock() >= deadline:
                raise DownloadTimeout(
                    f"checkpoint data not staged at {ckpt_path} within "
                    f"{self.timeout:.0f}s"
                )
            self._sleep(self.poll_interval)

    # -- CreateContainer log splice ---------------------------------------------

    def intercept_create_container(
        self,
        sandbox_annotations: dict[str, str],
        container_name: str,
        kubelet_container_log_dir: str,
    ) -> str | None:
        """Copy the checkpointed ``container.log`` into the new pod's kubelet
        log dir (as ``0.log``) before the container starts. Returns the
        seeded path, or None when not a restore / no saved log."""

        ckpt_path = sandbox_annotations.get(CHECKPOINT_DATA_PATH_ANNOTATION, "")
        if not ckpt_path:
            return None
        saved = os.path.join(ckpt_path, container_name, CONTAINER_LOG_FILE)
        if not os.path.exists(saved):
            return None
        os.makedirs(kubelet_container_log_dir, exist_ok=True)
        dst = os.path.join(kubelet_container_log_dir, "0.log")
        shutil.copyfile(saved, dst)
        return dst
