"""TTRPC client: the containerd↔shim wire protocol, from Python.

This is how the framework talks to a running ``containerd-shim-grit-tpu-v1``
daemon without containerd in the middle — the diagnostic/ops role the
reference gets from ``ctr`` against its shim. Frames are 10-byte big-endian
headers ``{len u32, stream u32, type u8, flags u8}`` followed by a
``grit.ttrpc.Request``/``Response`` protobuf (native/shim/proto/
gritttrpc.proto); the server side is native/shim/ttrpc_server.cc.

Reference analogue: the ttrpc Go client containerd uses to drive
``cmd/containerd-shim-grit-v1`` (manager_linux.go:186-188).
"""

from __future__ import annotations

import socket
import struct

from grit_tpu.runtime import shimpb

MESSAGE_TYPE_REQUEST = 0x1
MESSAGE_TYPE_RESPONSE = 0x2
_HEADER = struct.Struct(">IIBB")
MAX_MESSAGE_SIZE = 4 << 20

TASK_SERVICE = "containerd.task.v2.Task"


class TtrpcError(RuntimeError):
    """Non-OK status from the server (carries the gRPC code)."""

    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"ttrpc status {code}: {message}")
        self.code = code
        self.status_message = message


class TtrpcClient:
    """Unary-call client over a unix socket. Not thread-safe; use one per
    thread (blocking calls like Task.Wait hold the connection)."""

    def __init__(self, socket_path: str, timeout: float | None = 30.0) -> None:
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(socket_path)
        self._next_stream = 1  # client streams are odd

    def close(self) -> None:
        self._sock.close()

    def __enter__(self) -> "TtrpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- wire helpers -----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ttrpc connection closed mid-frame")
            buf += chunk
        return buf

    def _send_frame(self, stream_id: int, mtype: int, payload: bytes) -> None:
        self._sock.sendall(_HEADER.pack(len(payload), stream_id, mtype, 0))
        self._sock.sendall(payload)

    def _recv_frame(self) -> tuple[int, int, bytes]:
        length, stream_id, mtype, _flags = _HEADER.unpack(
            self._recv_exact(_HEADER.size)
        )
        if length > MAX_MESSAGE_SIZE:
            raise ConnectionError(f"oversized ttrpc frame ({length} bytes)")
        return stream_id, mtype, self._recv_exact(length)

    # -- calls ------------------------------------------------------------------

    def call(self, service: str, method: str, request, response_cls,
             timeout_nano: int = 0):
        """One unary call; raises :class:`TtrpcError` on non-OK status."""

        stream_id = self._next_stream
        self._next_stream += 2
        req = shimpb.Request(
            service=service,
            method=method,
            payload=request.SerializeToString(),
            timeout_nano=timeout_nano,
        )
        self._send_frame(stream_id, MESSAGE_TYPE_REQUEST, req.SerializeToString())
        while True:
            got_stream, mtype, payload = self._recv_frame()
            if mtype != MESSAGE_TYPE_RESPONSE or got_stream != stream_id:
                continue  # not ours (server is in-order, but be tolerant)
            resp = shimpb.Response()
            resp.ParseFromString(payload)
            if resp.status.code != 0:
                raise TtrpcError(resp.status.code, resp.status.message)
            out = response_cls()
            out.ParseFromString(resp.payload)
            return out


class ShimTaskClient:
    """Typed convenience wrapper for the task service."""

    def __init__(self, socket_path: str, timeout: float | None = 30.0) -> None:
        self._c = TtrpcClient(socket_path, timeout=timeout)

    def close(self) -> None:
        self._c.close()

    def __enter__(self) -> "ShimTaskClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _call(self, method: str, request, response_cls):
        return self._c.call(TASK_SERVICE, method, request, response_cls)

    def create(self, container_id: str, bundle: str, stdin: str = "",
               stdout: str = "", stderr: str = "", terminal: bool = False):
        return self._call(
            "Create",
            shimpb.CreateTaskRequest(id=container_id, bundle=bundle,
                                     stdin=stdin, stdout=stdout,
                                     stderr=stderr, terminal=terminal),
            shimpb.CreateTaskResponse,
        )

    def start(self, container_id: str, exec_id: str = ""):
        return self._call(
            "Start", shimpb.StartRequest(id=container_id, exec_id=exec_id),
            shimpb.StartResponse
        )

    def exec(self, container_id: str, exec_id: str, process_spec: dict,
             stdin: str = "", stdout: str = "", stderr: str = "",
             terminal: bool = False):
        """Register an auxiliary process (kubectl exec); run it with
        ``start(container_id, exec_id)``. ``process_spec`` is the OCI
        process document (at minimum ``{"args": [...]}``)."""
        import json

        from google.protobuf import any_pb2

        spec = any_pb2.Any(
            type_url="types.containerd.io/opencontainers/runtime-spec/1/Process",
            value=json.dumps(process_spec).encode(),
        )
        return self._call(
            "Exec",
            shimpb.ExecProcessRequest(
                id=container_id, exec_id=exec_id, terminal=terminal,
                stdin=stdin, stdout=stdout, stderr=stderr, spec=spec),
            shimpb.Empty,
        )

    def state(self, container_id: str, exec_id: str = ""):
        return self._call(
            "State", shimpb.StateRequest(id=container_id, exec_id=exec_id),
            shimpb.StateResponse
        )

    def wait(self, container_id: str, exec_id: str = ""):
        return self._call(
            "Wait", shimpb.WaitRequest(id=container_id, exec_id=exec_id),
            shimpb.WaitResponse
        )

    def kill(self, container_id: str, signal: int = 15,
             all_procs: bool = False, exec_id: str = ""):
        return self._call(
            "Kill",
            shimpb.KillRequest(id=container_id, exec_id=exec_id,
                               signal=signal, all=all_procs),
            shimpb.Empty,
        )

    def pause(self, container_id: str):
        return self._call(
            "Pause", shimpb.PauseRequest(id=container_id), shimpb.Empty
        )

    def resume(self, container_id: str):
        return self._call(
            "Resume", shimpb.ResumeRequest(id=container_id), shimpb.Empty
        )

    def checkpoint(self, container_id: str, path: str):
        return self._call(
            "Checkpoint",
            shimpb.CheckpointTaskRequest(id=container_id, path=path),
            shimpb.Empty,
        )

    def delete(self, container_id: str, exec_id: str = ""):
        return self._call(
            "Delete",
            shimpb.DeleteRequest(id=container_id, exec_id=exec_id),
            shimpb.DeleteResponse
        )

    def pids(self, container_id: str):
        return self._call(
            "Pids", shimpb.PidsRequest(id=container_id), shimpb.PidsResponse
        )

    def stats(self, container_id: str):
        """Cgroup-v2 task stats; returns a GritStats message (or None
        when the container has no cgroup recorded)."""
        resp = self._call(
            "Stats", shimpb.StatsRequest(id=container_id), shimpb.StatsResponse
        )
        if not resp.stats.value:
            return None
        out = shimpb.GritStats()
        out.ParseFromString(resp.stats.value)
        return out

    def connect(self, container_id: str = ""):
        return self._call(
            "Connect", shimpb.ConnectRequest(id=container_id),
            shimpb.ConnectResponse,
        )

    def resize_pty(self, container_id: str, width: int, height: int,
                   exec_id: str = ""):
        return self._call(
            "ResizePty",
            shimpb.ResizePtyRequest(id=container_id, exec_id=exec_id,
                                    width=width, height=height),
            shimpb.Empty,
        )

    def close_io(self, container_id: str, exec_id: str = "",
                 stdin: bool = True):
        return self._call(
            "CloseIO",
            shimpb.CloseIORequest(id=container_id, exec_id=exec_id,
                                  stdin=stdin),
            shimpb.Empty,
        )

    def update(self, container_id: str, resources: dict):
        """Live resource update: ``resources`` is an OCI runtime-spec
        LinuxResources document, carried JSON-encoded in the Any exactly
        as containerd's typeurl marshals runtime-spec types."""
        import json

        from google.protobuf import any_pb2

        res = any_pb2.Any(
            type_url=("types.containerd.io/opencontainers/runtime-spec/1/"
                      "LinuxResources"),
            value=json.dumps(resources).encode(),
        )
        return self._call(
            "Update",
            shimpb.UpdateTaskRequest(id=container_id, resources=res),
            shimpb.Empty,
        )

    def shutdown(self, now: bool = True):
        return self._call(
            "Shutdown", shimpb.ShutdownRequest(now=now), shimpb.Empty
        )
