"""GRIT-Runtime: the container-runtime integration layer.

Parity: reference ``cmd/containerd-shim-grit-v1/`` (the forked runc-v2 shim)
and ``contrib/containerd/grit-interceptor.diff`` (the CRI patch). The shim's
GRIT delta — annotation-driven create→restore rewrite, rootfs-diff apply,
checkpoint execution — lives in :mod:`grit_tpu.runtime.shim`; the CRI-side
PullImage gate and log splice live in :mod:`grit_tpu.runtime.interceptor`.
"""

from grit_tpu.runtime.shim import CheckpointOpts, ShimTaskService  # noqa: F401
from grit_tpu.runtime.interceptor import CriInterceptor  # noqa: F401
