"""protoc-generated messages for the grit-tpu shim wire protocol.

Source of truth: ``native/shim/proto/*.proto`` (regenerate with
``make -C native proto``). The C++ shim links the same definitions, so the
Python client here and the daemon can never skew.
"""

import os as _os
import sys as _sys

# protoc emits flat module names that import each other absolutely; make the
# package dir importable so `import grittask_pb2` inside generated code works.
_here = _os.path.dirname(_os.path.abspath(__file__))
if _here not in _sys.path:
    _sys.path.insert(0, _here)

from grittask_pb2 import *  # noqa: F401,F403,E402
from gritttrpc_pb2 import Request, Response, Status, KeyValue  # noqa: F401,E402
import gritevents_pb2 as events  # noqa: E402,F401  (lifecycle event messages)
