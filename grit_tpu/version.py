"""Build/version stamping.

Parity: reference injects version/commit via ldflags at build time
(``pkg/injections/injections.go``, ``Makefile:22-29``). Python images get
the commit via the ``GRIT_TPU_GIT_SHA`` env baked in at image build
(docker --build-arg); a live git checkout resolves it on demand.
"""

from __future__ import annotations

import os
import subprocess

from grit_tpu import __version__
from grit_tpu.api import config


def git_sha() -> str:
    sha = config.TPU_GIT_SHA.get()
    if sha:
        return sha
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 - no git in the image
        return "unknown"


def version_string() -> str:
    return f"grit-tpu {__version__} ({git_sha()})"
