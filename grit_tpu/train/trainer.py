"""The Trainer: jitted sharded train step + snapshot/restore at step
boundaries.

TPU-first mechanics:

- the step is one ``jax.jit`` with explicit in/out shardings from the
  model's rule table and **donated** state (params/opt-state update in
  place in HBM — no transient 2× memory);
- batches are derived from the state's RNG key (``fold_in(step)``), so the
  data stream is a pure function of checkpointed state — exact resume
  without dataloader checkpointing;
- ``snapshot()`` = quiesce (drain device queues at the step boundary — the
  consistent cut) + streaming HBM dump (:mod:`grit_tpu.device.snapshot`);
- ``restore()`` rebuilds abstract state via ``jax.eval_shape`` (no wasted
  init compute), then loads shards straight to their target devices.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from grit_tpu.device import quiesce, restore_snapshot, write_snapshot
from grit_tpu.parallel.sharding import ShardingRules


@dataclass
class TrainerConfig:
    learning_rate: float = 1e-3
    seed: int = 0
    batch_spec: PartitionSpec = PartitionSpec()


class Trainer:
    """Owns the jitted step and the migratable state pytree.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar``.
      init_params: ``init_params(rng) -> params`` (called once, or never if
        restoring).
      batch_fn: ``batch_fn(rng) -> batch`` — pure function of the per-step
        RNG (fold_in of the state key and step).
      optimizer: optax transform; Adam(cfg.learning_rate) by default.
      mesh / rules: sharding context; None → single-device.
    """

    def __init__(
        self,
        loss_fn: Callable[[Any, Any], jax.Array],
        init_params: Callable[[jax.Array], Any],
        batch_fn: Callable[[jax.Array], Any],
        cfg: TrainerConfig | None = None,
        optimizer: optax.GradientTransformation | None = None,
        mesh: Mesh | None = None,
        rules: ShardingRules | None = None,
    ) -> None:
        self.cfg = cfg or TrainerConfig()
        self.loss_fn = loss_fn
        self.batch_fn = batch_fn
        self.mesh = mesh
        self.rules = rules
        self.optimizer = optimizer or optax.adam(self.cfg.learning_rate)
        self._init_params = init_params

        abstract, self._make_state = self._abstract_state()
        self._state_shardings = self._shardings_for(abstract)
        self._abstract = abstract
        # Lazy: a restoring process (maybe_restore_from_env / restore)
        # must never pay the full param init — at flagship scale that is
        # minutes of RNG + optimizer-state materialization spent inside
        # the migration blackout, thrown away by the restore one call
        # later. First access through the property materializes.
        self._state = None
        # In-flight post-copy restore (GRIT_RESTORE_POSTCOPY): the cold
        # bulk is still faulting in through the handle's tail; first
        # touch of the state resolves it (blocking per remaining array).
        self._postcopy = None
        self._postcopy_step: int | None = None
        self._step_fn = self._build_step()

    @property
    def state(self):
        if self._postcopy is not None:
            # First touch of the full pytree: join the post-copy tail.
            # Per-array blocking happens inside the handle — by the time
            # the workload computes here the tail has typically already
            # overlapped the restart/compile window. The handle is only
            # dropped AFTER wait() succeeds: a failed join must stay
            # loud on every retry, never silently degrade the next
            # access to a freshly-initialized state at step 0.
            resolved = self._postcopy.wait()
            self._postcopy = None
            self._postcopy_step = None
            self._state = resolved
        if self._state is None:
            self._state = self._build_state()
        return self._state

    @state.setter
    def state(self, value) -> None:
        self._state = value
        self._postcopy = None
        self._postcopy_step = None

    # -- state ------------------------------------------------------------------

    def _abstract_state(self):
        def make():
            params = self._init_params(jax.random.PRNGKey(self.cfg.seed))
            return {
                "params": params,
                "opt_state": self.optimizer.init(params),
                "step": jnp.zeros((), jnp.int32),
                "rng": jax.random.PRNGKey(self.cfg.seed),
            }

        return jax.eval_shape(make), make

    def _shardings_for(self, abstract):
        """Params/opt-state leaves follow the rule table (opt-state moments
        mirror their parameter's shape); scalars/rng replicate."""
        if self.mesh is None or self.rules is None:
            return None

        def leaf_sharding(path, leaf):
            from grit_tpu.parallel.sharding import _path_str

            p = _path_str(path)
            spec = self.rules.spec_for(p)
            if len(spec) > len(leaf.shape):
                spec = PartitionSpec()  # scalar opt-state leaf (e.g. count)
            return NamedSharding(self.mesh, spec)

        flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
        return jax.tree_util.tree_unflatten(
            treedef, [leaf_sharding(p, l) for p, l in flat]
        )

    def _build_state(self):
        if self._state_shardings is None:
            return self._make_state()
        return jax.jit(self._make_state, out_shardings=self._state_shardings)()

    # -- step -------------------------------------------------------------------

    def _build_step(self):
        def step(state):
            rng = jax.random.fold_in(state["rng"], state["step"])
            batch = self.batch_fn(rng)
            if self.mesh is not None:
                batch = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(
                        x, NamedSharding(self.mesh, self.cfg.batch_spec)
                    ),
                    batch,
                )
            loss, grads = jax.value_and_grad(self.loss_fn)(state["params"], batch)
            updates, opt_state = self.optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
            new_state = {
                "params": params,
                "opt_state": opt_state,
                "step": state["step"] + 1,
                "rng": state["rng"],
            }
            return new_state, {"loss": loss}

        kwargs = {}
        if self._state_shardings is not None:
            kwargs = dict(
                in_shardings=(self._state_shardings,),
                out_shardings=(
                    self._state_shardings,
                    NamedSharding(self.mesh, PartitionSpec()),
                ),
            )
        return jax.jit(step, donate_argnums=0, **kwargs)

    def train_step(self) -> dict:
        self.state, metrics = self._step_fn(self.state)
        return metrics

    def run(self, n_steps: int) -> list[float]:
        losses = []
        for _ in range(n_steps):
            losses.append(float(self.train_step()["loss"]))
        return losses

    @property
    def step(self) -> int:
        # A pending post-copy restore answers from the manifest's
        # recorded cut step WITHOUT touching the state: the workload's
        # loop condition (`while tr.step < n`) must not force the tail.
        if self._postcopy is not None and self._postcopy_step is not None:
            return self._postcopy_step
        return int(self.state["step"])

    # -- snapshot / restore -----------------------------------------------------

    def snapshot(
        self, directory: str, *, barrier=lambda: None,
        base: str | None = None, hashes: bool = False,
    ) -> str:
        """Consistent cut at the current step boundary → committed dir.

        ``base``: delta-dump against an earlier committed snapshot (the
        pre-copy pattern — dump full while training, delta at blackout).
        ``hashes``: record per-chunk sha256 so a later delta against this
        dump matches by hash instead of reading the bytes back."""
        quiesce(self.state)
        return write_snapshot(
            directory, self.state, meta={"step": self.step}, barrier=barrier,
            base=base, hashes=hashes,
        )

    def snapshot_coordinated(self, directory: str, coordinator) -> str:
        """Consistent-cut snapshot across all hosts of the slice: agree on
        the cut step, run forward to it, dump. ``coordinator`` is a
        :class:`grit_tpu.parallel.coordination.SliceCoordinator`. The state
        is passed as a getter because ``train_step`` donates and rebinds
        ``self.state``."""
        return coordinator.snapshot(
            directory,
            lambda: self.state,
            step_fn=self.train_step,
            current_step=self.step,
        )

    def maybe_restore_from_env(self) -> int | None:
        """Transparent-migration entry: if the shim injected
        ``GRIT_TPU_RESTORE_DIR`` (restore-mode pod create), reload state
        from it and return the step; otherwise None. Workloads call this
        once before their loop and need no other migration awareness."""
        from grit_tpu.device.hook import (  # noqa: PLC0415
            enable_compile_cache_from_env,
            restore_dir_from_env,
            seed_compile_cache,
        )

        # Opt into the persistent compilation cache early: source-side
        # compiles populate it so dumps can carry it; on the restore side
        # seed it from the snapshot's carried copy NOW — before the
        # eval_shape/jit machinery below touches the compiler — so every
        # compile from the first is a cache hit, not just the ones after
        # restore_snapshot's own (re-)seeding. With streamed staging the
        # carried cache is priority-staged ahead of the bulk HBM data, so
        # this overlaps the compile-cache warmup with the chunk transfer.
        cache_on = enable_compile_cache_from_env()
        d = restore_dir_from_env()
        if d and cache_on:
            seed_compile_cache(d)
        return self.restore(d) if d else None

    def restore(self, directory: str) -> int:
        """Load state; returns the restored step. The Trainer must be
        constructed with the same model/optimizer config (same state
        structure) but may be on a different mesh — shards are re-laid-out
        from the manifest's global indices. Never materializes the initial
        state (the lazy-init blackout lever — see ``__init__``).

        With ``GRIT_RESTORE_POSTCOPY`` set, restore goes lazy: the hot
        set (small arrays) places now, this method returns the cut step
        from the manifest, and the cold bulk faults in through a
        background tail — the first state touch (normally the first
        ``train_step``) blocks on whatever has not landed yet, per
        array. Blackout ends here, not at the last byte."""
        from grit_tpu.api import config as grit_config  # noqa: PLC0415

        if grit_config.RESTORE_POSTCOPY.get():
            from grit_tpu.device.snapshot import (  # noqa: PLC0415
                restore_snapshot_postcopy,
            )

            handle = restore_snapshot_postcopy(
                directory,
                like=self._abstract,
                mesh=self.mesh,
                shardings=self._state_shardings,
            )
            step = handle.meta.get("step")
            if isinstance(step, (int, float)):
                self._state = None
                self._postcopy = handle
                self._postcopy_step = int(step)
                return self._postcopy_step
            # No recorded cut step (a bare write_snapshot without meta):
            # the caller needs the step NOW, so resolve the tail.
            self.state = handle.wait()
            return self.step
        self.state = restore_snapshot(
            directory,
            like=self._abstract,
            mesh=self.mesh,
            shardings=self._state_shardings,
        )
        return self.step
