"""Training harness — step-oriented loop with migratable state.

The contract that makes a workload live-migratable: *all* mutable training
state lives in one pytree (params, optimizer state, RNG key, step counter),
every batch is a pure function of that state, and the loop offers a
quiesce+snapshot point at each step boundary. Restore then needs no
cooperation from the workload beyond "construct the same Trainer and call
``restore()``" — the TPU analogue of CRIU resuming the process mid-step
(reference resumes a falcon-7b job at step 15/200,
``docs/experiments/checkpoint-restore-tuning-job.md:98-148``).
"""

from grit_tpu.train.trainer import Trainer, TrainerConfig

__all__ = ["Trainer", "TrainerConfig"]
