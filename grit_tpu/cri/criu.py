"""Real-CRIU process runtime: the node path that actually dumps processes.

Parity: the reference delegates the process freeze to ``runc checkpoint`` →
CRIU (``cmd/containerd-shim-grit-v1/process/init.go:425-452``), and its
validation recipe drives CRIU against a raw pid
(``docs/experiments/checkpoint-restore-tuning-job.md:50-148``). This adapter
is that layer for us: it implements the same runtime protocol the agent
drives against containerd (:class:`grit_tpu.cri.runtime.FakeRuntime`'s
surface — list → pause → checkpoint_task → resume/kill), but the task
operations exec the real ``criu`` binary on live OS processes:

- ``pause``/``resume`` — SIGSTOP/SIGCONT (the raw-process analogue of the
  cgroup freezer containerd pause uses);
- ``checkpoint_task`` — ``criu dump --leave-stopped`` into the image dir,
  with ``--libdir`` pointed at the TPU plugin so ``grit_tpu_plugin.so``
  handles ``/dev/accel*`` fds (the role ``cuda_plugin.so`` plays in the
  reference);
- ``restore_task`` — ``criu restore --restore-detached`` + SIGCONT;
- failures salvage the tail of CRIU's log, mirroring the reference's
  criu-dump.log extraction (``process/init.go:445-449``,
  ``process/utils.go:90-95``).

Gating: :func:`criu_available` — the binary, root, and a passing
``criu check``. The e2e test skips without it; the adapter itself is the
real code a deployed node runs.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import time

from grit_tpu import faults
from grit_tpu.cri.runtime import Container, FakeRuntime, Task, TaskState

DUMP_LOG = "dump.log"
RESTORE_LOG = "restore.log"
_LOG_TAIL = 2000


def _criu_timeout_s() -> float:
    """Hard ceiling on one criu invocation (GRIT_CRIU_TIMEOUT_S, 600 s).
    criu can wedge indefinitely on a pathological tree (stuck D-state
    task, fuse mount); the agent must fail loudly inside its phase
    deadline, not spin until the manager watchdog shoots the Job."""
    from grit_tpu.api import config  # noqa: PLC0415

    return config.CRIU_TIMEOUT_S.get()


def default_plugin_dir() -> str | None:
    """Directory holding ``grit_tpu_plugin.so``: the repo's native build in
    a checkout, ``/usr/lib/criu`` in the node images (see
    ``docker/grit-agent/Dockerfile``)."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for cand in (os.path.join(here, "native", "build"), "/usr/lib/criu"):
        if os.path.isfile(os.path.join(cand, "grit_tpu_plugin.so")):
            return cand
    return None


def criu_available(criu_bin: str = "criu") -> tuple[bool, str]:
    """(usable, reason-if-not): binary present, running as root, and
    ``criu check`` passes (kernel features)."""
    path = shutil.which(criu_bin)
    if path is None:
        return False, f"{criu_bin} not on PATH"
    if hasattr(os, "geteuid") and os.geteuid() != 0:
        return False, "criu requires root"
    try:
        proc = subprocess.run(
            [path, "check"], capture_output=True, text=True, timeout=30
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        return False, f"criu check failed to run: {exc}"
    if proc.returncode != 0:
        return False, f"criu check: {proc.stdout}{proc.stderr}"[:500]
    return True, ""


class CriuError(RuntimeError):
    """CRIU invocation failure carrying the salvaged log tail."""

    def __init__(self, action: str, rc: int, log_path: str, note: str = ""):
        tail = ""
        try:
            with open(log_path, errors="replace") as f:
                tail = f.read()[-_LOG_TAIL:]
        except OSError:
            tail = f"(no {log_path})"
        prefix = f"criu {action} rc={rc}"
        if note:
            prefix += f" ({note})"
        super().__init__(f"{prefix}; log tail:\n{tail}")
        self.rc = rc


def _proc_state(pid: int) -> str:
    """Single-char process state from /proc (R/S/T/Z/...), '?' if gone."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(") ", 1)[1].split(" ", 1)[0]
    except (OSError, IndexError):
        return "?"


class CriuProcessRuntime(FakeRuntime):
    """The FakeRuntime's CRI bookkeeping (sandbox/container/label filtering
    is identical — it models containerd's metadata, not the fake process),
    with every task operation re-implemented over real pids + criu."""

    def __init__(
        self,
        criu_bin: str = "criu",
        *,
        plugin_dir: str | None = None,
        shell_job: bool = False,
        log_root: str = "/tmp/grit-criu-logs",
    ) -> None:
        super().__init__(log_root=log_root)
        self.criu_bin = criu_bin
        self.plugin_dir = plugin_dir if plugin_dir is not None else default_plugin_dir()
        self.shell_job = shell_job

    # -- registration ----------------------------------------------------------

    def attach_process(self, container: Container, pid: int,
                       running: bool = True) -> Container:
        """Register a real OS process as the container's task."""
        super().add_container(container, process=None, running=running)
        self.tasks[container.id] = Task(
            container_id=container.id, pid=pid,
            state=TaskState.RUNNING if running else TaskState.CREATED,
            process=None,
        )
        return container

    # -- task ops over real processes ------------------------------------------

    def pause(self, container_id: str) -> None:
        task = self.tasks[container_id]
        if task.state != TaskState.RUNNING:
            raise RuntimeError(f"task {container_id} not running ({task.state})")
        os.kill(task.pid, signal.SIGSTOP)
        deadline = time.monotonic() + 10.0
        while _proc_state(task.pid) not in ("T", "t"):
            if time.monotonic() > deadline:
                raise RuntimeError(f"pid {task.pid} did not stop")
            time.sleep(0.01)
        task.state = TaskState.PAUSED

    def resume(self, container_id: str) -> None:
        task = self.tasks[container_id]
        if task.state != TaskState.PAUSED:
            raise RuntimeError(f"task {container_id} not paused ({task.state})")
        os.kill(task.pid, signal.SIGCONT)
        task.state = TaskState.RUNNING

    def _criu(self, args: list[str], action: str, work_dir: str,
              log_name: str) -> None:
        cmd = [self.criu_bin, action, "--work-dir", work_dir,
               "-o", log_name, "-v4", *args]
        if self.plugin_dir:
            cmd += ["--libdir", self.plugin_dir]
        if self.shell_job:
            cmd += ["--shell-job"]
        timeout = _criu_timeout_s()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout)
        except subprocess.TimeoutExpired as exc:
            # subprocess.run already SIGKILLed the criu child; surface a
            # loud, classified error instead of spinning forever.
            raise CriuError(
                action, -1, os.path.join(work_dir, log_name),
                note=f"timed out after {timeout:.0f}s and was killed",
            ) from exc
        if proc.returncode != 0:
            raise CriuError(action, proc.returncode,
                            os.path.join(work_dir, log_name))

    def checkpoint_task(self, container_id: str, image_path: str,
                        work_dir: str) -> None:
        """``criu dump`` of the paused task (reference writeCriuCheckpoint
        runtime.go:177-186 → runc → criu). ``--leave-stopped`` keeps the
        agent's pause/resume contract: the driver decides afterwards whether
        to SIGCONT (leave-running) or kill (migration)."""
        faults.fault_point("cri.criu.dump")
        task = self.tasks[container_id]
        if task.state != TaskState.PAUSED:
            raise RuntimeError(f"checkpoint requires paused task ({task.state})")
        os.makedirs(image_path, exist_ok=True)
        os.makedirs(work_dir, exist_ok=True)
        self._criu(
            ["--tree", str(task.pid), "--images-dir", image_path,
             "--leave-stopped", "--tcp-established", "--file-locks"],
            "dump", work_dir, DUMP_LOG,
        )

    def restore_task(self, container_id: str, image_path: str) -> Task:
        """``criu restore --restore-detached`` (reference
        init_state.go:147-192 → runc restore), then SIGCONT — the dump left
        the tree stopped."""
        faults.fault_point("cri.criu.restore")
        task = self.tasks[container_id]
        work_dir = os.path.join(image_path, os.pardir, "criu-restore-work")
        os.makedirs(work_dir, exist_ok=True)
        pidfile = os.path.join(work_dir, "restored.pid")
        if os.path.exists(pidfile):
            os.unlink(pidfile)
        self._criu(
            ["--images-dir", image_path, "--restore-detached",
             "--pidfile", pidfile, "--tcp-established", "--file-locks"],
            "restore", work_dir, RESTORE_LOG,
        )
        with open(pidfile) as f:
            task.pid = int(f.read().strip())
        # The image was taken --leave-stopped; wake the restored tree.
        try:
            os.kill(task.pid, signal.SIGCONT)
        except ProcessLookupError:
            pass
        task.state = TaskState.RUNNING
        return task

    def kill_task(self, container_id: str) -> None:
        task = self.tasks[container_id]
        for sig in (signal.SIGKILL,):
            try:
                os.kill(task.pid, sig)
            except ProcessLookupError:
                pass
        # No reap: this runtime ATTACHES to pids it did not spawn, so the
        # zombie belongs to whoever holds the Popen — an opportunistic
        # waitpid here races the owner's wait() and, when it wins, makes
        # that wait() see ECHILD and report exit status 0 for a SIGKILLed
        # process.
        task.state = TaskState.STOPPED

    # -- node-level data (raw processes have no rootfs/kubelet logs) ----------

    def export_rootfs_diff(self, container_id: str) -> bytes:
        """Raw processes have no snapshotter; an empty tar keeps the
        checkpoint layout uniform (the containerd-backed path exports the
        real rw layer)."""
        import io
        import tarfile

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w"):
            pass
        return buf.getvalue()
