"""Production runtime adapter: CRI gRPC + grit-tpu shim TTRPC.

This is the node path the agent drives on a real Kubernetes node — the
role ``pkg/gritagent/checkpoint/runtime.go:46-224`` plays in the reference
(CRI ListContainers → containerd task Pause/Checkpoint → snapshotter diff),
recomposed for our stack:

- **Discovery / teardown** go to the CRI socket over gRPC
  (``runtime.v1.RuntimeService``: ListContainers with pod-label filters,
  ContainerStatus with ``verbose`` for the init pid, ListPodSandbox,
  StopContainer). Wire messages: :mod:`grit_tpu.cri.cripb`.
- **Task operations** (pause/resume/checkpoint/restore-start) go straight
  to the container's ``containerd-shim-grit-tpu-v1`` over its TTRPC socket
  (:mod:`grit_tpu.runtime.ttrpc`) — where the reference loads a containerd
  client and calls the forked shim through containerd's task service, we
  skip the middleman; the shim is ours.
- **rootfs rw-layer diff** is read from the overlayfs ``upperdir`` of the
  container's rootfs mount (found via ``/proc/self/mountinfo``), the same
  bytes the reference obtains through the snapshotter's Diff service
  (runtime.go:188-224) without needing containerd's private snapshot DB.

Implements the same protocol surface as
:class:`grit_tpu.cri.runtime.FakeRuntime`, so
:func:`grit_tpu.agent.checkpoint.run_checkpoint` drives either untouched.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from dataclasses import dataclass

import grpc

from grit_tpu.api import config
from grit_tpu.cri import cripb
from grit_tpu.cri.rootfs_diff import add_upperdir_to_tar, write_upperdir_diff
from grit_tpu.cri.runtime import (
    CONTAINER_NAME_LABEL,
    POD_NAME_LABEL,
    POD_NAMESPACE_LABEL,
    POD_UID_LABEL,
    Container,
    OciSpec,
    Task,
    TaskState,
)
from grit_tpu.runtime.ttrpc import ShimTaskClient

RUNTIME_SERVICE = "/runtime.v1.RuntimeService/"

DEFAULT_CRI_ENDPOINT = "unix:///run/containerd/containerd.sock"
DEFAULT_SHIM_SOCKET_DIR = config.SHIM_SOCKET_DIR.default


class CriError(RuntimeError):
    pass


@dataclass
class _Method:
    name: str
    request_cls: type
    response_cls: type


_METHODS = {
    m.name: m
    for m in (
        _Method("Version", cripb.VersionRequest, cripb.VersionResponse),
        _Method("ListPodSandbox", cripb.ListPodSandboxRequest,
                cripb.ListPodSandboxResponse),
        _Method("PodSandboxStatus", cripb.PodSandboxStatusRequest,
                cripb.PodSandboxStatusResponse),
        _Method("ListContainers", cripb.ListContainersRequest,
                cripb.ListContainersResponse),
        _Method("ContainerStatus", cripb.ContainerStatusRequest,
                cripb.ContainerStatusResponse),
        _Method("StopContainer", cripb.StopContainerRequest,
                cripb.StopContainerResponse),
    )
}


class CriClient:
    """Thin unary gRPC client for runtime.v1.RuntimeService (no generated
    stubs needed — methods are addressed by path)."""

    def __init__(self, endpoint: str = DEFAULT_CRI_ENDPOINT,
                 timeout: float = 30.0) -> None:
        self.endpoint = endpoint
        self.timeout = timeout
        self._channel = grpc.insecure_channel(endpoint)
        self._calls = {
            name: self._channel.unary_unary(
                RUNTIME_SERVICE + name,
                request_serializer=m.request_cls.SerializeToString,
                response_deserializer=m.response_cls.FromString,
            )
            for name, m in _METHODS.items()
        }

    def close(self) -> None:
        self._channel.close()

    def call(self, name: str, request):
        try:
            return self._calls[name](request, timeout=self.timeout)
        except grpc.RpcError as exc:
            raise CriError(
                f"CRI {name} failed: {exc.code().name}: {exc.details()}"
            ) from exc

    def version(self) -> cripb.VersionResponse:
        return self.call("Version", cripb.VersionRequest(version="v1"))


def parse_mountinfo_upperdir(mountinfo: str, rootfs: str) -> str | None:
    """Find the overlay ``upperdir=`` for the mount at ``rootfs`` in a
    ``/proc/*/mountinfo`` text (fields: ... mountpoint ... - fstype source
    super_options)."""

    rootfs = rootfs.rstrip("/")
    for line in mountinfo.splitlines():
        parts = line.split(" - ")
        if len(parts) != 2:
            continue
        pre, post = parts
        pre_fields = pre.split()
        if len(pre_fields) < 5 or pre_fields[4].rstrip("/") != rootfs:
            continue
        post_fields = post.split()
        if not post_fields or not post_fields[0].startswith("overlay"):
            continue
        for opt in post_fields[-1].split(","):
            if opt.startswith("upperdir="):
                return opt[len("upperdir="):]
    return None


class GrpcCriRuntime:
    """FakeRuntime-protocol adapter over a live CRI endpoint + shim sockets."""

    def __init__(
        self,
        cri_endpoint: str = DEFAULT_CRI_ENDPOINT,
        shim_socket_dir: str | None = None,
        containerd_namespace: str = "k8s.io",
        timeout: float = 30.0,
        upperdir_resolver=None,
        mountinfo_path: str | None = None,
    ) -> None:
        self.cri = CriClient(cri_endpoint, timeout=timeout)
        self.shim_socket_dir = shim_socket_dir or config.SHIM_SOCKET_DIR.get()
        self.containerd_namespace = containerd_namespace
        self._upperdir_resolver = upperdir_resolver
        # Container rootfs overlays live in the HOST mount namespace; in
        # the agent Job pod (hostPID: true, chart agent-config.yaml) that
        # is /proc/1/mountinfo — /proc/self/mountinfo only shows the
        # agent's own namespace and can never resolve an upperdir.
        if mountinfo_path is None:
            mountinfo_path = config.HOST_MOUNTINFO.get()
        if not mountinfo_path:
            mountinfo_path = (
                "/proc/1/mountinfo"
                if os.access("/proc/1/mountinfo", os.R_OK)
                else "/proc/self/mountinfo"
            )
        self._mountinfo_path = mountinfo_path
        # container id → sandbox id (for shim-socket fallback + log dirs)
        self._sandbox_of: dict[str, str] = {}
        self._sandboxes: dict[str, cripb.PodSandbox] = {}

    def close(self) -> None:
        self.cri.close()

    # -- shim plumbing ----------------------------------------------------------

    def shim_socket(self, container_id: str) -> str:
        """The task socket for this container's shim. Our shim names its
        socket ``<dir>/<containerd-ns>-<shim-id>.sock`` (native/shim/
        main.cc SocketPath); without pod grouping the shim id is the
        container id, with grouping it is the sandbox id — try both."""

        mine = os.path.join(
            self.shim_socket_dir,
            f"{self.containerd_namespace}-{container_id}.sock",
        )
        if os.path.exists(mine):
            return mine
        sandbox = self._sandbox_of.get(container_id, "")
        grouped = os.path.join(
            self.shim_socket_dir,
            f"{self.containerd_namespace}-{sandbox}.sock",
        )
        if sandbox and os.path.exists(grouped):
            return grouped
        raise CriError(
            f"no shim socket for container {container_id} under "
            f"{self.shim_socket_dir}"
        )

    def _shim(self, container_id: str) -> ShimTaskClient:
        return ShimTaskClient(self.shim_socket(container_id))

    # -- CRI surface (FakeRuntime protocol) -------------------------------------

    def list_containers(self, pod_name: str, pod_namespace: str,
                        state: TaskState | None = TaskState.RUNNING,
                        ) -> list[Container]:
        """CRI ListContainers filtered by pod labels + state — the same
        label filter the reference uses (runtime.go:46-57)."""

        req = cripb.ListContainersRequest()
        req.filter.label_selector[POD_NAME_LABEL] = pod_name
        req.filter.label_selector[POD_NAMESPACE_LABEL] = pod_namespace
        if state is not None:
            req.filter.state.state = _to_cri_state(state)
        resp = self.cri.call("ListContainers", req)

        out = []
        for c in resp.containers:
            self._sandbox_of[c.id] = c.pod_sandbox_id
            spec = OciSpec(image=c.image.image,
                           annotations=dict(c.annotations))
            out.append(Container(
                id=c.id,
                sandbox_id=c.pod_sandbox_id,
                name=c.metadata.name or c.labels.get(CONTAINER_NAME_LABEL, ""),
                spec=spec,
                labels=dict(c.labels),
            ))
        return out

    def load_container(self, container_id: str) -> Container:
        resp = self.cri.call(
            "ContainerStatus",
            cripb.ContainerStatusRequest(container_id=container_id),
        )
        st = resp.status
        self._sandbox_of.setdefault(container_id, "")
        return Container(
            id=st.id,
            sandbox_id=self._sandbox_of.get(container_id, ""),
            name=st.metadata.name,
            spec=OciSpec(image=st.image.image,
                         annotations=dict(st.annotations)),
            labels=dict(st.labels),
        )

    def get_task(self, container_id: str) -> Task:
        """Task view with the init pid. The pid comes from the verbose
        ContainerStatus ``info`` blob (the JSON containerd attaches, the
        same place ``crictl inspect`` reads it). A running container with
        no recoverable pid is an error, not pid=0 — the device hook keys
        off the pid, and silently skipping the HBM dump would produce a
        checkpoint that restores to a diverged workload."""

        resp = self.cri.call(
            "ContainerStatus",
            cripb.ContainerStatusRequest(container_id=container_id,
                                         verbose=True),
        )
        pid = 0
        try:
            pid = int(json.loads(resp.info.get("info", "")).get("pid", 0))
        except Exception:  # noqa: BLE001 - any malformed blob → strict below
            pid = 0
        if pid <= 0 and resp.status.state == cripb.CONTAINER_RUNNING:
            raise CriError(
                f"running container {container_id} has no init pid in its "
                "verbose ContainerStatus info — cannot drive device hooks"
            )
        state_map = {
            cripb.CONTAINER_CREATED: TaskState.CREATED,
            cripb.CONTAINER_RUNNING: TaskState.RUNNING,
            cripb.CONTAINER_EXITED: TaskState.STOPPED,
        }
        return Task(
            container_id=container_id,
            pid=pid,
            state=state_map.get(resp.status.state, TaskState.STOPPED),
        )

    # -- task ops (via the shim) ------------------------------------------------

    def pause(self, container_id: str) -> None:
        with self._shim(container_id) as shim:
            shim.pause(container_id)

    def resume(self, container_id: str) -> None:
        with self._shim(container_id) as shim:
            shim.resume(container_id)

    def checkpoint_task(self, container_id: str, image_path: str,
                        work_dir: str) -> None:
        """CRIU dump via the shim (→ runc checkpoint). The shim owns the
        criu work dir and embeds the dump.log tail in any error; we mirror
        the outcome into ``work_dir`` for the agent's artifact layout."""

        os.makedirs(work_dir, exist_ok=True)
        with self._shim(container_id) as shim:
            shim.checkpoint(container_id, image_path)
        with open(os.path.join(work_dir, "dump.log"), "w") as f:
            f.write(f"criu dump ok (shim-managed) container={container_id}\n")

    def restore_task(self, container_id: str, image_path: str) -> Task:
        """Start a created-checkpoint container (the shim rewrote its
        create; Start executes the restore). On a k8s node kubelet issues
        this Start — the agent only needs it for node-local recovery."""

        del image_path  # the shim already knows its restore source
        with self._shim(container_id) as shim:
            resp = shim.start(container_id)
        return Task(container_id=container_id, pid=resp.pid,
                    state=TaskState.RUNNING)

    def kill_task(self, container_id: str) -> None:
        """CRI StopContainer with timeout 0 (immediate SIGKILL) — the
        teardown the manager's migration flow performs on the source pod."""

        self.cri.call(
            "StopContainer",
            cripb.StopContainerRequest(container_id=container_id, timeout=0),
        )

    # -- snapshotter (rootfs diff) ----------------------------------------------

    def rootfs_upperdir(self, container_id: str) -> str:
        """The overlayfs rw layer of this container's rootfs."""

        if self._upperdir_resolver is not None:
            return self._upperdir_resolver(container_id)
        bundle_rootfs = os.path.join(
            "/run/containerd/io.containerd.runtime.v2.task",
            self.containerd_namespace, container_id, "rootfs",
        )
        with open(self._mountinfo_path) as f:
            upper = parse_mountinfo_upperdir(f.read(), bundle_rootfs)
        if not upper:
            raise CriError(
                f"cannot locate overlay upperdir for {container_id} "
                f"(rootfs {bundle_rootfs})"
            )
        return upper

    def write_rootfs_diff(self, container_id: str, dest_path: str) -> int:
        """Stream the rw layer as an OCI layer tar (whiteouts, empty dirs,
        opaque markers — :mod:`grit_tpu.cri.rootfs_diff`) straight to
        ``dest_path``: a multi-GB upperdir must not transit agent memory
        while the pod is paused. Matches the snapshotter Diff export the
        reference performs (runtime.go:188-224)."""

        return write_upperdir_diff(self.rootfs_upperdir(container_id),
                                   dest_path)

    def export_rootfs_diff(self, container_id: str) -> bytes:
        """In-memory variant of :meth:`write_rootfs_diff` — convenience
        for small layers/tests; the checkpoint driver uses the streaming
        form."""

        upper = self.rootfs_upperdir(container_id)
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            add_upperdir_to_tar(tar, upper)
        return buf.getvalue()

    # -- kubelet log helpers ----------------------------------------------------

    def container_log_dir(self, container_id: str) -> str:
        """Kubelet convention: /var/log/pods/<ns>_<pod>_<uid>/<name>."""

        c = self.load_container(container_id)
        ns = c.labels.get(POD_NAMESPACE_LABEL, "default")
        pod = c.labels.get(POD_NAME_LABEL, "")
        uid = c.labels.get(POD_UID_LABEL, "")
        return os.path.join("/var/log/pods", f"{ns}_{pod}_{uid}", c.name)


def _to_cri_state(state: TaskState) -> int:
    return {
        TaskState.CREATED: cripb.CONTAINER_CREATED,
        TaskState.RUNNING: cripb.CONTAINER_RUNNING,
        TaskState.PAUSED: cripb.CONTAINER_RUNNING,  # CRI has no paused
        TaskState.STOPPED: cripb.CONTAINER_EXITED,
    }[state]
