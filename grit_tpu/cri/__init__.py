"""Container-runtime integration layer.

``runtime.py`` defines the runtime interface the agent and shim drive
(containers, tasks, pause/resume/checkpoint, snapshotter diffs) plus an
in-process fake implementation — the fake CRI/containerd the reference never
had (SURVEY §4: "no fixtures/mocks/fake backends"). A real containerd
adapter implements the same interface over the containerd gRPC socket
(see deploy/containerd/ for the node wiring).
"""

from grit_tpu.cri.runtime import (  # noqa: F401
    Container,
    FakeRuntime,
    OciSpec,
    Sandbox,
    SimProcess,
    Task,
    TaskState,
)
