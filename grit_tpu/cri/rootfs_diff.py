"""OCI rootfs rw-layer diff: overlayfs upperdir → layer tar, streamed.

The byte format follows the OCI image-layer conventions the reference
obtains from containerd's snapshotter Diff service (runtime.go:188-224):

- regular files / symlinks / hardlinks are archived as-is;
- directories are archived (so empty dirs survive the round-trip);
- overlayfs deletion whiteouts (0:0 character devices in the upperdir)
  become ``.wh.<name>`` marker entries;
- an opaque directory (``trusted.overlay.opaque=y`` xattr) gets a
  ``.wh..wh..opq`` entry so the restore side clears it first.

Streaming: the tar is written straight to its destination file — a
multi-GB rw layer must never be buffered in the agent's memory while the
pod is paused (advisor r3 finding).
"""

from __future__ import annotations

import os
import stat
import tarfile

OPAQUE_MARKER = ".wh..wh..opq"
WHITEOUT_PREFIX = ".wh."


def _is_whiteout(full: str) -> bool:
    st = os.lstat(full)
    return stat.S_ISCHR(st.st_mode) and st.st_rdev == 0


def _is_opaque(full: str) -> bool:
    try:
        return os.getxattr(full, "trusted.overlay.opaque",
                           follow_symlinks=False) == b"y"
    except OSError:
        return False


def add_upperdir_to_tar(tar: tarfile.TarFile, upper: str) -> int:
    """Archive ``upper`` as an OCI layer into an open tar; returns the
    number of entries written."""

    entries = 0
    for root, dirs, files in os.walk(upper):
        dirs.sort()
        rel_root = os.path.relpath(root, upper)
        for d in dirs:
            full = os.path.join(root, d)
            rel = os.path.normpath(os.path.join(rel_root, d))
            tar.add(full, arcname=rel, recursive=False)
            entries += 1
            if _is_opaque(full):
                info = tarfile.TarInfo(os.path.join(rel, OPAQUE_MARKER))
                info.size = 0
                tar.addfile(info)
                entries += 1
        for name in sorted(files):
            full = os.path.join(root, name)
            rel = os.path.normpath(os.path.join(rel_root, name))
            if _is_whiteout(full):
                marker = os.path.join(os.path.dirname(rel),
                                      WHITEOUT_PREFIX + name)
                info = tarfile.TarInfo(os.path.normpath(marker))
                info.size = 0
                tar.addfile(info)
            else:
                tar.add(full, arcname=rel, recursive=False)
            entries += 1
    return entries


def write_upperdir_diff(upper: str, dest_path: str) -> int:
    """Stream the layer tar for ``upper`` to ``dest_path`` (O(1) memory);
    returns the tar's size in bytes."""

    tmp = dest_path + ".tmp"
    with tarfile.open(tmp, "w") as tar:
        add_upperdir_to_tar(tar, upper)
    os.replace(tmp, dest_path)
    return os.path.getsize(dest_path)


def apply_names(names_to_content: dict[str, bytes],
                member_name: str, content: bytes | None) -> None:
    """Apply one layer entry to a flat path→bytes view of a rootfs — the
    in-memory applier FakeRuntime uses (mirrors containerd's applier
    semantics for whiteouts/opaque markers)."""

    norm = os.path.normpath(member_name)
    base = os.path.basename(norm)
    parent = os.path.dirname(norm)
    if base == OPAQUE_MARKER:
        prefix = parent + "/" if parent else ""
        for key in [k for k in names_to_content
                    if k.startswith(prefix) and k != norm]:
            del names_to_content[key]
        return
    if base.startswith(WHITEOUT_PREFIX):
        victim = os.path.normpath(
            os.path.join(parent, base[len(WHITEOUT_PREFIX):]))
        names_to_content.pop(victim, None)
        # A whiteout on a directory removes everything under it.
        for key in [k for k in names_to_content
                    if k.startswith(victim + "/")]:
            del names_to_content[key]
        return
    if content is not None:
        names_to_content[norm] = content
