"""minicriu adapter — process C/R over the in-tree engine.

The agent's host-process freeze normally delegates to real CRIU
(:mod:`grit_tpu.cri.criu` — reference ``process/init.go:425-452``). When
no criu binary exists (this dev/CI image cannot install one),
``native/minicriu`` supplies the same dump → SIGKILL → restore capability
from first principles: ptrace seize, /proc/pid/mem page extraction,
parasite-page remote syscalls on restore. This adapter plugs it into the
identical :class:`~grit_tpu.cri.criu.CriuProcessRuntime` surface, so the
agent driver, harness, and tests run the SAME flow against either engine
— and the live continuity e2e (tests/test_minicriu.py) executes in every
environment instead of skipping when criu is absent.

Engine scope (enforced by the binary, documented in minicriu.cc): x86_64
targets — including multi-threaded ones (per-tid seize on dump, remote
clone + per-thread register/rseq install on restore) —
private/read-only-shared mappings, regular-file fds, ASLR-off workloads
(use :func:`run_workload`).
"""

from __future__ import annotations

import os
import platform
import subprocess

from grit_tpu.api import config
from grit_tpu.cri.criu import CriuProcessRuntime
from grit_tpu.cri.runtime import Task, TaskState

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
MINICRIU_BIN = os.path.join(_REPO, "native", "build", "minicriu")
COUNTER_BIN = os.path.join(_REPO, "native", "build", "minicriu-counter")
COUNTER_MT_BIN = os.path.join(
    _REPO, "native", "build", "minicriu-counter-mt")


_PROBE: bool | None = None


def minicriu_available() -> bool:
    """True when the engine can actually operate here: right platform,
    built binary, AND a kernel/sandbox that lets ``run`` establish the
    ASLR-off contract (seccomp-filtered environments reject the
    personality(2) call, in which case every dump would target a
    relocated tree — skip, don't flail)."""
    global _PROBE
    if not (
        platform.system() == "Linux"
        and platform.machine() == "x86_64"
        and os.access(MINICRIU_BIN, os.X_OK)
    ):
        return False
    if _PROBE is None:
        try:
            _PROBE = subprocess.run(
                [MINICRIU_BIN, "run", "--", "/bin/true"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                timeout=10,
            ).returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            # Transient (loaded box, EINTR): report unavailable NOW but
            # leave the cache unset so a later call re-probes — only a
            # definitive exit status is worth remembering.
            return False
    return _PROBE


class MiniCriuError(RuntimeError):
    def __init__(self, action: str, rc: int, detail: str) -> None:
        super().__init__(f"minicriu {action} failed (rc {rc}): {detail}")
        self.action = action
        self.rc = rc


def run_workload(argv: list[str], **popen_kwargs) -> subprocess.Popen:
    """Launch a workload under the engine's ASLR-off contract."""
    return subprocess.Popen([MINICRIU_BIN, "run", "--", *argv],
                            **popen_kwargs)


class MiniCriuProcessRuntime(CriuProcessRuntime):
    """CriuProcessRuntime with the dump/restore legs on minicriu.

    pause/resume/kill/attach and all CRI bookkeeping are inherited — the
    agent's consistent-cut sequence is engine-agnostic.
    """

    def __init__(self, minicriu_bin: str | None = None,
                 log_root: str = "/tmp/grit-minicriu-logs") -> None:
        super().__init__(criu_bin="criu", log_root=log_root)
        self.minicriu_bin = minicriu_bin or MINICRIU_BIN

    def _run(self, action: str, args: list[str]) -> str:
        # Same ceiling as a real criu invocation: a wedged engine (stuck
        # D-state target, unkillable tracee) must fail inside the phase
        # deadline, not pin the agent Job forever.
        try:
            proc = subprocess.run([self.minicriu_bin, action, *args],
                                  capture_output=True, text=True,
                                  timeout=config.CRIU_TIMEOUT_S.get())
        except subprocess.TimeoutExpired as exc:
            raise MiniCriuError(
                action, -1,
                f"timed out after {config.CRIU_TIMEOUT_S.get():.0f}s"
            ) from exc
        if proc.returncode != 0:
            raise MiniCriuError(action, proc.returncode,
                                proc.stderr.strip()[-500:])
        return proc.stdout

    def checkpoint_task(self, container_id: str, image_path: str,
                        work_dir: str) -> None:
        """Dump the paused task; like criu --leave-stopped, the process
        stays stopped afterwards (the driver decides resume vs kill)."""
        task = self.tasks[container_id]
        if task.state != TaskState.PAUSED:
            raise RuntimeError(
                f"checkpoint requires paused task ({task.state})")
        os.makedirs(image_path, exist_ok=True)
        os.makedirs(work_dir, exist_ok=True)
        self._run("dump", ["--pid", str(task.pid), "--images", image_path])

    def restore_task(self, container_id: str, image_path: str) -> Task:
        out = self._run("restore", ["--images", image_path])
        pid = 0
        for line in out.splitlines():
            if line.startswith("pid "):
                pid = int(line.split()[1])
        if pid <= 0:
            raise MiniCriuError("restore", 0, f"no pid in output: {out!r}")
        task = self.tasks[container_id]
        task.pid = pid
        # minicriu's restore detaches a RUNNING process (no --leave-stopped
        # half on this side); the inherited SIGCONT contract is a no-op.
        task.state = TaskState.RUNNING
        return task
