"""Runtime model: sandboxes, containers, tasks, and a checkpointable
simulated process.

The shapes mirror what the reference drives through the containerd client
(``pkg/gritagent/checkpoint/runtime.go``: CRI ListContainers → LoadContainer
→ task.Pause → task.Checkpoint → snapshotter diff) and what its forked shim
manages (``cmd/containerd-shim-grit-v1/``). The fake's ``checkpoint`` writes
a CRIU-image-shaped directory (``pages-1.img`` + ``process-state.json``) so
every layer above — agent, data mover, interceptor, shim restore — handles
real files with the real layout.

``SimProcess`` stands in for the workload (a training loop with a step
counter and dirty memory); on real nodes the same interfaces are implemented
by containerd + runc/CRIU, with the TPU device hook layered at the shim
(see :mod:`grit_tpu.runtime.shim`).
"""

from __future__ import annotations

import enum
import io
import json
import os
import tarfile
import threading
from dataclasses import dataclass, field

# Kubernetes CRI labels containerd attaches to containers
# (used by the agent's ListContainers filter, reference runtime.go:46-57).
POD_NAME_LABEL = "io.kubernetes.pod.name"
POD_NAMESPACE_LABEL = "io.kubernetes.pod.namespace"
POD_UID_LABEL = "io.kubernetes.pod.uid"
CONTAINER_NAME_LABEL = "io.kubernetes.container.name"

# OCI annotation distinguishing sandbox vs workload containers — the shim
# only rewrites creates for container-type "container"
# (reference checkpoint_util.go:65-68).
CONTAINER_TYPE_ANNOTATION = "io.kubernetes.cri.container-type"

PAGES_IMG = "pages-1.img"
PROCESS_STATE = "process-state.json"


class TaskState(str, enum.Enum):
    CREATED = "created"
    RUNNING = "running"
    PAUSED = "paused"
    STOPPED = "stopped"


class SimProcess:
    """A checkpointable simulated workload: step counter + memory image.

    ``dump()/load()`` round-trip the full state so a restored process
    continues exactly where the dump froze it — the property the
    loss-parity harness checks end-to-end.
    """

    def __init__(self, memory_size: int = 4096, seed: int = 0) -> None:
        self.step = 0
        self.memory = bytearray(memory_size)
        self._seed = seed
        self.lock = threading.Lock()

    def run_steps(self, n: int) -> None:
        with self.lock:
            for _ in range(n):
                self.step += 1
                # Deterministic "training": memory evolves as a function of
                # step so divergence is detectable byte-for-byte.
                idx = (self.step * 31 + self._seed) % len(self.memory)
                self.memory[idx] = (self.memory[idx] + self.step) % 256

    def dump(self) -> tuple[bytes, bytes]:
        with self.lock:
            state = json.dumps({"step": self.step, "seed": self._seed,
                                "memory_size": len(self.memory)}).encode()
            return state, bytes(self.memory)

    @classmethod
    def load(cls, state: bytes, pages: bytes) -> SimProcess:
        meta = json.loads(state)
        proc = cls(memory_size=meta["memory_size"], seed=meta["seed"])
        proc.step = meta["step"]
        proc.memory = bytearray(pages)
        return proc


@dataclass
class OciSpec:
    """The slice of an OCI runtime spec the shim reads: annotations + image.
    (reference runc/checkpoint_util.go:59-78 reads annotations out of
    config.json)."""

    image: str = ""
    args: list[str] = field(default_factory=list)
    annotations: dict[str, str] = field(default_factory=dict)
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class Sandbox:
    id: str = ""
    pod_name: str = ""
    pod_namespace: str = "default"
    pod_uid: str = ""
    annotations: dict[str, str] = field(default_factory=dict)
    log_dir: str = ""  # kubelet pod log dir for this sandbox


@dataclass
class Container:
    id: str = ""
    sandbox_id: str = ""
    name: str = ""
    spec: OciSpec = field(default_factory=OciSpec)
    labels: dict[str, str] = field(default_factory=dict)
    # rootfs upper (rw) layer: rel-path → content. The snapshotter diff
    # exports exactly this (reference writeRootFsDiffTar runtime.go:188-224).
    rootfs_upper: dict[str, bytes] = field(default_factory=dict)


@dataclass
class Task:
    container_id: str = ""
    pid: int = 0
    state: TaskState = TaskState.CREATED
    process: SimProcess | None = None


class FakeRuntime:
    """In-process containerd+CRI fake with real on-disk checkpoint images."""

    def __init__(self, log_root: str = "/tmp/grit-fake-logs") -> None:
        self.sandboxes: dict[str, Sandbox] = {}
        self.containers: dict[str, Container] = {}
        self.tasks: dict[str, Task] = {}
        self.pulled_images: set[str] = set()
        self.log_root = log_root
        self._pid = 1000
        self._lock = threading.Lock()

    # -- setup helpers ----------------------------------------------------------

    def add_sandbox(self, sandbox: Sandbox) -> Sandbox:
        if not sandbox.log_dir:
            sandbox.log_dir = os.path.join(
                self.log_root,
                f"{sandbox.pod_namespace}_{sandbox.pod_name}_{sandbox.pod_uid}",
            )
        self.sandboxes[sandbox.id] = sandbox
        return sandbox

    def add_container(self, container: Container, process: SimProcess | None = None,
                      running: bool = True) -> Container:
        container.spec.annotations.setdefault(CONTAINER_TYPE_ANNOTATION, "container")
        sandbox = self.sandboxes[container.sandbox_id]
        container.labels.setdefault(POD_NAME_LABEL, sandbox.pod_name)
        container.labels.setdefault(POD_NAMESPACE_LABEL, sandbox.pod_namespace)
        container.labels.setdefault(POD_UID_LABEL, sandbox.pod_uid)
        container.labels.setdefault(CONTAINER_NAME_LABEL, container.name)
        self.containers[container.id] = container
        with self._lock:
            self._pid += 1
            pid = self._pid
        self.tasks[container.id] = Task(
            container_id=container.id, pid=pid,
            state=TaskState.RUNNING if running else TaskState.CREATED,
            process=process or SimProcess(),
        )
        return container

    # -- CRI surface (agent side) -----------------------------------------------

    def list_containers(self, pod_name: str, pod_namespace: str,
                        state: TaskState | None = TaskState.RUNNING) -> list[Container]:
        """CRI ListContainers filtered by pod labels + state
        (reference runtime.go:46-57)."""

        out = []
        for c in self.containers.values():
            if c.labels.get(POD_NAME_LABEL) != pod_name:
                continue
            if c.labels.get(POD_NAMESPACE_LABEL) != pod_namespace:
                continue
            if state is not None and self.tasks[c.id].state != state:
                continue
            out.append(c)
        return out

    def load_container(self, container_id: str) -> Container:
        return self.containers[container_id]

    def get_task(self, container_id: str) -> Task:
        return self.tasks[container_id]

    # -- task ops ---------------------------------------------------------------

    def pause(self, container_id: str) -> None:
        task = self.tasks[container_id]
        if task.state != TaskState.RUNNING:
            raise RuntimeError(f"task {container_id} not running ({task.state})")
        task.state = TaskState.PAUSED

    def resume(self, container_id: str) -> None:
        task = self.tasks[container_id]
        if task.state != TaskState.PAUSED:
            raise RuntimeError(f"task {container_id} not paused ({task.state})")
        task.state = TaskState.RUNNING

    def checkpoint_task(self, container_id: str, image_path: str,
                        work_dir: str) -> None:
        """Dump the task's process into a CRIU-image-shaped directory
        (reference writeCriuCheckpoint runtime.go:177-186 → shim
        service.Checkpoint → runc checkpoint). The task must be paused —
        matching the agent's pause-before-checkpoint sequence."""

        task = self.tasks[container_id]
        if task.state != TaskState.PAUSED:
            raise RuntimeError(f"checkpoint requires paused task ({task.state})")
        os.makedirs(image_path, exist_ok=True)
        os.makedirs(work_dir, exist_ok=True)
        state, pages = task.process.dump()
        with open(os.path.join(image_path, PROCESS_STATE), "wb") as f:
            f.write(state)
        with open(os.path.join(image_path, PAGES_IMG), "wb") as f:
            f.write(pages)
        with open(os.path.join(work_dir, "dump.log"), "w") as f:
            f.write(f"criu dump ok pid={task.pid}\n")

    def restore_task(self, container_id: str, image_path: str) -> Task:
        """Recreate a task's process from a checkpoint image
        (reference init_state.go:147-192 → runc restore)."""

        with open(os.path.join(image_path, PROCESS_STATE), "rb") as f:
            state = f.read()
        with open(os.path.join(image_path, PAGES_IMG), "rb") as f:
            pages = f.read()
        task = self.tasks[container_id]
        task.process = SimProcess.load(state, pages)
        task.state = TaskState.RUNNING
        return task

    def kill_task(self, container_id: str) -> None:
        self.tasks[container_id].state = TaskState.STOPPED

    # -- snapshotter (rootfs diff) ----------------------------------------------

    def export_rootfs_diff(self, container_id: str) -> bytes:
        """Snapshotter+DiffService export of the rw layer as a tar
        (reference writeRootFsDiffTar runtime.go:188-224)."""

        container = self.containers[container_id]
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            for rel, content in sorted(container.rootfs_upper.items()):
                info = tarfile.TarInfo(rel)
                info.size = len(content)
                tar.addfile(info, io.BytesIO(content))
        return buf.getvalue()

    def write_rootfs_diff(self, container_id: str, dest_path: str) -> int:
        """Streaming-form export used by the checkpoint driver (the real
        adapter streams a multi-GB upperdir; here the layer is in-memory
        anyway)."""

        data = self.export_rootfs_diff(container_id)
        with open(dest_path, "wb") as f:
            f.write(data)
        return len(data)

    def apply_rootfs_diff(self, container_id: str, tar_bytes: bytes) -> None:
        """Apply a layer tar onto a container's rootfs, honoring OCI
        whiteout/opaque markers (restore side, reference
        container.go:139-172; marker semantics in
        :mod:`grit_tpu.cri.rootfs_diff`)."""

        from grit_tpu.cri.rootfs_diff import apply_names

        container = self.containers[container_id]
        with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
            for member in tar.getmembers():
                if member.isdir():
                    continue
                content = (tar.extractfile(member).read()
                           if member.isfile() else None)
                apply_names(container.rootfs_upper, member.name, content)

    # -- kubelet log helpers ----------------------------------------------------

    def container_log_dir(self, container_id: str) -> str:
        container = self.containers[container_id]
        sandbox = self.sandboxes[container.sandbox_id]
        return os.path.join(sandbox.log_dir, container.name)

    def write_container_log(self, container_id: str, filename: str, text: str) -> str:
        log_dir = self.container_log_dir(container_id)
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, filename)
        with open(path, "a") as f:
            f.write(text)
        return path
