"""protoc-generated CRI runtime.v1 messages (source of truth:
``grit_tpu/cri/proto/cri_runtime.proto``; regenerate via
``make -C native proto``)."""

import os as _os
import sys as _sys

_here = _os.path.dirname(_os.path.abspath(__file__))
if _here not in _sys.path:
    _sys.path.insert(0, _here)

from cri_runtime_pb2 import *  # noqa: F401,F403,E402
