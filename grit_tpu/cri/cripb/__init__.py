"""protoc-generated CRI runtime.v1 messages (source of truth:
``grit_tpu/cri/proto/cri_runtime.proto``; regenerate via
``make -C native proto``)."""

from grit_tpu.cri.cripb.cri_runtime_pb2 import *  # noqa: F401,F403
