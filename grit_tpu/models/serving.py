"""Stateful serving engine — BASELINE config 5 (live-KV-cache restore).

An inference pod differs from a training pod in what must survive
migration: not an optimizer, but the **decode state** — KV cache contents,
sequence positions, sampler RNG, and the tokens emitted so far. This engine
keeps all of that in one pytree (``engine.state``) so the generic snapshot
machinery migrates a generation mid-stream: restore on another host and the
next sampled token is bit-identical to the uninterrupted run.

The decode step is a single compiled program reused for every token
(static shapes: fixed batch, cache length = ``max_seq_len``); prefill is a
second program per prompt-bucket length. Sampling is greedy or
temperature-based via the state RNG.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from grit_tpu.device import quiesce, restore_snapshot, write_snapshot
from grit_tpu.models import llama
from grit_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P

# KV cache leaves: (L, B, max_seq, kv_heads, hd) — batch over data axes,
# kv heads over model axis (matches the attention weights' tp split).
KV_CACHE_RULES = ShardingRules(
    rules=[
        (r"cache/(k|v)$", P(None, ("data", "fsdp"), None, "model", None)),
    ],
    default=P(),
)


@dataclass(frozen=True)
class ServingConfig:
    batch_size: int = 1
    max_seq_len: int = 1024
    temperature: float = 0.0  # 0 → greedy
    seed: int = 0


class InferenceEngine:
    """Owns params (frozen) + mutable decode state (migratable pytree).

    ``mesh`` shards the KV cache per :data:`KV_CACHE_RULES` (kv heads over
    the model axis, batch over the data axes) and replicates the small
    scalars; without a mesh everything is single-device.
    """

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params: dict,
        scfg: ServingConfig | None = None,
        mesh=None,
    ) -> None:
        # Opt into the persistent compilation cache (env-gated no-op):
        # prefill/decode compiles populate it so snapshots carry it, and
        # a restored engine's recompile becomes a cache hit (hook.py).
        from grit_tpu.device.hook import (  # noqa: PLC0415
            enable_compile_cache_from_env,
        )

        enable_compile_cache_from_env()
        self.cfg = cfg
        self.scfg = scfg or ServingConfig()
        self.params = params
        self.mesh = mesh
        # Family dispatch: MoE configs decode through moe_llama (same
        # cache layout, expert feed-forward); dense configs through
        # llama. Both run the identical serving-step plumbing
        # (llama.decode parameterized over the FFN).
        from grit_tpu.models import moe_llama as _moe  # noqa: PLC0415

        self._decode_fn = (
            # mesh bound here so the expert-activation sharding
            # constraints are live in the jitted step (advisor finding).
            partial(_moe.decode, mesh=mesh)
            if isinstance(cfg, _moe.MoeLlamaConfig)
            else llama.decode
        )
        self._state_shardings = None
        if mesh is not None:
            abstract = jax.eval_shape(self._fresh_state)
            self._state_shardings = KV_CACHE_RULES.tree_shardings(abstract, mesh)
        self.state = self._make_state()
        # Host-side mirror of cache['length'] so capacity is enforced
        # without a per-token device sync; resynced on restore.
        self._cache_len = 0
        # One compiled program per token: decode + sample + state update all
        # inside jit — no per-token host round-trip on the logits.
        self._step = jax.jit(
            partial(_decode_and_sample, self._decode_fn, cfg,
                    self.scfg.temperature)
        )

    def _fresh_state(self) -> dict:
        s = self.scfg
        return {
            "cache": llama.init_kv_cache(self.cfg, s.batch_size, s.max_seq_len),
            "last_token": jnp.zeros((s.batch_size, 1), jnp.int32),
            "rng": jax.random.PRNGKey(s.seed),
            "n_generated": jnp.zeros((), jnp.int32),
        }

    def _make_state(self) -> dict:
        if self._state_shardings is None:
            return self._fresh_state()
        return jax.jit(self._fresh_state, out_shardings=self._state_shardings)()

    # -- generation -------------------------------------------------------------

    def _reserve(self, n: int) -> None:
        """Guard cache capacity: past ``max_seq_len``, dynamic_update_slice
        would silently clamp the write offset and corrupt the newest cache
        slots — fail loudly on the host instead."""
        if self._cache_len + n > self.scfg.max_seq_len:
            raise ValueError(
                f"KV cache overflow: {self._cache_len} + {n} tokens exceeds "
                f"max_seq_len={self.scfg.max_seq_len}"
            )
        self._cache_len += n

    def prefill(self, prompt: jax.Array) -> jax.Array:
        """Feed prompt (B, S); returns the first sampled token (B, 1)."""
        self._reserve(prompt.shape[1])
        tok, self.state = self._step(self.params, prompt, self.state)
        return tok

    def generate_step(self) -> jax.Array:
        """One autoregressive step from ``last_token``; returns (B, 1)."""
        self._reserve(1)
        tok, self.state = self._step(
            self.params, self.state["last_token"], self.state
        )
        return tok

    def generate(self, n_tokens: int) -> jax.Array:
        """Emit ``n_tokens`` from the current state; (B, n)."""
        out = []
        for _ in range(n_tokens):
            out.append(self.generate_step())
        return jnp.concatenate(out, axis=1)

    # -- migration --------------------------------------------------------------


    def snapshot(self, directory: str, *, barrier=lambda: None) -> str:
        """Dump decode state (not params — those ship with the pod image /
        checkpoint PV separately, exactly once, not per-migration)."""
        quiesce(self.state)
        return write_snapshot(
            directory,
            self.state,
            meta={"n_generated": int(self.state["n_generated"])},
            barrier=barrier,
        )

    def restore(self, directory: str, **kwargs) -> int:
        like = jax.eval_shape(self._fresh_state)
        kwargs.setdefault("mesh", self.mesh)
        kwargs.setdefault("shardings", self._state_shardings)
        self.state = restore_snapshot(directory, like=like, **kwargs)
        self._cache_len = int(self.state["cache"]["length"])
        return int(self.state["n_generated"])


def _decode_and_sample(
    decode_fn, cfg: llama.LlamaConfig, temperature: float, params: dict,
    tokens: jax.Array, state: dict,
) -> tuple[jax.Array, dict]:
    """Jitted decode+sample: one dispatch per token, no logits on the host."""
    logits, cache = decode_fn(cfg, params, tokens, state["cache"])
    last = logits[:, -1, :]
    if temperature <= 0.0:
        tok = jnp.argmax(last, axis=-1, keepdims=True).astype(jnp.int32)
    else:
        step_rng = jax.random.fold_in(state["rng"], state["n_generated"])
        tok = jax.random.categorical(step_rng, last / temperature)[
            :, None
        ].astype(jnp.int32)
    new_state = {
        "cache": cache,
        "last_token": tok,
        "rng": state["rng"],
        "n_generated": state["n_generated"] + 1,
    }
    return tok, new_state
