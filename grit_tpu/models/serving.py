"""Stateful serving engine — BASELINE config 5 (live-KV-cache restore).

An inference pod differs from a training pod in what must survive
migration: not an optimizer, but the **decode state** — KV cache contents,
sequence positions, sampler RNG, and the tokens emitted so far. This engine
keeps all of that in one pytree (``engine.state``) so the generic snapshot
machinery migrates a generation mid-stream: restore on another host and the
next sampled token is bit-identical to the uninterrupted run.

The decode step is a single compiled program reused for every token
(static shapes: fixed batch, cache length = ``max_seq_len``); prefill is a
second program per prompt-bucket length. Sampling is greedy or
temperature-based via the state RNG.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from grit_tpu.device import quiesce, restore_snapshot, write_snapshot
from grit_tpu.models import llama
from grit_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P

# KV cache leaves: (L, B, max_seq, kv_heads, hd) — batch over data axes,
# kv heads over model axis (matches the attention weights' tp split).
KV_CACHE_RULES = ShardingRules(
    rules=[
        (r"cache/(k|v)$", P(None, ("data", "fsdp"), None, "model", None)),
    ],
    default=P(),
)


@dataclass(frozen=True)
class ServingConfig:
    batch_size: int = 1
    max_seq_len: int = 1024
    temperature: float = 0.0  # 0 → greedy
    seed: int = 0


def _init_state(fresh_fn, mesh):
    """``(state, shardings)`` for an engine's decode state: KV-cache rules
    applied over ``mesh`` (slots on data axes, kv heads on model), or
    single-device when mesh is None. The single copy of this logic for
    both engines."""
    if mesh is None:
        return fresh_fn(), None
    shardings = KV_CACHE_RULES.tree_shardings(jax.eval_shape(fresh_fn), mesh)
    return jax.jit(fresh_fn, out_shardings=shardings)(), shardings


class InferenceEngine:
    """Owns params (frozen) + mutable decode state (migratable pytree).

    ``mesh`` shards the KV cache per :data:`KV_CACHE_RULES` (kv heads over
    the model axis, batch over the data axes) and replicates the small
    scalars; without a mesh everything is single-device.
    """

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params: dict,
        scfg: ServingConfig | None = None,
        mesh=None,
    ) -> None:
        # Opt into the persistent compilation cache (env-gated no-op):
        # prefill/decode compiles populate it so snapshots carry it, and
        # a restored engine's recompile becomes a cache hit (hook.py).
        from grit_tpu.device.hook import (  # noqa: PLC0415
            enable_compile_cache_from_env,
        )

        enable_compile_cache_from_env()
        self.cfg = cfg
        self.scfg = scfg or ServingConfig()
        self.params = params
        self.mesh = mesh
        # Family dispatch: MoE configs decode through moe_llama (same
        # cache layout, expert feed-forward); dense configs through
        # llama. Both run the identical serving-step plumbing
        # (llama.decode parameterized over the FFN).
        from grit_tpu.models import moe_llama as _moe  # noqa: PLC0415

        self._decode_fn = (
            # mesh bound here so the expert-activation sharding
            # constraints are live in the jitted step (advisor finding).
            partial(_moe.decode, mesh=mesh)
            if isinstance(cfg, _moe.MoeLlamaConfig)
            else llama.decode
        )
        self.state, self._state_shardings = _init_state(
            self._fresh_state, mesh)
        # Host-side mirror of cache['length'] so capacity is enforced
        # without a per-token device sync; resynced on restore.
        self._cache_len = 0
        # One compiled program per token: decode + sample + state update all
        # inside jit — no per-token host round-trip on the logits.
        self._step = jax.jit(
            partial(_decode_and_sample, self._decode_fn, cfg,
                    self.scfg.temperature)
        )

    def _fresh_state(self) -> dict:
        s = self.scfg
        return {
            "cache": llama.init_kv_cache(self.cfg, s.batch_size, s.max_seq_len),
            "last_token": jnp.zeros((s.batch_size, 1), jnp.int32),
            "rng": jax.random.PRNGKey(s.seed),
            "n_generated": jnp.zeros((), jnp.int32),
        }

    # -- generation -------------------------------------------------------------

    def _reserve(self, n: int) -> None:
        """Guard cache capacity: past ``max_seq_len``, dynamic_update_slice
        would silently clamp the write offset and corrupt the newest cache
        slots — fail loudly on the host instead."""
        if self._cache_len + n > self.scfg.max_seq_len:
            raise ValueError(
                f"KV cache overflow: {self._cache_len} + {n} tokens exceeds "
                f"max_seq_len={self.scfg.max_seq_len}"
            )
        self._cache_len += n

    def prefill(self, prompt: jax.Array) -> jax.Array:
        """Feed prompt (B, S); returns the first sampled token (B, 1)."""
        self._reserve(prompt.shape[1])
        tok, self.state = self._step(self.params, prompt, self.state)
        return tok

    def generate_step(self) -> jax.Array:
        """One autoregressive step from ``last_token``; returns (B, 1)."""
        self._reserve(1)
        tok, self.state = self._step(
            self.params, self.state["last_token"], self.state
        )
        return tok

    def generate(self, n_tokens: int) -> jax.Array:
        """Emit ``n_tokens`` from the current state; (B, n)."""
        out = []
        for _ in range(n_tokens):
            out.append(self.generate_step())
        return jnp.concatenate(out, axis=1)

    # -- migration --------------------------------------------------------------


    def snapshot(self, directory: str, *, barrier=lambda: None) -> str:
        """Dump decode state (not params — those ship with the pod image /
        checkpoint PV separately, exactly once, not per-migration)."""
        quiesce(self.state)
        return write_snapshot(
            directory,
            self.state,
            meta={"n_generated": int(self.state["n_generated"])},
            barrier=barrier,
        )

    def restore(self, directory: str, **kwargs) -> int:
        like = jax.eval_shape(self._fresh_state)
        kwargs.setdefault("mesh", self.mesh)
        kwargs.setdefault("shardings", self._state_shardings)
        self.state = restore_snapshot(directory, like=like, **kwargs)
        self._cache_len = int(self.state["cache"]["length"])
        return int(self.state["n_generated"])


def _decode_and_sample(
    decode_fn, cfg: llama.LlamaConfig, temperature: float, params: dict,
    tokens: jax.Array, state: dict,
) -> tuple[jax.Array, dict]:
    """Jitted decode+sample: one dispatch per token, no logits on the host."""
    logits, cache = decode_fn(cfg, params, tokens, state["cache"])
    last = logits[:, -1, :]
    if temperature <= 0.0:
        tok = jnp.argmax(last, axis=-1, keepdims=True).astype(jnp.int32)
    else:
        step_rng = jax.random.fold_in(state["rng"], state["n_generated"])
        tok = jax.random.categorical(step_rng, last / temperature)[
            :, None
        ].astype(jnp.int32)
    new_state = {
        "cache": cache,
        "last_token": tok,
        "rng": state["rng"],
        "n_generated": state["n_generated"] + 1,
    }
    return tok, new_state


# -- continuous batching ------------------------------------------------------


@dataclass(frozen=True)
class BatchingConfig:
    """Continuous-batching engine knobs."""

    n_slots: int = 4
    max_seq_len: int = 1024
    temperature: float = 0.0  # 0 → greedy
    seed: int = 0
    eos_id: int | None = None
    # Prompts are padded up to the next bucket so prefill compiles one
    # program per bucket, not per prompt length.
    prefill_buckets: tuple[int, ...] = (16, 64, 256, 1024)


class ContinuousBatchingEngine:
    """vLLM-style continuous batching over a fixed slot grid.

    Unlike :class:`InferenceEngine` (lock-step batch: every sequence at
    the same position), each slot here sits at its own cache position;
    sequences join mid-decode (``submit``), leave on EOS/length, and the
    freed slot is reused — all through ONE compiled decode program
    (:func:`grit_tpu.models.llama.decode_ragged`: raggedness is masking,
    never a shape). The whole decode state, heterogeneous positions
    included, is one pytree, so the generic snapshot machinery migrates
    the batch mid-flight exactly like the lock-step engine.
    """

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params: dict,
        bcfg: BatchingConfig | None = None,
        mesh=None,
    ) -> None:
        from grit_tpu.device.hook import (  # noqa: PLC0415
            enable_compile_cache_from_env,
        )

        enable_compile_cache_from_env()
        self.cfg = cfg
        self.bcfg = bcfg or BatchingConfig()
        self.params = params
        self.mesh = mesh
        self._submissions = 0  # per-slot RNG stream seed (monotonic)
        # KV cache sharded per KV_CACHE_RULES (slots over the data axes,
        # kv heads over model — same layout as the lock-step engine);
        # slot bookkeeping vectors replicate.
        self.state, self._state_shardings = _init_state(
            self._fresh_state, mesh)
        # Family dispatch, same pattern as InferenceEngine: MoE configs
        # decode through moe_llama's expert FFN, dense through llama.
        from grit_tpu.models import moe_llama as _moe  # noqa: PLC0415

        is_moe = isinstance(cfg, _moe.MoeLlamaConfig)
        if is_moe:
            decode_fn = partial(_moe.decode, mesh=mesh)
            ragged_fn = partial(_moe.decode_ragged, mesh=mesh)
        else:
            decode_fn, ragged_fn = llama.decode, llama.decode_ragged
        step_kwargs = {}
        if self._state_shardings is not None:
            step_kwargs = dict(out_shardings=(self._state_shardings, None))
        self._step_fn = jax.jit(
            partial(_cb_step, cfg, self.bcfg.temperature,
                    self.bcfg.eos_id, ragged_fn),
            **step_kwargs,
        )
        prefill_kwargs = {}
        tag_kwargs = {}
        if self._state_shardings is not None:
            # Pin the returned caches to the canonical sharding: without
            # this, the traced-slot dynamic update along the slot-sharded
            # axis leaves GSPMD free to gather/replicate the whole cache
            # per admission and hand back a drifted layout (a snapshot
            # taken between submit and step would record it).
            cache_sh = self._state_shardings["cache"]
            prefill_kwargs = dict(
                out_shardings=(cache_sh["k"], cache_sh["v"]))
            tag_kwargs = prefill_kwargs
        self._prefill_fns = {
            b: jax.jit(partial(_cb_prefill, cfg, decode_fn, is_moe),
                       **prefill_kwargs)
            for b in self.bcfg.prefill_buckets
        }
        self._tag_fn = jax.jit(_tag_elidable_kv, **tag_kwargs)
        # Post-copy clone protocol (snapshot fan-out): while the cold KV
        # bulk is still landing, _parked_mask marks the slots the source
        # had in flight (blocked from admission AND from stepping until
        # their cache rows arrive) and _fresh_mask the slots this clone
        # admitted into its fresh grid since — absorb_restored() merges
        # the two worlds when the tail lands.
        self._postcopy = None
        self._parked_mask = None
        self._fresh_mask = None

    def _fresh_state(self) -> dict:
        b = self.bcfg
        return {
            "cache": llama.init_kv_cache(self.cfg, b.n_slots, b.max_seq_len),
            "lengths": jnp.zeros((b.n_slots,), jnp.int32),
            "active": jnp.zeros((b.n_slots,), bool),
            "last_token": jnp.zeros((b.n_slots, 1), jnp.int32),
            "rngs": jax.vmap(
                lambda i: jax.random.fold_in(jax.random.PRNGKey(b.seed), i)
            )(jnp.arange(b.n_slots)),
            "n_generated": jnp.zeros((b.n_slots,), jnp.int32),
        }

    # -- admission -------------------------------------------------------------

    def free_slots(self) -> list[int]:
        import numpy as np  # noqa: PLC0415

        free = ~np.asarray(self.state["active"])
        if self._parked_mask is not None:
            # Mid post-copy clone restore: the source's in-flight slots
            # are reserved — their KV rows are still landing, and a new
            # admission into one would be destroyed by the absorb merge.
            free &= ~self._parked_mask
        return [int(i) for i in np.flatnonzero(free)]

    def submit(self, prompt) -> int:
        """Admit a prompt into a free slot; returns the slot id. The next
        :meth:`step` decodes its first token alongside the running batch."""
        prompt = jnp.asarray(prompt, jnp.int32).reshape(-1)
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slots — poll step()/release first")
        slot = free[0]
        n = int(prompt.shape[0])
        if n == 0:
            raise ValueError("empty prompt")
        # The bucket must also fit the cache: a 256-bucket prefill against
        # a 128-slot cache would blow up inside dynamic_update_slice.
        bucket = next(
            (b for b in self.bcfg.prefill_buckets
             if n <= b <= self.bcfg.max_seq_len),
            None,
        )
        if bucket is None or n >= self.bcfg.max_seq_len:
            raise ValueError(
                f"prompt length {n} fits no prefill bucket within "
                f"max_seq_len={self.bcfg.max_seq_len}"
            )
        padded = jnp.zeros((1, bucket), jnp.int32).at[0, :n].set(prompt)
        st = self.state
        cache_k, cache_v = self._prefill_fns[bucket](
            self.params, padded, jnp.asarray(n, jnp.int32),
            jnp.asarray(slot, jnp.int32), st["cache"]["k"], st["cache"]["v"],
        )
        # lengths = n-1 with the prompt's final token as last_token: the
        # next step() re-derives position n-1 (rewriting identical K/V)
        # and samples generated token #1 — every emitted token flows
        # through the one compiled step, prefill never samples.
        self.state = {
            **st,
            "cache": {**st["cache"], "k": cache_k, "v": cache_v},
            "lengths": st["lengths"].at[slot].set(n - 1),
            "active": st["active"].at[slot].set(True),
            "last_token": st["last_token"].at[slot, 0].set(prompt[n - 1]),
            "rngs": st["rngs"].at[slot].set(
                jax.random.fold_in(jax.random.PRNGKey(self.bcfg.seed),
                                   self.bcfg.n_slots + self._submissions)),
            "n_generated": st["n_generated"].at[slot].set(0),
        }
        self._submissions += 1
        if self._fresh_mask is not None:
            # This slot's KV rows now live in the clone's fresh grid;
            # the absorb merge must keep them over the restored cache.
            self._fresh_mask[slot] = True
        return slot

    def release(self, slot: int) -> None:
        self.state = {
            **self.state,
            "active": self.state["active"].at[slot].set(False),
        }

    # -- decode ----------------------------------------------------------------

    def step(self) -> dict[int, int]:
        """One ragged decode for every active slot. Returns
        ``{slot: token}`` for slots that emitted this step; slots hitting
        EOS or the cache limit auto-deactivate (their final token is still
        reported)."""
        import numpy as np  # noqa: PLC0415

        if self._postcopy is not None and self._postcopy.done:
            # Batch boundary = the safe merge point: the cold tail has
            # landed, fold the restored streams in before this step so
            # they decode alongside the clone's own traffic.
            self.absorb_restored()
        was_active = np.asarray(self.state["active"])
        if not was_active.any():
            return {}
        self.state, toks = self._step_fn(self.params, self.state)
        out = np.asarray(toks).reshape(-1)
        return {int(i): int(out[i]) for i in np.flatnonzero(was_active)}

    # -- migration -------------------------------------------------------------

    def snapshot_meta(self) -> dict:
        """Manifest metadata every dump of this engine must carry —
        the engine's own :meth:`snapshot` and the serving agentlet's
        managed dump both ship it."""
        return {"engine": "continuous-batching",
                # Host-side mirror: the next submission's RNG stream id.
                # Restoring it keeps post-migration submissions off the
                # streams still-running slots already consumed.
                "submissions": self._submissions}

    def snapshot_state(self) -> dict:
        """The state pytree as it should be DUMPED: KV pages that can
        never be attended — inactive slots' rows, positions past each
        slot's write waterline — are zeroed (tagged) so the transport
        codec's zero-block elision ships a half-empty grid's cache as
        mostly empty payloads. Semantically identical to ``state`` (the
        zeroed pages are re-prefilled or overwritten before any read);
        the serving agentlet's dump hook reads through this too."""
        if self._postcopy is not None:
            # Dumping a clone whose cold tail is still landing (the
            # serving-during-restore window): settle the merge first —
            # the half-merged world marks the source's in-flight slots
            # inactive and would drop their streams permanently.
            self.absorb_restored()
        st = self.state
        k, v = self._tag_fn(st["cache"]["k"], st["cache"]["v"],
                            st["lengths"], st["active"])
        return {**st, "cache": {**st["cache"], "k": k, "v": v}}

    def snapshot(self, directory: str, *, base: str | None = None) -> str:
        if self._postcopy is not None:
            # Iterative migration of a clone mid-restore: finish the
            # absorb first — a dump of the half-merged world would ship
            # a grid whose parked slots have no KV rows.
            self.absorb_restored()
        quiesce(self.state)
        return write_snapshot(
            directory, self.snapshot_state(), base=base,
            meta=self.snapshot_meta(),
        )

    def restore(self, directory: str, **kwargs) -> None:
        from grit_tpu.device.snapshot import SnapshotManifest  # noqa: PLC0415

        like = jax.eval_shape(self._fresh_state)
        kwargs.setdefault("mesh", self.mesh)
        kwargs.setdefault("shardings", self._state_shardings)
        self.state = restore_snapshot(directory, like=like, **kwargs)
        self._submissions = int(
            SnapshotManifest.load(directory).meta.get("submissions", 0))
        self._postcopy = self._parked_mask = self._fresh_mask = None

    def restore_postcopy(self, directory: str):
        """Post-copy clone restore — the snapshot fan-out's device leg.

        Places the snapshot's hot set synchronously (the per-slot
        bookkeeping vectors: positions, active mask, RNG streams, last
        tokens) and returns the in-flight
        :class:`~grit_tpu.device.snapshot.PostcopyRestore` handle while
        the cold KV bulk lands in the background. The engine starts
        SERVING immediately: new requests prefill into a fresh KV grid
        using only slots the source had free, while the source's
        in-flight slots stay parked until :meth:`absorb_restored` (run
        automatically at the first batch boundary after the tail lands)
        merges the restored rows in — from then on the migrated streams
        continue bit-identically. If the hot set did not cover the
        bookkeeping (operator zeroed the hot cut), this degrades to the
        blocking restore loudly-equivalently: correctness over latency.
        """
        import numpy as np  # noqa: PLC0415

        from grit_tpu.device.snapshot import (  # noqa: PLC0415
            restore_snapshot_postcopy,
        )

        if self._postcopy is not None:
            # Re-cloning an engine already mid-restore: settle the
            # previous fan-out first — two outstanding tails over one
            # state pytree cannot merge.
            self.absorb_restored()
        like = jax.eval_shape(self._fresh_state)
        handle = restore_snapshot_postcopy(
            directory, like=like, mesh=self.mesh,
            shardings=self._state_shardings)
        self._submissions = int(handle.meta.get("submissions", 0))
        placed = handle.placed_leaves()
        book = {}
        for path, _leaf in jax.tree_util.tree_flatten_with_path(like)[0]:
            name = jax.tree_util.keystr(path)
            if name in placed:
                keys = tuple(getattr(kk, "key", str(kk)) for kk in path)
                book[keys] = placed[name]
        need = [("lengths",), ("active",), ("last_token",), ("rngs",),
                ("n_generated",)]
        if any(n not in book for n in need):
            self.state = handle.wait()
            self._postcopy = self._parked_mask = self._fresh_mask = None
            return handle
        fresh, _ = _init_state(self._fresh_state, self.mesh)
        parked = np.asarray(book[("active",)]).astype(bool).copy()
        self.state = {
            "cache": fresh["cache"],
            "lengths": book[("lengths",)],
            # Parked until their KV rows land; absorb re-activates.
            "active": fresh["active"],
            "last_token": book[("last_token",)],
            "rngs": book[("rngs",)],
            "n_generated": book[("n_generated",)],
        }
        self._postcopy = handle
        self._parked_mask = parked
        self._fresh_mask = np.zeros_like(parked)
        return handle

    @property
    def resumed_all(self) -> bool:
        """True once no restored stream is still waiting on its KV rows
        (either never a clone, or the absorb merge has run)."""
        return self._postcopy is None

    def absorb_restored(self, timeout: float | None = None) -> None:
        """Block until the restored KV cache landed, then merge the two
        worlds: freshly-prefilled rows for slots this clone admitted,
        restored rows for everything else — and re-activate the parked
        slots, whose streams continue bit-identically from the next
        step. Idempotent; a tail that failed terminally re-raises out of
        the handle's own recovery path (blocking-fallback semantics)."""
        if self._postcopy is None:
            return
        full = self._postcopy.wait(**(
            {} if timeout is None else {"timeout": timeout}))
        fresh = jnp.asarray(self._fresh_mask)
        row = fresh[:, None]
        page = fresh[None, :, None, None, None]
        cur = self.state
        self.state = {
            "cache": {
                **full["cache"],
                "k": jnp.where(page, cur["cache"]["k"], full["cache"]["k"]),
                "v": jnp.where(page, cur["cache"]["v"], full["cache"]["v"]),
            },
            "lengths": jnp.where(fresh, cur["lengths"], full["lengths"]),
            "active": jnp.where(fresh, cur["active"], full["active"]),
            "last_token": jnp.where(row, cur["last_token"],
                                    full["last_token"]),
            "rngs": jnp.where(row, cur["rngs"], full["rngs"]),
            "n_generated": jnp.where(fresh, cur["n_generated"],
                                     full["n_generated"]),
        }
        self._postcopy = self._parked_mask = self._fresh_mask = None


def _tag_elidable_kv(cache_k, cache_v, lengths, active):
    """Zero every KV page that can never be attended: inactive slots'
    whole rows, and positions past an active slot's write waterline
    (``pos <= lengths`` stays — the next step re-derives and rewrites
    position ``lengths`` itself). Dense garbage in those pages is what
    kept the codec's zero-block elision from firing on half-empty
    grids; tagged, a free slot's cache bytes ship as empty payloads."""
    pos = jnp.arange(cache_k.shape[2])
    live = active[None, :, None, None, None] & (
        pos[None, None, :, None, None]
        <= lengths[None, :, None, None, None])
    zero_k = jnp.zeros((), cache_k.dtype)
    zero_v = jnp.zeros((), cache_v.dtype)
    return jnp.where(live, cache_k, zero_k), jnp.where(live, cache_v, zero_v)


def _cb_prefill(cfg, decode_fn, masked, params, padded, length, slot,
                cache_k, cache_v):
    """Prefill one slot: run the (1, bucket) prompt through the shared
    decode trunk against the slot's cache rows, write them back into the
    batch cache at ``slot`` (dynamic index → one program per bucket).
    Pad positions beyond the true prompt length (``length``, traced so one
    program serves every prompt in the bucket) leave garbage K/V that is
    never attended (per-slot kv_len mask) and is overwritten as the slot
    generates into those positions. For MoE configs (``masked``) the pads
    are additionally masked out of expert routing: a pad token competing
    for expert capacity would change which *real* tokens get their
    experts, making CB prefill diverge from a solo run."""
    slot_cache = {
        "k": jax.lax.dynamic_slice_in_dim(cache_k, slot, 1, axis=1),
        "v": jax.lax.dynamic_slice_in_dim(cache_v, slot, 1, axis=1),
        "length": jnp.zeros((), jnp.int32),
    }
    if masked:
        token_mask = jnp.arange(padded.shape[1]) < length  # (B*S,), B==1
        _logits, new_cache = decode_fn(
            cfg, params, padded, slot_cache, token_mask=token_mask)
    else:
        _logits, new_cache = decode_fn(cfg, params, padded, slot_cache)
    cache_k = jax.lax.dynamic_update_slice_in_dim(
        cache_k, new_cache["k"], slot, axis=1)
    cache_v = jax.lax.dynamic_update_slice_in_dim(
        cache_v, new_cache["v"], slot, axis=1)
    return cache_k, cache_v


def _cb_step(cfg, temperature, eos_id, ragged_fn, params, state):
    """Jitted continuous-batching step: ragged decode + per-slot sample +
    slot bookkeeping, one dispatch for the whole grid."""
    logits, cache = ragged_fn(
        cfg, params, state["last_token"], state["cache"],
        state["lengths"], state["active"],
    )
    last = logits[:, -1, :]  # (B, vocab)
    if temperature <= 0.0:
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    else:
        keys = jax.vmap(jax.random.fold_in)(state["rngs"],
                                            state["n_generated"])
        tok = jax.vmap(
            lambda k, l: jax.random.categorical(k, l / temperature)
        )(keys, last).astype(jnp.int32)
    active = state["active"]
    tok = jnp.where(active, tok, state["last_token"][:, 0])
    new_lengths = state["lengths"] + active.astype(jnp.int32)
    max_len = state["cache"]["k"].shape[2]
    still = active
    if eos_id is not None:
        still = still & (tok != eos_id)
    still = still & (new_lengths < max_len)
    new_state = {
        "cache": cache,
        "lengths": new_lengths,
        "active": still,
        "last_token": tok[:, None],
        "rngs": state["rngs"],
        "n_generated": state["n_generated"] + active.astype(jnp.int32),
    }
    return new_state, tok
