"""Pipeline-parallel llama: the flagship decoder over the GPipe schedule.

Decomposition (classic GPipe, TPU-native mechanics): embedding and
lm_head run outside the pipeline (replicated compute, negligible FLOPs);
the layer stack — where the parameters and FLOPs live — partitions into
``n_stages`` contiguous groups. Stage weights keep llama's stacked
(L, ...) leaves, reshaped to (n_stages, L/n_stages, ...) and sharded over
the ``pipe`` mesh axis; each stage's body is itself a ``lax.scan`` over
its local layers, so the whole schedule is the pipeline scan (ppermute
ring per tick — ``grit_tpu/parallel/pipeline.py``) around an inner layer
scan. Compiled once; no host control flow.

The stage interface carries activations of shape (mb, S, dim) — full
sequence per microbatch (attention is causal within the stage, positions
are static), microbatches ride the schedule.

Checkpoints interchange with the dense layout: :func:`to_stage_params` /
:func:`from_stage_params` are pure reshapes of the same tree, so a dense
snapshot restores onto a pipelined job and vice versa.

Reference analogue: none (SURVEY §2.4). Completes the pp story for the
flagship family (tests assert forward AND gradient parity vs dense).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grit_tpu.models import llama
from grit_tpu.models.llama import LlamaConfig, rms_norm, token_cross_entropy
from grit_tpu.parallel.pipeline import PIPE_AXIS, microbatch, pipeline_apply


def to_stage_params(cfg: LlamaConfig, params: dict, n_stages: int) -> dict:
    """Reshape the stacked layer leaves (L, ...) → (n_stages, L/S, ...).
    Pure layout change; :func:`from_stage_params` inverts it exactly."""

    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {n_stages} stages")
    per = cfg.n_layers // n_stages
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda a: a.reshape(n_stages, per, *a.shape[1:]), params["layers"])
    return out


def from_stage_params(params: dict) -> dict:
    """Undo :func:`to_stage_params` (restore the dense (L, ...) leaves)."""

    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]),
        params["layers"])
    return out


def stage_shardings(mesh: Mesh, params: dict, axis: str = PIPE_AXIS) -> dict:
    """Layer leaves sharded over ``pipe``; embed/head/final replicated."""

    return {
        k: (jax.tree.map(lambda _: NamedSharding(mesh, P(axis)), v)
            if k == "layers" else
            jax.tree.map(lambda _: NamedSharding(mesh, P()), v))
        for k, v in params.items()
    }


def _stage_fn(cfg: LlamaConfig, mlp_fn_builder=None):
    """One pipeline stage: scan this stage's local layers through
    llama.layer_body — the same single copy of the layer math the dense
    trunk runs. ``mlp_fn_builder(mb, S) -> mlp_fn`` swaps the FFN per
    activation shape (the MoE family pipelines through this)."""

    def fn(stage_layers, x):
        mb, S, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(S), (mb, S))
        mlp_fn = mlp_fn_builder(mb, S) if mlp_fn_builder else None

        def body(carry, layer_params):
            h, _aux = llama.layer_body(cfg, layer_params, carry, positions,
                                       mlp_fn=mlp_fn)
            return h, None

        x, _ = lax.scan(body, x, stage_layers)
        return x

    return fn


def forward_pp(cfg: LlamaConfig, stage_params: dict, tokens: jax.Array,
               *, mesh: Mesh, n_microbatches: int,
               axis: str = PIPE_AXIS, mlp_fn_builder=None) -> jax.Array:
    """Tokens (B, S) → logits (B, S, vocab) through the layer pipeline.
    ``stage_params`` from :func:`to_stage_params`, layer leaves sharded
    over ``axis``; B must divide by ``n_microbatches``."""

    B, S = tokens.shape
    x = stage_params["tok_emb"].astype(cfg.dtype)[tokens]      # (B, S, D)
    x_mb = microbatch(x, n_microbatches)                       # (M, mb, S, D)

    y_mb = pipeline_apply(
        _stage_fn(cfg, mlp_fn_builder), stage_params["layers"], x_mb,
        mesh=mesh, axis=axis,
    )
    y = y_mb.reshape(B, S, cfg.dim)
    y = rms_norm(y, stage_params["final_norm"], cfg.norm_eps)
    logits = y @ stage_params["lm_head"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def loss_fn_pp(cfg: LlamaConfig, stage_params: dict, tokens: jax.Array,
               targets: jax.Array, mask: jax.Array | None = None,
               *, mesh: Mesh, n_microbatches: int,
               axis: str = PIPE_AXIS) -> jax.Array:
    """Pipelined next-token loss (differentiable — ppermute transposes to
    the reverse ring, so grads flow back through the schedule)."""

    logits = forward_pp(cfg, stage_params, tokens, mesh=mesh,
                        n_microbatches=n_microbatches, axis=axis)
    return token_cross_entropy(logits, targets, mask)
