"""Workload models — the JAX programs the framework checkpoints/migrates.

The reference framework is workload-agnostic (it freezes whatever one pod
runs; its demo is a falcon-7b LoRA fine-tune,
``contrib/containerd/testdata/README.md:5-14``). The TPU build ships its
baseline workloads in-tree because they double as the integration tests for
BASELINE.json's configs:

- :mod:`grit_tpu.models.mnist` — config 1/2 (MNIST training pod).
- :mod:`grit_tpu.models.llama` — config 3 (Llama-2-7B LoRA fine-tune) and
  the flagship model for the driver's compile check.
- :mod:`grit_tpu.models.lora` — LoRA adapters over llama.
- :mod:`grit_tpu.models.moe_llama` — Mixtral-shaped MoE decoder
  (expert-parallel feed-forward over the ``model`` axis).
- :mod:`grit_tpu.models.long_context` — sequence-parallel llama (ring
  attention over a ``seq`` axis; dense↔SP checkpoint interchange).
- :mod:`grit_tpu.models.pipeline_llama` — the flagship over the GPipe
  schedule (layer-group stages on a ``pipe`` axis; grad-exact).
- :mod:`grit_tpu.models.serving` — config 5 (inference with live KV cache).
"""
