"""Long-context llama: sequence parallelism through ring attention.

A sequence too long for one chip's HBM shards over a ``seq`` mesh axis;
every per-token op (embedding, norms, MLP, lm_head, loss) partitions
trivially, and the one cross-token op — causal attention — runs as the
ring (``grit_tpu/ops/ring_attention.py``): K/V blocks rotate around the
axis with one ``ppermute`` neighbor hop per step, ICI-friendly, with
online-softmax accumulation so no chip ever holds the full S×S score
matrix or the full sequence.

Built as hooks over the shared llama trunk (``forward_trunk(attn_fn=…)``
— same pattern as the MoE family's ``mlp_fn``): one decoder
implementation, three families. The param tree is identical to dense
llama's, so checkpoints snapshot/restore interchangeably — dump on a
seq-parallel mesh, restore on a dense one, or vice versa (the snapshot
engine re-lays-out by global index; ``tests/test_long_context.py``).

Two interchangeable context-parallel schemes (``attn_impl=``): ``"ring"``
(ppermute K/V rotation, any head count) and ``"ulysses"`` (all-to-all to
head sharding, full-sequence flash attention per chip; needs
``n_kv_heads % axis_size == 0``) — see :mod:`grit_tpu.ops.ulysses` for
the trade-off table.

Reference analogue: none (SURVEY §2.4 — no model or sequence dimension
exists in the reference). This is the "long-context is first-class"
surface of the TPU build.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grit_tpu.models import llama
from grit_tpu.models.llama import LlamaConfig, token_cross_entropy
from grit_tpu.ops.ring_attention import ring_attention
from grit_tpu.ops.ulysses import ulysses_attention

SEQ_AXIS = "seq"

ATTN_IMPLS = {"ring": ring_attention, "ulysses": ulysses_attention}


def _seq_sharded(mesh: Mesh, axis: str):
    return NamedSharding(mesh, P(None, axis))


def forward_sp(cfg: LlamaConfig, params: dict, tokens: jax.Array,
               *, mesh: Mesh, axis: str = SEQ_AXIS,
               attn_impl: str = "ring") -> jax.Array:
    """Tokens (B, S) with S divided over ``mesh[axis]`` → logits
    (B, S, vocab) with the same sequence sharding."""

    tokens = jax.lax.with_sharding_constraint(tokens, _seq_sharded(mesh, axis))
    sp_attention = ATTN_IMPLS[attn_impl]

    def attn(q, k, v):
        return sp_attention(q, k, v, mesh=mesh, axis=axis)

    logits, _aux = llama.forward_trunk(cfg, params, tokens, attn_fn=attn)
    return jax.lax.with_sharding_constraint(
        logits, NamedSharding(mesh, P(None, axis, None)))


def loss_fn_sp(cfg: LlamaConfig, params: dict, tokens: jax.Array,
               targets: jax.Array, mask: jax.Array | None = None,
               *, mesh: Mesh, axis: str = SEQ_AXIS,
               attn_impl: str = "ring") -> jax.Array:
    """Sequence-parallel next-token loss — drop-in for llama.loss_fn on a
    seq mesh (close mesh/axis over it for the Trainer)."""

    logits = forward_sp(cfg, params, tokens, mesh=mesh, axis=axis,
                        attn_impl=attn_impl)
    targets = jax.lax.with_sharding_constraint(
        targets, _seq_sharded(mesh, axis))
    return token_cross_entropy(logits, targets, mask)
