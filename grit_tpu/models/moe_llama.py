"""MoE-llama: Mixtral-shaped decoder — llama attention + per-layer
top-k expert MLPs (``grit_tpu/ops/moe.py``; ``cfg.top_k``: 1 = Switch
routing, 2 = Mixtral's renormalized top-2).

Composes the existing pieces rather than forking them: attention/RoPE/
RMSNorm come from :mod:`grit_tpu.models.llama` (same scan-over-layers
XLA-friendly stack), the feed-forward is the expert-parallel MoE layer.
The router's load-balancing aux loss is accumulated through the layer
scan and added to the LM loss.

Sharding: experts ride the ``model`` mesh axis (expert parallelism is
tensor-parallel-shaped traffic — all-to-alls on the innermost ICI axis),
attention stays on the standard llama rules. Migratable like every other
workload: the param tree snapshots/restores through the generic engine
(``tests/test_moe_llama.py`` asserts a bit-identical resumed loss).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import dataclasses

from grit_tpu.models import llama
from grit_tpu.models.llama import (
    BATCH_SPEC,  # noqa: F401  (re-export: same batch sharding)
    LlamaConfig,
    rms_norm,
    token_cross_entropy,
)
from grit_tpu.ops.moe import init_moe_params, moe_mlp
from grit_tpu.parallel.sharding import ShardingRules

# Experts ride the tensor-parallel mesh axis: ep traffic is the same
# innermost-ICI all-to-all shape as tp activations.
EXPERT_MESH_AXIS = "model"


@dataclass(frozen=True)
class MoeLlamaConfig(LlamaConfig):
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_weight: float = 0.01  # load-balancing loss weight
    # Experts per token: 1 = Switch, 2 = Mixtral (gates renormalized over
    # the selected experts).
    top_k: int = 1

    @staticmethod
    def tiny(**overrides) -> "MoeLlamaConfig":
        # Derive from LlamaConfig.tiny so the two tiny families can never
        # drift apart.
        base = dataclasses.asdict(LlamaConfig.tiny())
        base.update({"n_experts": 4})
        base.update(overrides)
        return MoeLlamaConfig(**base)


# llama rules + expert weights: experts over the 'model' axis, hidden
# dims over 'fsdp' (ZeRO-style), router replicated.
MOE_LLAMA_RULES = ShardingRules(
    rules=(
        *llama.LLAMA_RULES.rules,
        (r"moe/router", P(None, None, None)),            # (L, dim, E)
        (r"moe/w_in", P(None, "model", "fsdp", None)),   # (L, E, dim, hid)
        (r"moe/w_out", P(None, "model", None, "fsdp")),  # (L, E, hid, dim)
    ),
)


def init_params(cfg: MoeLlamaConfig, key: jax.Array) -> dict:
    """Llama attention/embedding params with per-layer MoE feed-forward
    (dense mlp weights replaced by stacked expert weights)."""

    k_base, k_moe = jax.random.split(key)
    # with_mlp=False: no throwaway dense feed-forward allocation (at
    # llama2-7b scale that would be ~11 GB of discarded f32 on the eager
    # path).
    params = llama.init_params(cfg, k_base, with_mlp=False)
    layers = dict(params["layers"])

    moe_keys = jax.random.split(k_moe, cfg.n_layers)
    per_layer = [
        init_moe_params(k, cfg.dim, cfg.hidden_dim, cfg.n_experts,
                        dtype=cfg.param_dtype)
        for k in moe_keys
    ]
    layers["moe"] = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
    params["layers"] = layers
    return params


def _moe_ffn(cfg: MoeLlamaConfig, B: int, S: int, mesh, token_mask=None):
    """FFN closure for llama's trunk/decode hooks. ``token_mask`` (B*S,)
    excludes rows (bucket padding, released serving slots) from expert
    routing so garbage never competes for capacity."""

    def ffn(layer_params, normed):
        y, aux = moe_mlp(
            layer_params["moe"], normed.reshape(B * S, cfg.dim),
            capacity_factor=cfg.capacity_factor, mesh=mesh,
            axis=EXPERT_MESH_AXIS, top_k=cfg.top_k,
            token_mask=token_mask,
        )
        return y.reshape(B, S, cfg.dim), aux

    return ffn


def forward_with_aux(
    cfg: MoeLlamaConfig, params: dict, tokens: jax.Array,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Tokens (B, S) → (logits (B, S, V) float32, mean aux loss). Runs
    llama's shared trunk with the expert FFN — one decoder
    implementation for both families."""

    B, S = tokens.shape
    logits, aux_per_layer = llama.forward_trunk(
        cfg, params, tokens, mlp_fn=_moe_ffn(cfg, B, S, mesh))
    return logits, jnp.mean(aux_per_layer)


def forward(cfg: MoeLlamaConfig, params: dict, tokens: jax.Array,
            mesh=None) -> jax.Array:
    return forward_with_aux(cfg, params, tokens, mesh=mesh)[0]


def decode(cfg: MoeLlamaConfig, params: dict, tokens: jax.Array,
           cache: dict, mesh=None, token_mask=None) -> tuple[jax.Array, dict]:
    """Serving step (prefill or S=1 autoregressive): llama's cached
    attention with the MoE feed-forward. Cache layout is identical to
    llama's (``llama.init_kv_cache``), so the serving engine's snapshot/
    restore machinery migrates MoE generations unchanged.

    Capacity note: tokens compete for expert capacity within one call, so
    a prefill (many tokens) and per-step decode (B tokens) can drop
    differently when capacity binds — the standard capacity-MoE
    train/serve asymmetry. With ``capacity_factor >= n_experts`` nothing
    drops and decode is exactly consistent with :func:`forward`."""

    B, S = tokens.shape
    ffn = _moe_ffn(cfg, B, S, mesh, token_mask=token_mask)

    # One serving-step implementation for both families: llama.decode
    # carries the cache/positions semantics, we supply the FFN (decode's
    # hook takes just the activation; drop the aux).
    return llama.decode(cfg, params, tokens, cache,
                        mlp_fn=lambda lp, normed: ffn(lp, normed)[0])


def decode_ragged(cfg: MoeLlamaConfig, params: dict, tokens: jax.Array,
                  cache: dict, lengths: jax.Array, active: jax.Array,
                  mesh=None) -> tuple[jax.Array, dict]:
    """Continuous-batching step for the MoE family: llama's ragged cached
    attention with the expert feed-forward (same hook pattern as
    :func:`decode`; same capacity caveat)."""
    B, S = tokens.shape
    # Released slots' stale tokens must not route: mask them out of the
    # expert layer (S == 1 on this path, so the mask is just `active`).
    ffn = _moe_ffn(cfg, B, S, mesh,
                   token_mask=jnp.repeat(active, S))
    return llama.decode_ragged(
        cfg, params, tokens, cache, lengths, active,
        mlp_fn=lambda lp, normed: ffn(lp, normed)[0],
    )


init_kv_cache = llama.init_kv_cache  # same cache layout


def forward_pp(cfg: MoeLlamaConfig, stage_params: dict, tokens: jax.Array,
               *, mesh, n_microbatches: int, axis: str = "pipe") -> jax.Array:
    """Pipelined MoE forward — pp + ep composed in one model, the
    standard large-MoE deployment shape: layer-group stages over the
    ``pipe`` axis (grit_tpu/models/pipeline_llama.py schedule), expert
    weights within each stage sharded over ``expert`` (their
    partitioning propagates from the parameter shardings; no explicit
    constraint inside the manual-pipe body). ``stage_params`` from
    :func:`grit_tpu.models.pipeline_llama.to_stage_params` on an MoE
    param tree.

    Capacity note (same asymmetry as :func:`decode`): tokens compete for
    expert capacity within one microbatch here vs within the whole batch
    in :func:`forward`, so dropping can differ when capacity binds; with
    ``capacity_factor >= n_experts`` nothing drops and the pipelined
    forward is exactly consistent with the dense one."""

    from grit_tpu.models import pipeline_llama  # noqa: PLC0415

    return pipeline_llama.forward_pp(
        cfg, stage_params, tokens, mesh=mesh,
        n_microbatches=n_microbatches, axis=axis,
        mlp_fn_builder=lambda mb, S: _moe_ffn(cfg, mb, S, None),
    )


def pp_stage_shardings(mesh, stage_params: dict, pipe_axis: str = "pipe",
                       expert_axis: str = "expert") -> dict:
    """Param layout for the pipelined MoE: the standard pipeline layout
    (pipeline_llama.stage_shardings — one source of truth for 'layers
    over pipe, embed/head replicated') with the expert weights upgraded
    to shard their EXPERT dim over ``expert``. Staged w_in/w_out leaves
    are (n_stages, local_layers, E, ...): pipe on axis 0, experts on
    axis 2 — the local-layer axis stays unsharded."""

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from grit_tpu.models import pipeline_llama  # noqa: PLC0415

    out = pipeline_llama.stage_shardings(mesh, stage_params,
                                         axis=pipe_axis)

    def upgrade(path, sharding):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w_in", "w_out"):
            return NamedSharding(mesh, P(pipe_axis, None, expert_axis))
        return sharding

    out["layers"] = jax.tree_util.tree_map_with_path(upgrade, out["layers"])
    return out


def loss_fn(cfg: MoeLlamaConfig, params: dict, tokens: jax.Array,
            targets: jax.Array, mask: jax.Array | None = None,
            mesh=None) -> jax.Array:
    """Next-token cross entropy (llama's shared helper, same masking
    semantics) + weighted load-balancing aux. Pass the training mesh so
    the MoE layer pins its expert-activation sharding (close over it in
    the Trainer's loss lambda — see tests/test_moe_llama.py)."""

    logits, aux = forward_with_aux(cfg, params, tokens, mesh=mesh)
    return token_cross_entropy(logits, targets, mask) + cfg.aux_weight * aux
