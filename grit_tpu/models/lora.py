"""LoRA adapters for the llama model — merge-under-jit formulation.

BASELINE config 3 is a Llama LoRA fine-tune (the reference demo is a
falcon-7b LoRA job, ``contrib/containerd/testdata/README.md``). The
TPU-idiomatic formulation: keep base weights frozen, materialize
``W + (alpha/r)·A@B`` *inside* the jitted loss. XLA fuses the rank-r
update into the surrounding computation; differentiating w.r.t. the LoRA
tree alone gives adapter-only gradients with no stop-gradient bookkeeping,
and the optimizer state is rank-r sized (the point of LoRA).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from grit_tpu.models.llama import LlamaConfig, loss_fn
from grit_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P

TARGETS = ("wq", "wk", "wv", "wo")


@dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple[str, ...] = ("wq", "wv")


# A-factors shard like the base weight's input dim, B-factors like its
# output dim; the rank axis stays replicated (it is tiny).
LORA_RULES = ShardingRules(
    rules=[
        (r"/(wq|wk|wv|wo)_a$", P(None, "fsdp", None)),
        (r"/(wq|wk|wv)_b$", P(None, None, "model")),
        (r"/wo_b$", P(None, None, "fsdp")),
    ],
    default=P(),
)


def init_lora(cfg: LlamaConfig, lcfg: LoraConfig, key: jax.Array) -> dict:
    """A ~ N(0, 1/rank), B = 0 — adapters start as identity (delta = 0)."""
    hd = cfg.head_dim
    out_dims = {
        "wq": cfg.n_heads * hd,
        "wk": cfg.n_kv_heads * hd,
        "wv": cfg.n_kv_heads * hd,
        "wo": cfg.dim,
    }
    in_dims = {
        "wq": cfg.dim, "wk": cfg.dim, "wv": cfg.dim, "wo": cfg.n_heads * hd,
    }
    L = cfg.n_layers
    adapters = {}
    keys = jax.random.split(key, len(lcfg.targets))
    for t, k in zip(lcfg.targets, keys):
        adapters[f"{t}_a"] = (
            jax.random.normal(k, (L, in_dims[t], lcfg.rank), cfg.param_dtype)
            / jnp.sqrt(lcfg.rank)
        )
        adapters[f"{t}_b"] = jnp.zeros(
            (L, lcfg.rank, out_dims[t]), cfg.param_dtype
        )
    return {"layers": {"attn": adapters}}


def merge(params: dict, lora_params: dict, lcfg: LoraConfig) -> dict:
    """Base params + scaled low-rank deltas (new tree; base untouched)."""
    scale = lcfg.alpha / lcfg.rank
    attn = dict(params["layers"]["attn"])
    adapters = lora_params["layers"]["attn"]
    for t in lcfg.targets:
        delta = jnp.einsum(
            "lir,lro->lio", adapters[f"{t}_a"], adapters[f"{t}_b"]
        )
        attn[t] = attn[t] + scale * delta.astype(attn[t].dtype)
    out = dict(params)
    out["layers"] = dict(params["layers"])
    out["layers"]["attn"] = attn
    return out


def lora_loss_fn(cfg: LlamaConfig, lcfg: LoraConfig, base_params: dict,
                 lora_params: dict, tokens: jax.Array, targets: jax.Array,
                 mask: jax.Array | None = None) -> jax.Array:
    """Loss as a function of the adapter tree only (base frozen)."""
    merged = merge(base_params, lora_params, lcfg)
    return loss_fn(cfg, merged, tokens, targets, mask)
