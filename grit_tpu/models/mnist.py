"""MNIST-scale MLP — BASELINE configs 1/2 (the minimum end-to-end workload).

The reference validates its whole pipeline on small single-device training
pods before the flagship job; this model plays that role for the TPU build.
Data is a deterministic synthetic stream derived from (seed, step) — the
zero-egress environment has no dataset downloads, and deriving batches from
the step counter is what makes resume-parity exact: the restored process
regenerates the identical batch sequence with no dataloader state to dump.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from grit_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class MnistConfig:
    input_dim: int = 784
    hidden_dim: int = 256
    n_classes: int = 10
    n_hidden: int = 2


MNIST_RULES = ShardingRules(
    rules=[
        (r"w\d+$", P("fsdp", "model")),
        (r"b\d+$", P("model")),
        (r"w_out", P("fsdp", None)),
    ],
    default=P(),
)


def init_params(cfg: MnistConfig, key: jax.Array) -> dict:
    dims = [cfg.input_dim] + [cfg.hidden_dim] * cfg.n_hidden
    params: dict = {}
    keys = jax.random.split(key, cfg.n_hidden + 1)
    for i in range(cfg.n_hidden):
        params[f"w{i}"] = jax.random.normal(
            keys[i], (dims[i], dims[i + 1]), jnp.float32
        ) / jnp.sqrt(dims[i])
        params[f"b{i}"] = jnp.zeros(dims[i + 1], jnp.float32)
    params["w_out"] = jax.random.normal(
        keys[-1], (dims[-1], cfg.n_classes), jnp.float32
    ) / jnp.sqrt(dims[-1])
    params["b_out"] = jnp.zeros(cfg.n_classes, jnp.float32)
    return params


def forward(cfg: MnistConfig, params: dict, x: jax.Array) -> jax.Array:
    for i in range(cfg.n_hidden):
        x = jax.nn.relu(x @ params[f"w{i}"] + params[f"b{i}"])
    return x @ params["w_out"] + params["b_out"]


def loss_fn(cfg: MnistConfig, params: dict, batch: dict) -> jax.Array:
    logits = forward(cfg, params, batch["image"])
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def synthetic_batch(cfg: MnistConfig, rng: jax.Array, batch_size: int) -> dict:
    """Deterministic pseudo-MNIST: class-conditional gaussian blobs, so the
    loss genuinely decreases and a diverged resume is detectable."""
    k_lbl, k_img = jax.random.split(rng)
    labels = jax.random.randint(k_lbl, (batch_size,), 0, cfg.n_classes)
    centers = jax.nn.one_hot(labels, cfg.n_classes)
    proto = jnp.tile(centers, (1, cfg.input_dim // cfg.n_classes + 1))[
        :, : cfg.input_dim
    ]
    noise = jax.random.normal(k_img, (batch_size, cfg.input_dim)) * 0.5
    return {"image": proto + noise, "label": labels}
