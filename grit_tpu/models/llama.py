"""Llama-family decoder transformer, TPU-first.

Design choices (vs. a torch translation):

- **Stacked layer parameters + ``lax.scan``** over the layer axis: one
  compiled layer body regardless of depth — compile time and HLO size are
  O(1) in ``n_layers``, and every per-layer matmul keeps the same static
  shape for the MXU.
- **Pure pytree params** (nested dicts of ``jax.Array``): trivially
  shardable by keypath rules (:mod:`grit_tpu.parallel.sharding`) and
  trivially snapshottable (:mod:`grit_tpu.device.snapshot`) — the model
  *is* its checkpoint format.
- **bfloat16 activations / float32 master params** by default: matmuls hit
  the MXU in bf16; the optimizer update happens in f32.
- GQA (grouped-query attention), RoPE, RMSNorm, SwiGLU — the Llama-2
  architecture; 7B config matches the reference demo workload scale
  (falcon-7b LoRA, ``docs/experiments/checkpoint-restore-tuning-job.md:91``).

Attention runs through :func:`grit_tpu.ops.attention.causal_attention`,
which dispatches to a Pallas flash kernel on TPU and a pure-XLA fallback
elsewhere.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from grit_tpu.ops.attention import causal_attention
from grit_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    # Per-layer rematerialization: backward recomputes each layer's
    # activations instead of saving them — activation memory drops from
    # O(L) to O(1) layers, buying batch/sequence on a fixed-HBM chip for
    # ~1/3 more FLOPs (jax.checkpoint around the scan body).
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Sub-second-compile config for tests and the driver dryrun."""
        cfg = LlamaConfig(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=128, max_seq_len=128,
        )
        return replace(cfg, **overrides)


# Megatron-style partitioning over the (data, fsdp, model) mesh.
# Stacked layer leaves carry a leading n_layers axis (never sharded).
LLAMA_RULES = ShardingRules(
    rules=[
        (r"tok_emb", P("model", "fsdp")),           # (vocab, dim)
        (r"attn/wq", P(None, "fsdp", "model")),     # (L, dim, n_heads*hd)
        (r"attn/wk", P(None, "fsdp", "model")),
        (r"attn/wv", P(None, "fsdp", "model")),
        (r"attn/wo", P(None, "model", "fsdp")),     # (L, n_heads*hd, dim)
        (r"mlp/w_gate", P(None, "fsdp", "model")),  # (L, dim, hidden)
        (r"mlp/w_up", P(None, "fsdp", "model")),
        (r"mlp/w_down", P(None, "model", "fsdp")),  # (L, hidden, dim)
        (r"lm_head", P("fsdp", "model")),           # (dim, vocab)
        (r"norm", P()),
    ],
    default=P(),
)

# Batch rides both data-parallel axes; sequence stays unsharded here
# (sequence parallelism lives in ops/ring_attention for long-context).
BATCH_SPEC = P(("data", "fsdp"))


def init_params(cfg: LlamaConfig, key: jax.Array,
                with_mlp: bool = True) -> dict:
    """Initialize the full parameter pytree (stacked layer leaves).

    ``with_mlp=False`` skips the dense feed-forward weights — for model
    families that replace them (moe_llama) without paying a llama2-7b
    -scale throwaway allocation on the eager path."""
    k_emb, k_layers, k_head = jax.random.split(key, 3)
    hd = cfg.head_dim
    pd = cfg.param_dtype

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, pd) / jnp.sqrt(fan_in)).astype(pd)

    L = cfg.n_layers
    ks = jax.random.split(k_layers, 7)
    layers = {
        "attn": {
            "wq": dense(ks[0], (L, cfg.dim, cfg.n_heads * hd), cfg.dim),
            "wk": dense(ks[1], (L, cfg.dim, cfg.n_kv_heads * hd), cfg.dim),
            "wv": dense(ks[2], (L, cfg.dim, cfg.n_kv_heads * hd), cfg.dim),
            "wo": dense(ks[3], (L, cfg.n_heads * hd, cfg.dim), cfg.dim),
        },
        "attn_norm": jnp.ones((L, cfg.dim), pd),
        "mlp_norm": jnp.ones((L, cfg.dim), pd),
    }
    if with_mlp:
        layers["mlp"] = {
            "w_gate": dense(ks[4], (L, cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_up": dense(ks[5], (L, cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_down": dense(ks[6], (L, cfg.hidden_dim, cfg.dim),
                            cfg.hidden_dim),
        }
    return {
        "tok_emb": dense(k_emb, (cfg.vocab_size, cfg.dim), cfg.dim),
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), pd),
        "lm_head": dense(k_head, (cfg.dim, cfg.vocab_size), cfg.dim),
    }


def abstract_params(cfg: LlamaConfig) -> dict:
    """Shape/dtype skeleton of the param tree without allocating (for
    snapshot ``like=`` trees and sharding computation)."""
    return jax.eval_shape(partial(init_params, cfg), jax.random.PRNGKey(0))


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * scale.astype(jnp.float32)).astype(dt)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: (B, S, H, hd); positions: (B, S)."""
    hd = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, hd, 2, dtype=jnp.float32) / hd)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, hd/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _ragged_cache_write(cache: jax.Array, new: jax.Array, starts: jax.Array,
                        active: jax.Array) -> jax.Array:
    """Write row ``b``'s ``new[b]`` into ``cache[b]`` at its own offset
    ``starts[b]``; inactive rows are left byte-identical (their current
    content is re-written in place). Static shapes, B-row scatter cost —
    never a full-cache rewrite."""

    def row(c, kv, i, act):
        cur = lax.dynamic_slice_in_dim(c, i, kv.shape[0], axis=0)
        upd = jnp.where(act, kv, cur)
        return lax.dynamic_update_slice_in_dim(c, upd, i, axis=0)

    return jax.vmap(row)(cache, new, starts, active)


def _attn_block(cfg: LlamaConfig, p: dict, x: jax.Array, positions: jax.Array,
                cache: tuple[jax.Array, jax.Array, jax.Array] | None = None,
                attn_fn=None, active: jax.Array | None = None):
    """Self-attention; with ``cache=(k_cache, v_cache, cur_len)`` it runs
    the serving path: append new K/V at ``cur_len`` and attend into the
    cache. Returns (out, updated (k_cache, v_cache) or None).

    ``cur_len`` may be a scalar (lock-step batch: every row at the same
    position) or a per-row ``(B,)`` vector (continuous batching: each row
    at its own position; pass ``active`` so released slots' cache rows
    stay untouched). ONE implementation of projections/RoPE/output for
    both, so the paths cannot drift.

    ``attn_fn(q, k, v) -> out`` overrides the cache-less attention core —
    the long-context module runs ring attention (sequence parallelism)
    through this hook, the same pattern as ``mlp_fn``.
    """
    B, S, _ = x.shape
    hd = cfg.head_dim
    q = (x @ p["wq"].astype(cfg.dtype)).reshape(B, S, cfg.n_heads, hd)
    k = (x @ p["wk"].astype(cfg.dtype)).reshape(B, S, cfg.n_kv_heads, hd)
    v = (x @ p["wv"].astype(cfg.dtype)).reshape(B, S, cfg.n_kv_heads, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    if cache is None:
        out = (attn_fn or causal_attention)(q, k, v)
        new_cache = None
    else:
        k_cache, v_cache, cur_len = cache
        if jnp.ndim(cur_len) == 0:
            k_cache = lax.dynamic_update_slice(k_cache, k, (0, cur_len, 0, 0))
            v_cache = lax.dynamic_update_slice(v_cache, v, (0, cur_len, 0, 0))
        else:
            if active is None:
                active = jnp.ones((B,), bool)
            k_cache = _ragged_cache_write(k_cache, k, cur_len, active)
            v_cache = _ragged_cache_write(v_cache, v, cur_len, active)
        out = causal_attention(
            q, k_cache, v_cache, q_offset=cur_len, kv_len=cur_len + S
        )
        new_cache = (k_cache, v_cache)
    out = out.reshape(B, S, cfg.n_heads * hd)
    return out @ p["wo"].astype(cfg.dtype), new_cache


def _mlp_block(cfg: LlamaConfig, p: dict, x: jax.Array) -> jax.Array:
    gate = jax.nn.silu(x @ p["w_gate"].astype(cfg.dtype))
    up = x @ p["w_up"].astype(cfg.dtype)
    return (gate * up) @ p["w_down"].astype(cfg.dtype)


def layer_body(cfg: LlamaConfig, layer_params: dict, x: jax.Array,
               positions: jax.Array, mlp_fn=None, attn_fn=None):
    """One transformer layer (attn_norm → attn → residual → mlp_norm →
    FFN → residual). THE single copy of the layer math: forward_trunk,
    the pipeline stages, and (via the same hooks) the MoE/SP families
    all run this. Returns ``(h, aux)``; dense FFN emits aux=0."""

    attn_out, _ = _attn_block(
        cfg, layer_params["attn"],
        rms_norm(x, layer_params["attn_norm"], cfg.norm_eps),
        positions, attn_fn=attn_fn,
    )
    h = x + attn_out
    normed = rms_norm(h, layer_params["mlp_norm"], cfg.norm_eps)
    if mlp_fn is None:
        y, aux = _mlp_block(cfg, layer_params["mlp"], normed), jnp.zeros(())
    else:
        y, aux = mlp_fn(layer_params, normed)
    return h + y.astype(h.dtype), aux


def forward_trunk(cfg: LlamaConfig, params: dict, tokens: jax.Array,
                  mlp_fn=None, attn_fn=None) -> tuple[jax.Array, jax.Array]:
    """Shared decoder trunk: tokens (B, S) int32 → (logits (B, S, vocab)
    f32, per-layer aux stack). The layer stack is a ``lax.scan`` over
    stacked weights — compiled once, not unrolled (XLA-friendly control
    flow; no Python loop in the trace).

    ``mlp_fn(layer_params, normed) -> (y, aux)`` overrides the
    feed-forward block (moe_llama trains through this exact trunk, same
    contract as :func:`decode`'s hook, so positions/scan/logit semantics
    can never drift between the families). Dense default emits aux=0.
    """
    x, aux_per_layer = forward_hidden(cfg, params, tokens,
                                      mlp_fn=mlp_fn, attn_fn=attn_fn)
    logits = x @ params["lm_head"].astype(cfg.dtype)
    return logits.astype(jnp.float32), aux_per_layer


def forward_hidden(cfg: LlamaConfig, params: dict, tokens: jax.Array,
                   mlp_fn=None, attn_fn=None) -> tuple[jax.Array, jax.Array]:
    """Decoder trunk up to (and including) the final norm — the single
    copy of the scan/positions/remat semantics. :func:`forward_trunk`
    projects its output through ``lm_head``; the chunked-CE path
    (:func:`chunked_token_cross_entropy`) projects it per chunk instead.
    Returns ``(hidden (B, S, dim), per-layer aux stack)``."""
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    x = params["tok_emb"].astype(cfg.dtype)[tokens]

    def body(carry, layer_params):
        return layer_body(cfg, layer_params, carry, positions,
                          mlp_fn=mlp_fn, attn_fn=attn_fn)

    if cfg.remat:
        body = jax.checkpoint(body)
    x, aux_per_layer = lax.scan(body, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.norm_eps), aux_per_layer


def forward(cfg: LlamaConfig, params: dict, tokens: jax.Array) -> jax.Array:
    """Training/prefill forward: tokens (B, S) int32 → logits (B, S, vocab)."""
    return forward_trunk(cfg, params, tokens)[0]


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int | None = None) -> dict:
    """Allocate an all-layers KV cache: leaves (L, B, max_len, kv_heads, hd)."""
    max_len = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def decode(cfg: LlamaConfig, params: dict, tokens: jax.Array,
           cache: dict, mlp_fn=None) -> tuple[jax.Array, dict]:
    """Serving step: append ``tokens`` (B, S) at ``cache['length']``, attend
    into the cache, return (logits (B, S, vocab), updated cache).

    Works for both prefill (S = prompt length) and autoregressive decode
    (S = 1) — same compiled program per S. ``mlp_fn(layer_params, normed)``
    overrides the feed-forward block (moe_llama serves through this exact
    function with an expert-MLP closure, so cache/positions/clamp
    semantics can never drift between the families).
    """
    B, S = tokens.shape
    cur_len = cache["length"]
    positions = jnp.broadcast_to(cur_len + jnp.arange(S), (B, S))
    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    if mlp_fn is None:
        def mlp_fn(layer_params, normed):  # noqa: E306 - default dense FFN
            return _mlp_block(cfg, layer_params["mlp"], normed)

    def body(carry, xs):
        layer_params, kc, vc = xs
        attn_out, (kc, vc) = _attn_block(
            cfg, layer_params["attn"],
            rms_norm(carry, layer_params["attn_norm"], cfg.norm_eps),
            positions, cache=(kc, vc, cur_len),
        )
        h = carry + attn_out
        h = h + mlp_fn(
            layer_params, rms_norm(h, layer_params["mlp_norm"], cfg.norm_eps)
        ).astype(h.dtype)
        return h, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": cur_len + S}


def decode_ragged(cfg: LlamaConfig, params: dict, tokens: jax.Array,
                  cache: dict, lengths: jax.Array, active: jax.Array,
                  mlp_fn=None) -> tuple[jax.Array, dict]:
    """Continuous-batching serving step: one new token per slot, each slot
    at its OWN position in the cache.

    Args:
      tokens: (B, 1) int32 — each active slot's last token.
      cache: :func:`init_kv_cache` leaves; ``cache['length']`` is ignored
        (per-slot ``lengths`` replaces the batch-uniform scalar).
      lengths: (B,) int32 — valid KV entries per slot (= position of the
        token being decoded).
      active: (B,) bool — inactive slots compute (static shapes: the batch
        is the compiled program's shape) but their cache rows are left
        untouched, so joining/leaving slots never perturbs neighbors.

    Returns (logits (B, 1, vocab), updated cache). All batch rows run the
    same program — raggedness is masking, never a shape, so one compiled
    step serves any mix of sequence positions (XLA-friendly continuous
    batching).
    """
    B, S = tokens.shape
    if S != 1:
        raise ValueError("decode_ragged is the per-token step; use "
                         "decode() for prefill")
    positions = lengths[:, None]  # (B, 1)
    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    if mlp_fn is None:
        def mlp_fn(layer_params, normed):  # noqa: E306 - default dense FFN
            return _mlp_block(cfg, layer_params["mlp"], normed)

    def body(carry, xs):
        layer_params, kc, vc = xs
        attn_out, (kc, vc) = _attn_block(
            cfg, layer_params["attn"],
            rms_norm(carry, layer_params["attn_norm"], cfg.norm_eps),
            positions, cache=(kc, vc, lengths), active=active,
        )
        h = carry + attn_out
        h = h + mlp_fn(
            layer_params, rms_norm(h, layer_params["mlp_norm"], cfg.norm_eps)
        ).astype(h.dtype)
        return h, (kc, vc)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": cache["length"]}


def token_cross_entropy(logits: jax.Array, targets: jax.Array,
                        mask: jax.Array | None = None) -> jax.Array:
    """Mean next-token cross-entropy (f32 accumulation); shared by every
    decoder family (llama, moe_llama) so masking semantics can't drift."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def loss_fn(cfg: LlamaConfig, params: dict, tokens: jax.Array,
            targets: jax.Array, mask: jax.Array | None = None,
            ce_chunk: int | None = None) -> jax.Array:
    """Mean next-token cross-entropy (f32 accumulation).

    ``ce_chunk`` switches to the chunked vocab projection + CE
    (:func:`chunked_token_cross_entropy`): the full-sequence path
    materializes f32 logits of shape (B, S, vocab) — at train shapes
    that's multi-GB of HBM written, read by log_softmax, and saved for
    backward, a pure bandwidth tax the MXU never sees. Chunking bounds
    it to (B, ce_chunk, vocab) per scan step and rematerializes per
    chunk in backward. Same value (f32 accumulation, exact token count)
    up to sum reassociation.
    """
    if ce_chunk is None:
        return token_cross_entropy(
            forward(cfg, params, tokens), targets, mask)
    hidden, _ = forward_hidden(cfg, params, tokens)
    return chunked_token_cross_entropy(
        hidden, params["lm_head"].astype(cfg.dtype), targets, mask,
        chunk=ce_chunk)


def chunked_token_cross_entropy(
    hidden: jax.Array, lm_head: jax.Array, targets: jax.Array,
    mask: jax.Array | None = None, chunk: int = 4096,
) -> jax.Array:
    """CE over chunks of flattened token rows: project ``chunk`` rows of
    (B·S, dim) → logits → NLL sums, accumulated in f32 under a
    ``lax.scan`` whose body is rematerialized — backward recomputes each
    chunk's logits instead of holding (B·S, vocab) residuals. Peak logit
    footprint is (chunk, vocab) regardless of batch/seq."""
    B, S, D = hidden.shape
    N = B * S
    rows = hidden.reshape(N, D)
    t_flat = targets.reshape(N)
    m_flat = (jnp.ones((N,), jnp.float32) if mask is None
              else mask.reshape(N).astype(jnp.float32))
    if N % chunk != 0:
        # Static shapes only (XLA): fall back rather than pad-and-mask.
        logits = (rows @ lm_head).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, t_flat[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * m_flat) / jnp.maximum(jnp.sum(m_flat), 1.0)
    n = N // chunk

    @jax.checkpoint
    def body(carry, xs):
        h, t, m = xs
        logits = (h @ lm_head).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        total, count = carry
        return (total + jnp.sum(nll * m), count + jnp.sum(m)), None

    (total, count), _ = lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (rows.reshape(n, chunk, D), t_flat.reshape(n, chunk),
         m_flat.reshape(n, chunk)),
    )
    return total / jnp.maximum(count, 1.0)


