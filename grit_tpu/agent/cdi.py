"""CDI spec generator — deterministic TPU device injection for restores.

The reference deploys NVIDIA's device plugin in CDI mode because CRIU-style
restore needs device injection to be *reproducible*: the restored container
must see the same device nodes in the same order as the source (reference
``charts/.../nvidia-device-plugin-cdi.yaml``, rationale in
``docs/proposals/...md:263-270``). For TPU v5e the device nodes are
``/dev/accel0..N`` plus ``/dev/vfio/*``; this module writes a CDI spec that
pins enumeration to numeric (torus) order so chip *i* means the same
physical position on both ends of a migration.

Run as ``python -m grit_tpu.agent.cdi`` (the chart's DaemonSet), or call
:func:`generate_spec` directly.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

from grit_tpu.api import config

CDI_VERSION = "0.6.0"
KIND = "grit.tpu/chip"


def discover_accel_devices(dev_root: str = "/dev") -> list[str]:
    """TPU device nodes under ``dev_root``, in deterministic numeric order."""
    out = []
    try:
        names = os.listdir(dev_root)
    except OSError:
        return []
    for name in names:
        m = re.fullmatch(r"accel(\d+)", name)
        if m:
            out.append((int(m.group(1)), os.path.join(dev_root, name)))
    return [p for _, p in sorted(out)]


def generate_spec(dev_root: str = "/dev") -> dict:
    """CDI spec mapping chip ordinal → device node (+ vfio group if any)."""
    devices = []
    for ordinal, path in enumerate(discover_accel_devices(dev_root)):
        devices.append(
            {
                "name": str(ordinal),
                "containerEdits": {
                    "deviceNodes": [
                        # The container-visible path is the *ordinal* name:
                        # chip i is /dev/accel<i> in every container, no
                        # matter how the host enumerated it.
                        {"path": f"/dev/accel{ordinal}", "hostPath": path}
                    ]
                },
            }
        )
    return {
        "cdiVersion": CDI_VERSION,
        "kind": KIND,
        "devices": devices,
    }


def write_spec(cdi_dir: str = "/var/run/cdi", dev_root: str = "/dev",
               spec: dict | None = None) -> str:
    """Atomically (tmp+rename) write the spec; returns its path."""
    if spec is None:
        spec = generate_spec(dev_root)
    os.makedirs(cdi_dir, exist_ok=True)
    path = os.path.join(cdi_dir, "grit-tpu.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(spec, f, indent=2)
    os.rename(tmp, path)
    return path


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="grit-tpu-cdi")
    p.add_argument("--cdi-dir", default="/var/run/cdi")
    p.add_argument("--dev-root", default=config.TPU_DEV_ROOT.get())
    p.add_argument("--once", action="store_true",
                   help="write once and exit (default: rewrite on change "
                        "every --interval seconds)")
    p.add_argument("--interval", type=float, default=30.0)
    args = p.parse_args(argv)

    last = None
    while True:
        spec = generate_spec(args.dev_root)
        if spec != last:
            # Write the spec we compared, not a fresh rescan — a device
            # change between scans must not leave disk diverged from `last`.
            path = write_spec(args.cdi_dir, args.dev_root, spec=spec)
            print(f"grit-tpu-cdi: wrote {path} "
                  f"({len(spec['devices'])} chips)", flush=True)
            last = spec
        if args.once:
            return 0
        import time

        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
