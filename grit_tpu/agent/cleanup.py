"""Agent cleanup driver: delete a checkpoint's data (TTL GC).

The reference has no data lifecycle at all — checkpoint images accumulate
on the PVC until an operator hand-deletes them. grit-tpu's
``Checkpoint.spec.ttlSecondsAfterFinished`` drives this third agent action
(after checkpoint/restore): remove the PVC payload directory and the host
work directory for one checkpoint, idempotently (a retried GC Job must
succeed on already-missing paths).
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass


@dataclass
class CleanupOptions:
    # Host work path <host-path>/<ns>/<ckpt-name> (source node).
    work_dir: str
    # PVC payload dir <pvc-mount>/<ns>/<ckpt-name>.
    dst_dir: str


def run_cleanup(opts: CleanupOptions) -> dict:
    """Delete both directories; returns what was actually removed.

    Paths that don't exist are fine (idempotent retry); anything else —
    permission errors, a file where a dir is expected — raises, failing
    the Job loudly rather than reporting a GC that didn't happen.
    """
    removed = {}
    for label, path in (("work", opts.work_dir), ("pvc", opts.dst_dir)):
        if not path or not os.path.lexists(path):
            continue  # already gone: idempotent retry
        if not os.path.isdir(path) or os.path.islink(path):
            raise NotADirectoryError(
                f"cleanup target {path} is not a directory — refusing to "
                "report a GC that did not happen"
            )
        shutil.rmtree(path)
        removed[label] = path
    return removed
