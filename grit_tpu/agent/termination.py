"""Agent termination contract: typed exit codes + machine-readable reason.

PR-2 left the control plane blind to *why* an agent Job died: every
failure was one opaque nonzero status, so the Job's ``backoffLimit``
burned retries on terminal causes (missing pod, bad config) and the
manager's ``_checkpointing`` collapsed everything into a dead-end
``FAILED``. This module is the agent's half of the fix:

- distinct exit codes — :data:`EXIT_RETRIABLE` (75, EX_TEMPFAIL) for
  causes a fresh attempt can clear, :data:`EXIT_TERMINAL` (64,
  EX_USAGE-adjacent) for causes it cannot;
- a JSON termination-reason file (:data:`TERMINATION_REASON_FILE`)
  written into the host work dir before exit. The manager-side watchdog
  reads it (the work dir doubles as the node-local termination-message
  channel; in a kubelet deployment the same payload is what you would
  put in the container's terminationMessagePath) and classifies the
  retry without guessing from the exit status alone.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass

EXIT_OK = 0
EXIT_USAGE = 2          # bad CLI invocation (argparse-level)
EXIT_RETRIABLE = 75     # EX_TEMPFAIL: transient — a re-created Job may pass
EXIT_TERMINAL = 64      # config/state error no retry can fix

TERMINATION_REASON_FILE = ".grit-termination.json"


@dataclass
class TerminationReason:
    reason: str          # short CamelCase cause, e.g. "WireError"
    message: str
    retriable: bool
    exit_code: int
    action: str = ""     # checkpoint | restore | cleanup | abort
    time: float = 0.0    # unix seconds the agent wrote this


# Exception types whose cause no amount of re-running fixes: bad
# invocation, unusable node configuration, or corrupt inputs that a fresh
# Job would read identically. Everything else — wire drops, transient
# I/O, timeouts, injected chaos — defaults to retriable; the manager's
# bounded attempt counter caps the pathological case.
_TERMINAL_TYPES = ("ValueError", "KeyError", "TypeError",
                   "NotADirectoryError", "FaultSyntaxError")
_TERMINAL_SUBSTRINGS = (
    "no running containers",      # target pod gone/never matched
    "requires usable criu",       # node missing its checkpoint engine
    "must be checkpoint",         # CLI misuse
)


def classify_exception(exc: BaseException) -> tuple[str, bool]:
    """``(reason, retriable)`` for an agent failure."""
    reason = type(exc).__name__
    if reason in _TERMINAL_TYPES:
        return reason, False
    msg = str(exc)
    if any(s in msg for s in _TERMINAL_SUBSTRINGS):
        return reason, False
    return reason, True


def exit_code_for(retriable: bool) -> int:
    return EXIT_RETRIABLE if retriable else EXIT_TERMINAL


def write_termination(
    work_dir: str, reason: str, message: str, retriable: bool,
    action: str = "",
) -> TerminationReason | None:
    """Persist the reason file (fsynced — the Job may be killed right
    after). Returns what was written, or None when there is nowhere to
    write (no work dir: classification still rides the exit code)."""
    record = TerminationReason(
        reason=reason, message=message[:2000], retriable=retriable,
        exit_code=exit_code_for(retriable), action=action, time=time.time(),
    )
    if not work_dir:
        return None
    try:
        os.makedirs(work_dir, exist_ok=True)
        path = os.path.join(work_dir, TERMINATION_REASON_FILE)
        with open(path, "w") as f:
            json.dump(asdict(record), f)
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        return None  # reason file is best-effort; the exit code remains
    return record


def read_termination(work_dir: str) -> TerminationReason | None:
    """The reason a previous agent attempt recorded, or None (absent /
    unreadable / malformed — callers then classify by exit status)."""
    try:
        with open(os.path.join(work_dir, TERMINATION_REASON_FILE)) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(raw, dict) or "reason" not in raw:
        return None
    try:
        return TerminationReason(
            reason=str(raw.get("reason", "")),
            message=str(raw.get("message", "")),
            retriable=bool(raw.get("retriable", True)),
            exit_code=int(raw.get("exit_code", EXIT_RETRIABLE)),
            action=str(raw.get("action", "")),
            time=float(raw.get("time", 0.0)),
        )
    except (TypeError, ValueError):
        return None


def clear_termination(work_dir: str) -> None:
    """Remove a previous attempt's reason file (each attempt must speak
    for itself — a stale file must not classify a newer failure)."""
    try:
        os.unlink(os.path.join(work_dir, TERMINATION_REASON_FILE))
    except OSError:
        pass
