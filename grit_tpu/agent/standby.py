"""Preemption-armed standby: always-warm pre-copy + arm/fire protocol.

Production TPU fleets live on spot/preemptible capacity where reclaim
notices arrive seconds before the kill. A cold migration starts the whole
pre-copy round loop *inside* that window; StandbyCheckpoint inverts the
flow (ROADMAP item 5; PhoenixOS validates the speculative variant, CRIUgpu
the incremental-dump cadence): after the round-0 full dump the agent stays
resident and runs the PR-7 delta-dump→flatten loop forever on a slow
cadence, keeping the destination's flattened base ≤2 hops deep — so the
notice pays only the final momentary-quiesce delta + blackout.

Three pieces:

- **The governor** (:func:`standby_governor`, a pure function mirroring
  ``precopy_should_continue``): ship a probed delta only when its bytes
  justify the upload against the observed link rate; back off
  exponentially on quiet workloads (each momentary-quiesce probe costs
  the workload a step boundary), tighten to the floor within one interval
  when the dirty rate rises, and degrade LOUDLY to "stale but armed" —
  never shipping uncatchable deltas — when the workload dirties faster
  than the link ships.
- **The arm/fire protocol** (:class:`FireSignal`): a reclaim notice
  reaches the armed agent as the ``grit.dev/fire`` annotation on its own
  Job (stamped by the manager's preemption watcher / the drain
  controller's cordon path / an operator), as a ``.grit-fire`` file in
  the work or PVC dir (the no-apiserver vehicle), or as SIGTERM (what
  the kubelet actually delivers on node shutdown). Firing runs only the
  final delta + CRIU dump + commit through the ordinary
  :func:`~grit_tpu.agent.checkpoint.run_checkpoint` machinery.
- **Robustness as the contract**: staleness (seconds since the last
  flattened base) and the unshipped dirty backlog ride the progress
  snapshot (``status.progress.standby``) and the
  ``grit_standby_staleness_seconds`` / ``grit_standby_delta_backlog_
  bytes`` gauges; the governor stamps a tick timestamp every fire poll
  so the manager watchdog's ``StandbyStale`` verdict can shoot a frozen
  governor without ever shooting a healthy idle interval; and every
  round ship is crash-ordered (data files first, manifests atomically
  last) so a SIGKILL at ANY instant leaves the destination a valid
  previous base — degraded-but-correct, the whole point of keeping a
  warm one.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import signal
import threading
import time

from grit_tpu import deltachain, faults
from grit_tpu.agent.checkpoint import (
    HBM_SUBDIR,
    CheckpointOptions,
    DeviceCheckpointHook,
    NoopDeviceHook,
    PRECOPY_SUFFIX,
    _dump_precopy_round,
    _mirror_tokens,
    _mirrored_skip,
    _precopy_base,
    _precopy_measurable_bytes,
    run_checkpoint,
    run_precopy,
)
from grit_tpu.agent.copy import TransferStats, transfer_data, tree_state
from grit_tpu.api import config
from grit_tpu.api.constants import FIRE_ANNOTATION
from grit_tpu.cri.runtime import FakeRuntime, TaskState
from grit_tpu.metadata import FIRE_FILE
from grit_tpu.obs import flight, progress
from grit_tpu.obs.metrics import (
    STANDBY_DELTA_BACKLOG_BYTES,
    STANDBY_STALENESS_SECONDS,
)

log = logging.getLogger(__name__)

#: Progress-snapshot phase an armed standby reports: the watchdog's
#: ProgressStalled exemption and the controller's Checkpointing→Standby
#: transition both key on this literal.
STANDBY_PHASE = "standby"


# -- the governor, as a pure function -----------------------------------------


@dataclasses.dataclass(frozen=True)
class GovernorDecision:
    ship: bool
    next_interval_s: float
    reason: str
    #: Loud degrade (dirty rate at/above link rate: the base will go
    #: stale no matter what we ship). None on healthy decisions.
    degraded: str | None = None


def standby_governor(
    dirty_bytes: int,
    interval_s: float,
    link_bps: float | None,
    *,
    prev_interval_s: float,
    min_interval_s: float,
    max_interval_s: float,
    backoff: float,
    min_delta_bytes: int,
) -> GovernorDecision:
    """One governed-round decision: ship the probed delta or carry it as
    backlog, and pick the next probe interval.

    Inputs are the probe's measurements: ``dirty_bytes`` the round's
    physical delta, over ``interval_s`` of workload time since the
    previous cut; ``link_bps`` the cumulative observed upload rate (None
    until round 0 measured one). Clamps defend against counter resets
    and agent restarts: negative dirty bytes read as zero, a
    non-positive interval as one millisecond, and the returned interval
    always lands inside [min, max].

    The cadence policy, in priority order:

    - **uncatchable** — dirty rate at/above the link rate: shipping
      would chase its own tail forever; carry the delta as backlog,
      stay at the floor cadence (re-probe soon: bursts end), and
      degrade loudly ("stale but armed").
    - **quiet** — delta below the ship threshold: back off
      exponentially toward the ceiling (each probe quiesces the
      workload for a step boundary; an idle workload deserves to be
      left alone).
    - **dirty** — a shippable delta: ship, and tighten the cadence back
      to the floor within this one decision (a workload that just got
      busy must not wait out a built-up backoff before its next round).
    """
    min_interval_s = max(0.001, float(min_interval_s))
    max_interval_s = max(min_interval_s, float(max_interval_s))
    backoff = max(1.0, float(backoff))
    dirty = max(0, int(dirty_bytes))  # counter reset/restart clamp
    interval = max(1e-3, float(interval_s))
    prev = min(max(float(prev_interval_s), min_interval_s), max_interval_s)

    dirty_rate = dirty / interval
    if dirty and link_bps is not None and dirty_rate >= link_bps:
        return GovernorDecision(
            ship=False,
            next_interval_s=min_interval_s,
            reason=(f"dirty rate {dirty_rate / 1e6:.2f} MB/s >= link rate "
                    f"{link_bps / 1e6:.2f} MB/s"),
            degraded=(
                f"dirty rate {dirty_rate / 1e6:.2f} MB/s >= link rate "
                f"{link_bps / 1e6:.2f} MB/s — standby cannot keep the "
                "base warm; staying armed with a growing final-delta "
                "backlog"),
        )
    if dirty < max(1, int(min_delta_bytes)):
        return GovernorDecision(
            ship=False,
            next_interval_s=min(prev * backoff, max_interval_s),
            reason=(f"delta {dirty} B below ship threshold "
                    f"{min_delta_bytes} B — backing off"),
        )
    return GovernorDecision(
        ship=True,
        next_interval_s=min_interval_s,
        reason=f"shipping {dirty} B delta "
               f"({dirty_rate / 1e6:.2f} MB/s dirty rate)",
    )


# -- the fire signal ----------------------------------------------------------

# Process-level SIGTERM latch: the kubelet's shutdown signal IS a
# reclaim notice for an armed standby agent.
_sigterm_fired = threading.Event()


def _on_sigterm(signum, frame):  # noqa: ARG001
    _sigterm_fired.set()


def arm_sigterm_fire() -> bool:
    """Turn SIGTERM into a fire signal (main thread only; returns
    whether the handler installed). The agent CLI arms this for standby
    runs — a spot VM's shutdown sequence TERMs the agent pod seconds
    before the kill, which is exactly the notice window the warm base
    exists to exploit."""
    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
        return True
    except (ValueError, OSError):  # not the main thread / exotic host
        return False


def reset_sigterm_fire() -> None:
    """Forget a latched SIGTERM (tests)."""
    _sigterm_fired.clear()


class FireSignal:
    """The armed agent's view of the arm/fire protocol: polled between
    governed rounds (and inside every idle wait slice), returns the fire
    reason once any vehicle delivered one, None while armed.

    Vehicles, cheapest first: a latched SIGTERM; a ``.grit-fire`` file
    in the work dir or the shared PVC dir (content = reason; the
    no-apiserver path — tests, the harness, node-local tooling); the
    ``grit.dev/fire`` annotation on the agent's own Job, read through
    the same cluster handle the heartbeat lease renews through."""

    def __init__(self, work_dir: str, dst_dir: str = "",
                 cluster=None, job_name: str = "",
                 namespace: str = "default") -> None:
        self.work_dir = work_dir
        self.dst_dir = dst_dir
        self.cluster = cluster
        self.job_name = job_name
        self.namespace = namespace
        self._reason: str | None = None
        # The annotation vehicle is an apiserver GET; an armed agent
        # polls for days, so it runs on the heartbeat-lease cadence
        # (first check polls immediately), not the ~1 s fire-poll slice
        # the O(local) vehicles use. The notice window is still covered:
        # the kubelet's SIGTERM and the fire file arrive at reclaim
        # time, and the annotation path's extra seconds ride inside the
        # window the warm base already bought.
        self._next_ann_poll = 0.0

    @classmethod
    def from_env(cls, work_dir: str, dst_dir: str = "",
                 cluster=None) -> "FireSignal":
        """The production wiring: Job coordinates from the same env the
        heartbeat lease uses; the in-cluster handle is built lazily only
        when a Job name exists (harness runs poll files alone)."""
        job = config.JOB_NAME.get()
        if job and cluster is None:
            from grit_tpu.agent.lease import _in_cluster_handle  # noqa: PLC0415

            cluster = _in_cluster_handle()
        return cls(work_dir, dst_dir=dst_dir, cluster=cluster,
                   job_name=job or "",
                   namespace=config.JOB_NAMESPACE.get())

    def _file_reason(self, directory: str) -> str | None:
        if not directory:
            return None
        path = os.path.join(directory, FIRE_FILE)
        try:
            with open(path) as f:
                return (f.read().strip() or "fire-file")
        except OSError:
            return None

    def check(self) -> str | None:
        if self._reason is not None:
            return self._reason  # latched: fire is one-way
        reason: str | None = None
        if _sigterm_fired.is_set():
            reason = "SIGTERM"
        if reason is None:
            reason = self._file_reason(self.work_dir) \
                or self._file_reason(self.dst_dir)
        if reason is None and self.cluster is not None and self.job_name \
                and time.monotonic() >= self._next_ann_poll:
            self._next_ann_poll = time.monotonic() + max(
                1.0, float(config.HEARTBEAT_PERIOD_S.get()))
            try:
                job = self.cluster.try_get("Job", self.job_name,
                                           self.namespace)
            except Exception:  # noqa: BLE001 — a flaky poll must not kill the arm
                job = None
            if job is not None:
                ann = job.metadata.annotations.get(FIRE_ANNOTATION, "")
                if ann:
                    reason = ann
        if reason is not None:
            self._reason = reason
        return reason


# grit: atomic-commit
def write_fire_file(directory: str, reason: str = "fire") -> str:
    """Drop the fire file (tests / node tooling); returns its path."""
    path = os.path.join(directory, FIRE_FILE)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(reason)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# -- staleness/backlog publication --------------------------------------------

# The armed loop's module-level mirror, aged forward by the sampler
# callback between governor ticks (staleness grows with wall time; a
# gauge set only at tick time would understate it for the whole backed-
# off interval).
_arm_lock = threading.Lock()
_armed: dict | None = None  # grit: guarded-by(_arm_lock)


def _publish_arm_state(tracker, *, last_base_wall: float,
                       backlog_bytes: int, rounds_shipped: int,
                       rounds_skipped: int, degraded: str | None) -> None:
    now = time.time()
    staleness = max(0.0, now - last_base_wall)
    with _arm_lock:
        global _armed
        _armed = {"last_base_wall": last_base_wall,
                  "backlog": backlog_bytes}
    STANDBY_STALENESS_SECONDS.set(staleness)
    STANDBY_DELTA_BACKLOG_BYTES.set(backlog_bytes)
    tracker.set_standby(
        lastBaseAt=round(last_base_wall, 3),
        stalenessSeconds=round(staleness, 3),
        backlogBytes=int(backlog_bytes),
        roundsShipped=rounds_shipped,
        roundsSkipped=rounds_skipped,
        tickAt=round(now, 3),
        **({"degraded": degraded} if degraded else {}),
    )


def sample_standby() -> None:
    """Periodic-sampler callback: age the staleness gauge forward from
    the last flattened base while the governor sleeps out a (possibly
    minutes-long) backed-off interval."""
    with _arm_lock:
        state = dict(_armed) if _armed is not None else None
    if state is None:
        return
    STANDBY_STALENESS_SECONDS.set(
        max(0.0, time.time() - state["last_base_wall"]))
    STANDBY_DELTA_BACKLOG_BYTES.set(state["backlog"])


def _disarm_gauges() -> None:
    with _arm_lock:
        global _armed
        _armed = None


# -- crash-ordered round shipping ---------------------------------------------

_MANIFEST_NAMES = (deltachain.MANIFEST_FILE, deltachain.COMMIT_FILE)


# grit: atomic-commit
def _atomic_copy(src: str, dst: str) -> int:
    """Small-file copy that lands atomically at ``dst`` (write tmp,
    fsync, rename) — the manifest leg of a round ship. A SIGKILL at any
    instant leaves either the old or the new manifest, never a torn
    one."""
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    tmp = f"{dst}.standby-tmp-{os.getpid()}"
    with open(src, "rb") as fsrc, open(tmp, "wb") as fdst:
        data = fsrc.read()
        fdst.write(data)
        fdst.flush()
        os.fsync(fdst.fileno())
    os.replace(tmp, dst)
    return len(data)


# grit: data-ship
def _ship_round_ordered(
    opts: CheckpointOptions, shipped: dict[str, tuple[int, int]],
) -> tuple[TransferStats, dict[str, tuple[int, int]]]:
    """Upload everything that changed since ``shipped``, in crash-safe
    order: data files first (flatten gives every round's physical file a
    FRESH name, so nothing the destination's current manifest references
    is ever overwritten), then each changed MANIFEST/COMMIT atomically.
    A kill mid-data-write leaves the old manifest plus one torn
    unreferenced file; a kill between the passes leaves the old manifest
    plus complete unreferenced files — the destination restores the
    previous flattened base either way. Returns ``(stats, new_shipped
    capture)``."""
    state = tree_state(opts.work_dir)
    manifest_rels = {rel for rel in state
                     if os.path.basename(rel) in _MANIFEST_NAMES}
    # Pass 1 (bulk data): pin every manifest file to its CURRENT
    # identity in the skip set so transfer_data cannot ship it early.
    skip = dict(shipped)
    skip.update({rel: state[rel] for rel in manifest_rels})
    stats = transfer_data(opts.work_dir, opts.dst_dir, direction="upload",
                          skip_unchanged=skip)
    # Pass 2 (metadata): only manifests that actually changed.
    for rel in sorted(manifest_rels):
        if shipped.get(rel) == state[rel]:
            continue
        n = _atomic_copy(os.path.join(opts.work_dir, rel),
                         os.path.join(opts.dst_dir, rel))
        stats.bytes += n
        stats.files += 1
        progress.add_bytes(progress.ROLE_SOURCE, n)
    return stats, state


def _prune_destination_base(opts: CheckpointOptions,
                            runtime: FakeRuntime) -> None:
    """GC destination data files the freshly-shipped manifest no longer
    references (their source twins were pruned after flatten). Errors
    are swallowed per file: pruning is hygiene, never worth failing an
    armed standby over."""
    for container in runtime.list_containers(
            opts.pod_name, opts.pod_namespace, TaskState.RUNNING):
        dst_base = os.path.join(
            opts.dst_dir, container.name + PRECOPY_SUFFIX, HBM_SUBDIR)
        if not os.path.isfile(os.path.join(dst_base,
                                           deltachain.MANIFEST_FILE)):
            continue
        try:
            deltachain.prune_unreferenced(dst_base)
        except (OSError, ValueError):
            continue


# -- the standby loop ---------------------------------------------------------


def _base_bloat_exceeded(opts: CheckpointOptions, runtime: FakeRuntime,
                         factor: float) -> bool:
    """Whether any container's rolling base accumulated more disk bytes
    than ``factor`` × its logical state (superseded chunk bytes inside
    still-referenced files, which file-level pruning cannot reclaim) —
    the trigger for a full-dump rebase round."""
    if factor <= 0:
        return False
    for container in runtime.list_containers(
            opts.pod_name, opts.pod_namespace, TaskState.RUNNING):
        base = _precopy_base(opts.work_dir, container.name)
        if base is None:
            continue
        try:
            logical = deltachain.manifest_physical_nbytes(base)
            disk = deltachain.data_disk_bytes(base)
        except (OSError, ValueError, KeyError):
            continue
        if logical > 0 and disk > factor * logical:
            return True
    return False


def _round_dirty_bytes(pending) -> int:
    """Physical delta bytes of one probe, preferring the manifest's
    device-side dirty accounting (exact, and cheap) over re-deriving it."""
    total = 0
    for _base, round_hbm, _round_dir, nbytes in pending:
        dirty = None
        try:
            manifest = deltachain._load_manifest(round_hbm)
            rec = manifest.get("dirty")
            if isinstance(rec, dict) and "bytes" in rec:
                dirty = int(rec["bytes"])
        except (OSError, ValueError, KeyError, TypeError):
            dirty = None
        total += dirty if dirty is not None else nbytes
    return total


def run_standby_checkpoint(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    device_hook: DeviceCheckpointHook | None = None,
    fire: FireSignal | None = None,
    lease=None,
    info: dict | None = None,
    stop: threading.Event | None = None,
    max_rounds: int | None = None,
) -> TransferStats | None:
    """Arm, hold, fire: the StandbyCheckpoint agent driver.

    Round 0 is the live full dump + upload (identical to pre-copy's
    round 0); then the loop runs governed delta rounds FOREVER — probe
    (momentary quiesce delta dump), ask :func:`standby_governor`, ship
    (flatten → prune → crash-ordered upload → destination prune) or
    carry as backlog — until a fire signal arrives, at which point only
    the final delta + blackout runs through :func:`run_checkpoint`
    (``preshipped`` = everything the warm base already holds).

    ``stop``/``max_rounds`` are test/bench bounds: a set stop event or an
    exhausted round budget disarms and returns None (no blackout ran).
    ``info`` (optional dict) receives the arm/fire evidence: rounds
    shipped/skipped, per-round deltas, staleness + backlog at fire,
    the fire reason, rebases, any loud degrade, and ``probe_mode``
    ("speculative" = governed probes run as non-parking clone dumps
    that never cost the workload a step boundary; "parked" = the
    momentary-quiesce probes of a GRIT_SNAP_SPECULATE=0 workload)."""
    from grit_tpu.obs import sampler as obs_sampler  # noqa: PLC0415
    from grit_tpu.obs import trace  # noqa: PLC0415

    hook = device_hook or NoopDeviceHook()
    flight.configure(opts.work_dir, "source")
    uid = progress.uid_from_dir(opts.work_dir)
    tracker = progress.configure(uid, progress.ROLE_SOURCE,
                                 publish_dir=opts.work_dir)
    if fire is None:
        fire = FireSignal.from_env(opts.work_dir, dst_dir=opts.dst_dir)
    if lease is None:
        from grit_tpu.agent.lease import lease_from_env  # noqa: PLC0415

        lease = lease_from_env()

    min_interval = max(0.001, float(config.STANDBY_MIN_INTERVAL_S.get()))
    max_interval = max(min_interval,
                       float(config.STANDBY_MAX_INTERVAL_S.get()))
    backoff = float(config.STANDBY_BACKOFF.get())
    min_delta = int(float(config.STANDBY_MIN_DELTA_MB.get()) * 1e6)
    poll_s = max(0.01, float(config.STANDBY_FIRE_POLL_S.get()))
    rebase_factor = float(config.STANDBY_REBASE_FACTOR.get())

    rounds_shipped = 0
    rounds_skipped = 0
    rebases = 0
    round_deltas: list[int] = []
    backlog = 0
    degraded: str | None = None
    fired: str | None = None

    def _note(**extra) -> None:
        if info is not None:
            info.update({
                "probe_mode": ("speculative"
                               if config.SNAP_SPECULATE.get()
                               else "parked"),
                "rounds_shipped": rounds_shipped,
                "rounds_skipped": rounds_skipped,
                "round_deltas": round_deltas,
                "rebases": rebases,
                "backlog_bytes": backlog,
                "degraded": degraded,
                "fired": fired,
            }, **extra)

    # -- round 0: the arming full pass (pre-copy's round 0) -------------------
    pre_tokens = _mirror_tokens(opts)
    tracker.set_phase("precopy")
    faults.fault_point("standby.round")
    flight.emit("standby.round.start", round=0)
    cut_wall = time.time()
    t0 = time.monotonic()
    with trace.span("agent.standby_live_dump"):
        run_precopy(runtime, opts, hook)
    mirror_skip = _mirrored_skip(opts, pre_tokens)
    with trace.span("agent.standby_upload"):
        stats = transfer_data(opts.work_dir, opts.dst_dir,
                              direction="upload",
                              skip_unchanged=mirror_skip or None)
    round0_s = time.monotonic() - t0
    full_bytes, base_status = _precopy_measurable_bytes(opts, runtime)
    ship_bytes_total = stats.bytes + sum(
        st[0] for st in mirror_skip.values())
    ship_seconds_total = round0_s
    link_rate = (ship_bytes_total / ship_seconds_total
                 if ship_bytes_total and ship_seconds_total > 0 else None)
    round_deltas.append(full_bytes)
    flight.emit("standby.round.end", round=0, bytes=full_bytes,
                shipped=True)
    tracker.note_round(0)
    shipped = tree_state(opts.work_dir)
    last_base_wall = cut_wall
    rounds_shipped += 1
    if base_status == "unreadable":
        degraded = ("standby base has no readable manifest — governed "
                    "delta rounds need the snapshot format; staying "
                    "armed on the round-0 base alone")
        log.warning("standby: %s", degraded)

    # Armed: the snapshot's phase flips to the literal the watchdog
    # exemption and the controller's Standby transition key on.
    tracker.set_total(max(ship_bytes_total,
                          tracker.snapshot()["bytesShipped"]))
    if link_rate is not None:
        tracker.set_rates(link_bps=link_rate)
    tracker.set_phase(STANDBY_PHASE)
    _publish_arm_state(tracker, last_base_wall=last_base_wall,
                       backlog_bytes=0, rounds_shipped=rounds_shipped,
                       rounds_skipped=rounds_skipped, degraded=degraded)
    tracker.publish()
    if lease is not None:
        lease.beat()
    obs_sampler.default_sampler().register("standby-staleness",
                                           sample_standby)
    log.info("standby armed: base %d B shipped in %.1fs (link %.1f MB/s)",
             full_bytes, round0_s,
             (link_rate or 0.0) / 1e6)
    _note()

    interval = min_interval
    governed = 0
    try:
        while True:
            # Idle-armed wait, sliced at the fire-poll cadence; every
            # slice stamps the governor tick (the StandbyStale
            # watchdog's health signal) without touching advancedAt.
            deadline = time.monotonic() + interval
            while True:
                fired = fire.check()
                if fired is not None:
                    break
                tracker.set_standby(
                    tickAt=round(time.time(), 3),
                    stalenessSeconds=round(
                        max(0.0, time.time() - last_base_wall), 3))
                tracker.publish(min_interval_s=min(1.0, poll_s))
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                wait = min(poll_s, remaining)
                if stop is not None:
                    if stop.wait(wait):
                        _note()
                        return None
                else:
                    time.sleep(wait)
            if fired is not None:
                break
            if max_rounds is not None and governed >= max_rounds:
                _note()
                return None
            governed += 1

            if base_status != "ok":
                # CPU-only pods (nothing to refine) and unreadable bases
                # idle armed: the fire path still runs a full checkpoint.
                interval = min(max(interval, min_interval) * max(1.0, backoff),
                               max_interval)
                continue

            # One governed round: probe (momentary quiesce delta dump),
            # decide, ship or carry.
            faults.fault_point("standby.round")
            flight.emit("standby.round.start", round=governed)
            round_cut_wall = time.time()
            # Dirty bytes accumulate against the LAST SHIPPED base (a
            # skipped round's probe is discarded and the base stays), so
            # the rate's denominator is time since that base — NOT time
            # since the last probe. Probe-anchored intervals made the
            # uncatchable degrade an absorbing state: a burst's whole
            # backlog divided by one short probe interval reads as a
            # permanently link-beating dirty rate long after the burst
            # ended; base-anchored, the measured rate decays with wall
            # time and the governor ships the backlog once it is
            # genuinely catchable again.
            dirty_interval = max(round_cut_wall - last_base_wall, 1e-3)
            # A governed round is now IN FLIGHT: the tick freezes for the
            # round's (possibly minutes-long: flagship rebase) duration
            # by design, so the StandbyStale watchdog bounds the round by
            # the ordinary phase deadline off this stamp instead.
            tracker.set_standby(
                roundStartedAt=round(round_cut_wall, 3))
            tracker.publish()
            rebase = _base_bloat_exceeded(opts, runtime, rebase_factor)
            shipped_this = False
            try:
                if rebase:
                    # Full-dump rebase: the rolling base re-dumps fresh
                    # (bounding disk bloat flatten's file-level prune
                    # cannot reclaim); ships like any round, ordered.
                    # Crash-ordering must survive the rebase too: the
                    # streaming mirror is OFF for this pass (its
                    # dir-replace commit would un-commit the warm remote
                    # base mid-swap), and the fresh dump's canonical
                    # data-file names — exactly the names the remote's
                    # current manifest references — are renamed into the
                    # flatten namespace before the ship, so new bytes
                    # land BESIDE the old base and the manifest still
                    # flips atomically last.
                    with trace.span("agent.standby_rebase_dump"):
                        run_precopy(
                            runtime,
                            dataclasses.replace(opts, stream_upload=False),
                            hook)
                    for container in runtime.list_containers(
                            opts.pod_name, opts.pod_namespace,
                            TaskState.RUNNING):
                        base = _precopy_base(opts.work_dir, container.name)
                        if base is None:
                            continue
                        dst_base = os.path.join(
                            opts.dst_dir, container.name + PRECOPY_SUFFIX,
                            HBM_SUBDIR)
                        deltachain.rename_data_files_fresh(
                            base, avoid_dirs=(dst_base,))
                    dirty_bytes, _ = _precopy_measurable_bytes(
                        opts, runtime)
                    decision = GovernorDecision(
                        ship=True, next_interval_s=min_interval,
                        reason="rebase: base disk bloat over "
                               f"{rebase_factor:.1f}x logical state")
                    rebases += 1
                else:
                    with trace.span("agent.standby_round_dump"):
                        pending = _dump_precopy_round(runtime, opts, hook)
                    dirty_bytes = _round_dirty_bytes(pending)
                    faults.fault_point("standby.governor")
                    decision = standby_governor(
                        dirty_bytes, dirty_interval, link_rate,
                        prev_interval_s=interval,
                        min_interval_s=min_interval,
                        max_interval_s=max_interval,
                        backoff=backoff,
                        min_delta_bytes=min_delta,
                    )
                round_deltas.append(dirty_bytes)
                if decision.degraded is not None and \
                        decision.degraded != degraded:
                    degraded = decision.degraded
                    log.warning("standby governor: %s", degraded)

                if decision.ship:
                    if not rebase:
                        for base, round_hbm, round_dir, _ in pending:
                            deltachain.flatten_delta_into_base(
                                base, round_hbm)
                            deltachain.prune_unreferenced(base)
                            shutil.rmtree(round_dir, ignore_errors=True)
                    with trace.span("agent.standby_upload"):
                        up_t0 = time.monotonic()
                        stats, shipped = _ship_round_ordered(opts, shipped)
                        up_s = time.monotonic() - up_t0
                    _prune_destination_base(opts, runtime)
                    ship_bytes_total += stats.bytes
                    ship_seconds_total += up_s
                    if ship_bytes_total and ship_seconds_total > 0:
                        link_rate = ship_bytes_total / ship_seconds_total
                        tracker.set_rates(
                            dirty_bps=dirty_bytes / dirty_interval,
                            link_bps=link_rate)
                    last_base_wall = round_cut_wall
                    backlog = 0
                    rounds_shipped += 1
                    shipped_this = True
                    # Shipped rounds ARE forward progress: note_round +
                    # the transfer's byte feed bump advancedAt, so a
                    # genuinely wedged standby (rounds never finishing)
                    # is still shot by the watchdog while a healthy
                    # idle-armed one never is.
                    tracker.note_round(governed)
                    tracker.set_total(tracker.snapshot()["bytesShipped"])
                else:
                    if not rebase:
                        for _b, _h, round_dir, _n in pending:
                            shutil.rmtree(round_dir, ignore_errors=True)
                    backlog = dirty_bytes
                    rounds_skipped += 1
                    tracker.set_rates(
                        dirty_bps=dirty_bytes / dirty_interval,
                        link_bps=link_rate)
            finally:
                tracker.set_standby(roundStartedAt=None)
                flight.emit("standby.round.end", round=governed,
                            bytes=round_deltas[-1]
                            if len(round_deltas) > governed else 0,
                            shipped=shipped_this)
            _publish_arm_state(
                tracker, last_base_wall=last_base_wall,
                backlog_bytes=backlog, rounds_shipped=rounds_shipped,
                rounds_skipped=rounds_skipped, degraded=degraded)
            tracker.publish()
            if lease is not None:
                lease.beat()
            interval = decision.next_interval_s
            _note()

        # -- fired: only the final delta + blackout remain -----------------
        faults.fault_point("standby.fire")
        staleness_at_fire = max(0.0, time.time() - last_base_wall)
        flight.emit("standby.fire", reason=fired,
                    staleness_s=round(staleness_at_fire, 3),
                    backlog=backlog, rounds=rounds_shipped)
        log.info("standby FIRED (%s): staleness %.1fs, backlog %d B — "
                 "running the final delta + blackout", fired,
                 staleness_at_fire, backlog)
        _note(staleness_at_fire_s=round(staleness_at_fire, 3))
        fire_opts = dataclasses.replace(opts, pre_copy=True,
                                        leave_running=False)
        return run_checkpoint(runtime, fire_opts, hook,
                              preshipped=shipped)
    finally:
        obs_sampler.default_sampler().unregister("standby-staleness")
        _disarm_gauges()
        _note()
