"""Gang slice migration: the agent as a replicated role.

Everything through PR 11 migrates ONE host. A v5e-16-class slice is N
host pods driving one ICI mesh, and its migration is a robustness
contract before it is a data path (CRIUgpu's gang-consistent cut,
PhoenixOS's validated commit):

- **never tear a collective**: the cross-host quiesce barrier
  (:class:`grit_tpu.parallel.coordination.SliceQuiesceGate`, driven
  through the agentlet quiesce hook) parks every host at the same
  agreed step boundary before any dump starts;
- **never commit half a slice**: destinations park in a *prepared*
  state after their session verifies, and resume only when the gang
  commit record lands — written iff every host prepared;
- **resume every source the instant any host's leg fails**: any
  terminal failure writes the slice-wide ABORT record; every parked
  destination poisons-then-clears its stage dir, and the manager (or
  harness) drives ``run_abort`` on every source host.

This module is the agent half of that machine:

- :class:`SliceRole` — per-host rank/ordinal identity (from
  ``GRIT_SLICE_ORDINAL``/``GRIT_SLICE_HOSTS`` or explicit args);
- :class:`GangLedger` — the shared-filesystem gang protocol: per-host
  marker files plus the COMMIT/ABORT records, under
  ``<shared>/.grit-slice/a<nonce>/`` in the checkpoint's PVC work dir
  (the one filesystem every host's legs already share). All writes are
  atomic; COMMIT and ABORT are O_EXCL so exactly one host decides each;
- :func:`run_slice_checkpoint` / :func:`run_slice_restore` — one
  host's leg of the gang, wrapping the single-host drivers with ledger
  bookkeeping, per-host flight roles (``source-h0002``), prepared
  parking and abort propagation;
- :func:`remap_snapshot_host_ordinals` — host-ordinal remapping of
  snapshot metadata, so a destination slice whose runtime relabels host
  indices (a new JobSet's pod ordinals) re-inits its ICI/mesh reading
  each shard under the ordinal it now owns.

The manager's slice machinery (per-host leases under one Checkpoint CR,
``status.hosts[]`` fan-in, the slice abort state machine) lives in
:mod:`grit_tpu.manager.checkpoint_controller`.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from dataclasses import dataclass

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu import metadata
from grit_tpu.metadata import SLICE_LEDGER_DIRNAME
from grit_tpu.obs import flight, progress
from grit_tpu.obs.metrics import SLICE_GANG_TOTAL

log = logging.getLogger(__name__)

COMMIT_RECORD = "COMMIT"
ABORT_RECORD = "ABORT"

#: Ledger marker states, in protocol order. ``dumped`` = this source
#: host's checkpoint leg finished shipping; ``prepared`` = this
#: destination host's staged session verified and is parked awaiting the
#: gang commit; ``committed`` = this destination observed the commit
#: record and dropped its sentinel.
STATES = ("dumped", "prepared", "committed")


class SliceAborted(RuntimeError):
    """The gang's ABORT record exists (or this leg wrote it): the whole
    slice migration is off. Terminal for the leg — classified
    non-retriable-within-the-attempt; the manager retries the WHOLE
    gang or fails the CR."""

    def __init__(self, reason: str):
        super().__init__(f"slice migration aborted: {reason}")
        self.reason = reason


@dataclass(frozen=True)
class SliceRole:
    """One host's identity within the gang."""

    ordinal: int
    hosts: int

    @staticmethod
    def from_env() -> "SliceRole":
        return SliceRole(ordinal=int(config.SLICE_ORDINAL.get()),
                         hosts=int(config.SLICE_HOSTS.get()))

    @property
    def enabled(self) -> bool:
        return self.hosts > 1

    @property
    def tag(self) -> str:
        return f"h{self.ordinal:04d}"

    def flight_role(self, base: str) -> str:
        """Per-host flight role (``source-h0002``): gritscope's
        per-host lane key."""
        return f"{base}-{self.tag}"


def attempt_nonce() -> str:
    """The gang's attempt namespace (``GRIT_SLICE_NONCE``; the manager
    stamps the CR's attempt count into every per-host Job). Empty env =
    attempt 0."""
    return str(config.SLICE_NONCE.get()) or "0"


_HOST_SUBDIR_RE = re.compile(r"^host-\d{4}$")


def gang_shared_dir(leg_dir: str) -> str:
    """The SHARED dir holding the gang ledger, from one leg's PVC data
    dir: per-host legs ship into ``<shared>/host-<k>`` (N dumps must
    never collide in one tree), while the ledger lives at the shared
    root every host can see. A dir without the per-host suffix is
    already the shared root (harness flows that pass it directly)."""
    norm = os.path.normpath(leg_dir)
    if _HOST_SUBDIR_RE.fullmatch(os.path.basename(norm)):
        return os.path.dirname(norm)
    return norm


class GangLedger:
    """The shared-dir gang protocol for one slice migration attempt.

    Layout (under the shared PVC work dir)::

        .grit-slice/a<nonce>/
            dumped-h0000 ...      per-host source markers
            prepared-h0000 ...    per-host destination markers
            committed-h0000 ...   per-host post-commit acknowledgments
            COMMIT                the gang commit record (O_EXCL, once)
            ABORT                 the slice-wide abort record (O_EXCL)

    Any host may write COMMIT — but only when every host's ``prepared``
    (and, when sources participate, ``dumped``) marker exists and no
    ABORT does; any host's failure writes ABORT. Both are
    create-exclusive, so exactly one record of each kind can ever
    exist, and ABORT always wins: :meth:`wait_commit` re-checks it
    after observing COMMIT is absent, and a destination that sees ABORT
    never un-parks.
    """

    def __init__(self, shared_dir: str, role: SliceRole,
                 nonce: str | None = None) -> None:
        self.role = role
        self.nonce = nonce if nonce is not None else attempt_nonce()
        self.dir = os.path.join(shared_dir, SLICE_LEDGER_DIRNAME,
                                f"a{self.nonce}")

    def ensure(self) -> "GangLedger":
        os.makedirs(self.dir, exist_ok=True)
        return self

    # -- markers ---------------------------------------------------------------

    def _marker(self, state: str, ordinal: int) -> str:
        return os.path.join(self.dir, f"{state}-h{ordinal:04d}")

    # grit: atomic-commit
    def mark(self, state: str) -> None:
        """Drop this host's marker for ``state`` (atomic; idempotent —
        re-marking replaces with a fresh timestamp)."""
        if state not in STATES:
            raise ValueError(f"unknown ledger state {state!r}")
        self.ensure()
        path = self._marker(state, self.role.ordinal)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"host": self.role.ordinal, "wall": time.time()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def hosts_in(self, state: str) -> list[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for name in names:
            m = re.fullmatch(rf"{state}-h(\d{{4}})", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- terminal records ------------------------------------------------------

    def aborted(self) -> str | None:
        """The abort reason, or None. ABORT outranks everything."""
        try:
            with open(os.path.join(self.dir, ABORT_RECORD)) as f:
                rec = json.load(f)
            return str(rec.get("reason", "unknown"))
        except (OSError, ValueError):
            return None if not os.path.exists(
                os.path.join(self.dir, ABORT_RECORD)) else "unreadable"

    def committed(self) -> bool:
        return os.path.isfile(os.path.join(self.dir, COMMIT_RECORD))

    # grit: atomic-commit
    def _write_record(self, name: str, payload: dict) -> bool:
        """Create-exclusive record write; False when it already exists
        (somebody else decided first — fine, the record is the truth)."""
        self.ensure()
        path = os.path.join(self.dir, name)
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
        except FileExistsError:
            return False
        try:
            os.write(fd, json.dumps(payload).encode())
            os.fsync(fd)
        finally:
            os.close(fd)
        return True

    def abort(self, reason: str) -> bool:
        """Record the slice-wide abort. First writer wins; every later
        call is a no-op (the first reason is the cause). Returns whether
        THIS call created the record."""
        faults.fault_point("slice.abort")
        created = self._write_record(ABORT_RECORD, {
            "reason": reason, "host": self.role.ordinal,
            "wall": time.time()})
        if created:
            SLICE_GANG_TOTAL.inc(outcome="aborted")
            flight.emit("slice.abort", reason=reason,
                        ordinal=self.role.ordinal)
            log.error("slice migration ABORTED by host %d: %s",
                      self.role.ordinal, reason)
        return created

    def try_commit(self, require_dumped: bool = True) -> bool:
        """Write the gang commit record iff EVERY host prepared (and,
        by default, every source dumped) and no ABORT exists. Any host
        may call this; O_EXCL keeps the record single. Returns whether
        the record now exists (written by us or a peer)."""
        faults.fault_point("slice.commit")
        if self.aborted() is not None:
            return False
        want = set(range(self.role.hosts))
        if set(self.hosts_in("prepared")) < want:
            return False
        if require_dumped and set(self.hosts_in("dumped")) < want:
            return False
        created = self._write_record(COMMIT_RECORD, {
            "hosts": self.role.hosts, "by": self.role.ordinal,
            "wall": time.time()})
        if created:
            # ABORT may have landed between our check and the O_EXCL
            # create; ABORT wins — readers check it first, and we flag
            # the commit as superseded for the record.
            SLICE_GANG_TOTAL.inc(outcome="committed")
            flight.emit("slice.commit", hosts=self.role.hosts,
                        by=self.role.ordinal)
        return self.committed()

    def wait_commit(self, timeout: float | None = None,
                    require_dumped: bool = True) -> None:
        """Park until the gang commit record lands. Raises
        :class:`SliceAborted` the moment ABORT appears; on timeout the
        gang demonstrably cannot commit — this host writes ABORT itself
        (a gang that cannot commit must abort everywhere, never hold
        some hosts parked forever) and raises."""
        if timeout is None:
            timeout = float(config.SLICE_COMMIT_TIMEOUT_S.get())
        poll = max(0.01, float(config.SLICE_POLL_S.get()))
        deadline = time.monotonic() + timeout
        while True:
            reason = self.aborted()
            if reason is not None:
                raise SliceAborted(reason)
            if self.try_commit(require_dumped=require_dumped):
                # ABORT-wins double check: an abort racing the commit
                # write still aborts every host that has not acted yet.
                reason = self.aborted()
                if reason is not None:
                    raise SliceAborted(reason)
                return
            if time.monotonic() > deadline:
                msg = (f"host {self.role.ordinal}: gang commit did not "
                       f"land within {timeout:.0f}s "
                       f"(prepared={self.hosts_in('prepared')}, "
                       f"dumped={self.hosts_in('dumped')}, "
                       f"hosts={self.role.hosts})")
                self.abort(msg)
                raise SliceAborted(msg)
            time.sleep(poll)


# -- host-ordinal remapping ----------------------------------------------------

_HOST_FILE_RE = re.compile(r"^(data-h|index-h|mirror-ok-h)(\d{4})(.*)$")


def _remap_name(name: str, mapping: dict[int, int]) -> str:
    m = _HOST_FILE_RE.match(name)
    if m is None:
        return name
    src = int(m.group(2))
    if src not in mapping:
        return name
    return f"{m.group(1)}{mapping[src]:04d}{m.group(3)}"


def remap_snapshot_host_ordinals(snapshot_dir: str,
                                 mapping: dict[int, int],
                                 follow_refs: bool = True) -> int:
    """Relabel a committed snapshot's host ordinals in place.

    A restored slice re-inits its mesh from the LIVE topology and reads
    shards by global index, so the data layout is ordinal-agnostic —
    but the snapshot's physical artifacts are not: per-host data files
    are named ``data-h<k>.bin`` and every manifest chunk references one
    by name. When the destination runtime relabels host indices (a new
    JobSet numbers its pods fresh), the destination agent remaps the
    staged snapshot so host j's local tooling — delta dumps against
    this snapshot, per-host file pruning, mirror identity — finds its
    shards under the ordinal it now owns.

    ``mapping`` is source-ordinal → destination-ordinal and must be a
    bijection over the ordinals it mentions (two sources mapping onto
    one destination would overwrite a shard file). Renames run in two
    phases through unique temp names, so overlapping mappings (a full
    rotation) never collide mid-flight. With ``follow_refs`` every
    ``ref_dir`` a delta chunk points into is remapped too (once), so a
    staged delta chain stays internally consistent.

    Returns the number of files renamed across all visited dirs."""
    targets = [mapping[k] for k in mapping]
    if len(set(targets)) != len(targets):
        raise ValueError(f"ordinal mapping is not a bijection: {mapping}")
    visited: set[str] = set()

    def _one(d: str) -> int:
        d = os.path.normpath(d)
        if d in visited or not os.path.isdir(d):
            return 0
        visited.add(d)
        count = 0
        manifest_path = os.path.join(d, "MANIFEST.json")
        manifest = None
        if os.path.isfile(manifest_path):
            with open(manifest_path) as f:
                manifest = json.load(f)
            ref_dirs = set()
            for rec in manifest.get("arrays", []):
                for chunk in rec.get("chunks", []):
                    chunk["file"] = _remap_name(str(chunk["file"]), mapping)
                    if chunk.get("ref_dir"):
                        ref_dirs.add(os.path.join(d, chunk["ref_dir"]))
        # Two-phase rename: old → unique tmp, then tmp → new. A direct
        # rename under a rotation mapping (0→1, 1→0) would destroy one
        # file before the other moved.
        moves: list[tuple[str, str]] = []
        for name in sorted(os.listdir(d)):
            new = _remap_name(name, mapping)
            if new != name:
                moves.append((name, new))
        # A partial mapping whose target collides with an UNMAPPED
        # ordinal's existing file would silently overwrite that shard
        # in phase two (mapping={0: 1} over data-h0000 + data-h0001
        # destroys host 1's data). Refuse it — the caller must map
        # every colliding ordinal explicitly.
        sources = {old for old, _new in moves}
        for _old, new in moves:
            if new not in sources and os.path.exists(os.path.join(d, new)):
                raise ValueError(
                    f"ordinal remap target {new!r} already exists in {d} "
                    f"and is not itself remapped — a partial mapping "
                    f"({mapping}) would overwrite that host's shard")
        tmp_names = []
        for i, (old, new) in enumerate(moves):
            tmp = os.path.join(d, f".remap-tmp-{i}")
            os.rename(os.path.join(d, old), tmp)
            tmp_names.append((tmp, os.path.join(d, new)))
        for tmp, new in tmp_names:
            os.rename(tmp, new)
            count += 1
        if manifest is not None:
            metadata.atomic_write_json(manifest_path, manifest)
            if follow_refs:
                for rd in sorted(ref_dirs):
                    count += _one(rd)
        return count

    return _one(snapshot_dir)


def remap_staged_checkpoint(stage_dir: str, mapping: dict[int, int]) -> int:
    """Apply :func:`remap_snapshot_host_ordinals` to every committed HBM
    snapshot under a staged checkpoint tree (``<container>/hbm`` and the
    ``-precopy`` siblings a pre-copy migration stages). Returns files
    renamed."""
    renamed = 0
    if not os.path.isdir(stage_dir):
        return 0
    for entry in sorted(os.listdir(stage_dir)):
        hbm = os.path.join(stage_dir, entry, "hbm")
        if os.path.isfile(os.path.join(hbm, "COMMIT")):
            renamed += remap_snapshot_host_ordinals(hbm, mapping)
    return renamed


# -- the per-host agent legs ---------------------------------------------------


def slice_work_suffixed(path: str, role: SliceRole) -> str:
    """Per-host twin of a shared path: ``<path>/host-<k>`` — the layout
    the manager's per-host Jobs mount (each host's work dir is node-
    local anyway; the PVC side needs the split so N dumps never collide
    in one tree)."""
    return os.path.join(path, f"host-{role.ordinal:04d}")


def run_slice_checkpoint(runtime, opts, role: SliceRole | None = None,
                         device_hook=None, preshipped=None):
    """One host's checkpoint leg of the gang.

    Exactly :func:`grit_tpu.agent.checkpoint.run_checkpoint` — same
    dump, same transports, same wire mode (the PR 10 native plane's
    per-stream sockets give the N×N shape: each host pair is its own
    session with ``GRIT_WIRE_STREAMS`` sockets, multi-NIC striped via
    ``GRIT_WIRE_IFACES``) — wrapped in gang bookkeeping:

    - entry refuses to start a leg whose gang already aborted;
    - the blackout quiesce runs the cross-host barrier (the device hook
      reads ``GRIT_SLICE_HOSTS`` and asks the agentlet for the slice
      cut);
    - success drops this host's ``dumped`` marker;
    - ANY failure writes the slice-wide ABORT record before re-raising,
      so every peer — parked destinations included — learns within one
      ledger poll.
    """
    from grit_tpu.agent.checkpoint import run_checkpoint  # noqa: PLC0415

    role = role or SliceRole.from_env()
    if not role.enabled:
        return run_checkpoint(runtime, opts, device_hook=device_hook,
                              preshipped=preshipped)
    ledger = GangLedger(gang_shared_dir(opts.dst_dir), role).ensure()
    reason = ledger.aborted()
    if reason is not None:
        raise SliceAborted(reason)
    try:
        stats = run_checkpoint(runtime, opts, device_hook=device_hook,
                               preshipped=preshipped, slice_role=role)
    except BaseException as exc:
        if isinstance(exc, SliceAborted):
            raise
        try:
            ledger.abort(f"host {role.ordinal} checkpoint leg failed: "
                         f"{type(exc).__name__}: {exc}")
        except Exception:  # noqa: BLE001 — the original failure wins
            log.exception("could not record slice abort")
        raise
    ledger.mark("dumped")
    return stats


def verify_staged_tree(src_dir: str, dst_dir: str) -> list[str]:
    """PhoenixOS-style validated commit, the per-host half: the staged
    tree must carry every source file at its source size, and every
    committed HBM snapshot must still be committed. Returns the list of
    problems (empty = verified). Byte integrity inside data files is
    already enforced by the transports (per-chunk CRC on the wire,
    container CRC-of-raw on decode); this check catches the gang-level
    failure of a HOST's session ending short — exactly what must block
    the commit record."""
    from grit_tpu.agent.copy import tree_state  # noqa: PLC0415

    problems: list[str] = []
    src = tree_state(src_dir)
    dst = tree_state(dst_dir)
    for rel, (size, _mtime) in sorted(src.items()):
        got = dst.get(rel)
        if got is None:
            problems.append(f"missing staged file: {rel}")
        elif got[0] != size:
            problems.append(
                f"staged size mismatch: {rel} ({got[0]} != {size})")
    if not src:
        problems.append(f"source tree {src_dir} is empty")
    return problems


def run_slice_restore(opts, role: SliceRole | None = None,
                      ordinal_mapping: dict[int, int] | None = None):
    """One host's restore leg of the gang: stage, verify, park
    *prepared*, resume only on the gang commit.

    The two-phase finish: after its stage verifies, the destination
    marks ``prepared`` and PARKS — the download-state sentinel (what
    lets the replacement pod start) drops only once the commit record
    exists, which requires every host of the slice to have prepared.
    An ABORT observed while parked poisons-then-clears this host's
    stage dir (the PR 7/11 crash-ordered discipline: journal ``failed``
    marker first, then sentinel, then content) and raises — a
    destination of an aborted gang NEVER un-parks.

    ``ordinal_mapping`` (source→destination host ordinals) remaps the
    staged snapshot metadata before verification, so the destination
    slice re-inits its ICI/mesh with relabeled ordinals
    (:func:`remap_snapshot_host_ordinals`)."""
    from grit_tpu.agent.abort import poison_and_clear_stage  # noqa: PLC0415
    from grit_tpu.agent.copy import transfer_data  # noqa: PLC0415
    from grit_tpu.agent.restore import (  # noqa: PLC0415
        _clear_stale_stage_state,
    )

    role = role or SliceRole.from_env()
    ledger = GangLedger(gang_shared_dir(opts.src_dir), role).ensure()
    reason = ledger.aborted()
    if reason is not None:
        raise SliceAborted(reason)
    _clear_stale_stage_state(opts.dst_dir)
    flight.configure(opts.dst_dir, role.flight_role("destination"))
    tracker = progress.configure(
        progress.uid_from_dir(opts.dst_dir), progress.ROLE_DESTINATION,
        publish_dir=opts.dst_dir, ordinal=role.ordinal)
    tracker.set_phase("stage")
    try:
        flight.emit("stage.start", streamed=False, ordinal=role.ordinal)
        stats = None
        try:
            stats = transfer_data(opts.src_dir, opts.dst_dir,
                                  direction="download")
        finally:
            flight.emit("stage.end", streamed=False, ok=stats is not None,
                        **({"bytes": stats.bytes, "files": stats.files}
                           if stats is not None else {}))
    except BaseException as exc:
        if not isinstance(exc, SliceAborted):
            try:
                ledger.abort(f"host {role.ordinal} restore leg failed: "
                             f"{type(exc).__name__}: {exc}")
            except Exception:  # noqa: BLE001
                log.exception("could not record slice abort")
        poison_and_clear_stage(opts.dst_dir)
        raise
    gang_commit_staged(opts, role, ordinal_mapping=ordinal_mapping,
                       ledger=ledger, verify_against=opts.src_dir)
    return stats


def gang_commit_staged(opts, role: SliceRole,
                       ordinal_mapping: dict[int, int] | None = None,
                       ledger: GangLedger | None = None,
                       verify_against: str | None = None) -> None:
    """The gang-commit two-phase finish over an already-staged tree
    (serial stage, streamed stage, or a verified wire session that was
    asked NOT to drop its sentinel): remap ordinals, verify the staged
    session, mark *prepared*, PARK until the commit record lands, and
    only then drop the download-state sentinel.

    Any failure — verification, an observed ABORT, the bounded commit
    wait expiring — poisons-then-clears this host's stage dir and
    raises; a destination of an aborted gang never un-parks."""
    from grit_tpu.agent.abort import poison_and_clear_stage  # noqa: PLC0415
    from grit_tpu.agent.copy import create_sentinel_file  # noqa: PLC0415

    ledger = ledger or GangLedger(gang_shared_dir(opts.src_dir), role).ensure()
    tracker = progress.get(progress.ROLE_DESTINATION)
    try:
        # Verify BEFORE remapping (the staged tree still file-matches
        # its source), then relabel — a remap failure aborts the gang
        # like any other leg failure.
        if verify_against is not None:
            problems = verify_staged_tree(verify_against, opts.dst_dir)
            if problems:
                raise RuntimeError(
                    f"host {role.ordinal} staged session failed "
                    "verification: " + "; ".join(problems[:5]))
        if ordinal_mapping:
            remap_staged_checkpoint(opts.dst_dir, ordinal_mapping)
    except BaseException as exc:
        if not isinstance(exc, SliceAborted):
            try:
                ledger.abort(f"host {role.ordinal} verification failed: "
                             f"{type(exc).__name__}: {exc}")
            except Exception:  # noqa: BLE001
                log.exception("could not record slice abort")
        poison_and_clear_stage(opts.dst_dir)
        raise
    # Prepared: verified, parked, sentinel NOT down.
    ledger.mark("prepared")
    flight.emit("slice.prepared", ordinal=role.ordinal)
    if tracker is not None:
        tracker.set_phase("gang_commit")
        tracker.publish()
    try:
        ledger.wait_commit()
    except SliceAborted:
        # The gang is off: this destination never un-parks — poisoned
        # journal first, sentinel and staged content gone, tombstone
        # left. The PR 3 discipline, slice-wide.
        poison_and_clear_stage(opts.dst_dir)
        raise
    ledger.mark("committed")
    create_sentinel_file(opts.dst_dir)
    if tracker is not None:
        tracker.set_phase("committed")
        tracker.publish()
