"""Agent heartbeat lease: proof-of-life the manager watchdog can read.

A wedged agent (hung wire, stuck NFS write, livelocked CRIU) looks
identical to a slow one from the control plane — the Job is Active either
way. The lease breaks the tie: while the agent works, a renewal thread
stamps ``grit.dev/heartbeat`` (unix seconds) onto its own Job's
annotations every :data:`DEFAULT_PERIOD_S`; the watchdog in
``checkpoint_controller``/``restore_controller`` fails the attempt over
to the retry/abort machinery once the stamp goes stale
(``GRIT_LEASE_TIMEOUT_S``).

Renewal targets:

- **Job annotation** (production): the agent Job carries its own
  coordinates in env (``GRIT_JOB_NAME``/``GRIT_JOB_NAMESPACE``, stamped
  by the AgentManager) and patches the annotation through any
  cluster-shaped handle (``patch(kind, name, mutate, namespace)`` — the
  in-process :class:`~grit_tpu.kube.cluster.Cluster` and the real
  :class:`~grit_tpu.kube.client.KubeCluster` share that signature).
- **File** (harness / no-apiserver nodes): ``GRIT_HEARTBEAT_FILE`` names
  a path that gets the timestamp written-and-replaced atomically.

Renewal failures never kill the agent — a broken heartbeat at worst
triggers one spurious retry, while an agent dying of its own liveness
plumbing would be the tail wagging the dog. Misses are counted and
logged after :data:`_MISS_WARN_THRESHOLD` consecutive failures.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections.abc import Callable

from grit_tpu.api import config
from grit_tpu.api.constants import HEARTBEAT_ANNOTATION, PROGRESS_ANNOTATION
from grit_tpu.obs import progress

log = logging.getLogger(__name__)

DEFAULT_PERIOD_S = config.HEARTBEAT_PERIOD_S.default
HEARTBEAT_PERIOD_ENV = config.HEARTBEAT_PERIOD_S.name
HEARTBEAT_FILE_ENV = config.HEARTBEAT_FILE.name
JOB_NAME_ENV = config.JOB_NAME.name
JOB_NAMESPACE_ENV = config.JOB_NAMESPACE.name

_MISS_WARN_THRESHOLD = 3


def job_annotation_renewer(cluster, job_name: str,
                           namespace: str) -> Callable[[float], None]:
    """Renewer patching ``grit.dev/heartbeat`` on the agent's own Job —
    and, when a live migration progress tracker is configured, the
    ``grit.dev/progress`` snapshot in the SAME patch. Riding the lease
    is the telemetry plane's write-amplification contract: the CR's
    status.progress updates exactly as often as the lease renews, never
    more."""

    def renew(ts: float) -> None:
        snap = agent_progress_annotation()

        def mutate(job) -> None:
            job.metadata.annotations[HEARTBEAT_ANNOTATION] = f"{ts:.3f}"
            if snap is not None:
                job.metadata.annotations[PROGRESS_ANNOTATION] = snap

        cluster.patch("Job", job_name, mutate, namespace)

    return renew


def agent_progress_annotation() -> str | None:
    """The progress JSON for this agent process's migration leg: an
    agent Job is either the source or the destination of exactly one
    migration, so the first configured driver role wins."""
    for role in (progress.ROLE_SOURCE, progress.ROLE_DESTINATION):
        value = progress.annotation_value(role)
        if value is not None:
            return value
    return None


def file_renewer(path: str) -> Callable[[float], None]:
    """Renewer writing the timestamp to ``path`` atomically."""

    def renew(ts: float) -> None:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"{ts:.3f}")
        os.replace(tmp, path)

    return renew


def read_heartbeat_file(path: str) -> float | None:
    try:
        with open(path) as f:
            return float(f.read().strip())
    except (OSError, ValueError):
        return None


class HeartbeatLease:
    """Background renewal loop around one renew callable."""

    def __init__(self, renew: Callable[[float], None],
                 period: float = DEFAULT_PERIOD_S) -> None:
        self._renew = renew
        self.period = max(0.05, period)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.renewals = 0
        self.misses = 0
        self._consecutive_misses = 0

    def beat(self) -> None:
        """One renewal, now (also called synchronously at start/stop so
        short agent runs still leave a fresh stamp)."""
        try:
            self._renew(time.time())
        except Exception as exc:  # noqa: BLE001 — liveness must not kill work
            self.misses += 1
            self._consecutive_misses += 1
            if self._consecutive_misses == _MISS_WARN_THRESHOLD:
                log.warning(
                    "heartbeat renewal failing (%d consecutive: %s) — the "
                    "manager watchdog may retry this attempt spuriously",
                    self._consecutive_misses, exc)
        else:
            self.renewals += 1
            self._consecutive_misses = 0
        # Lease cadence doubles as the node-local telemetry cadence: the
        # progress snapshot file (`gritscope watch`'s feed) and gauges
        # refresh here even when no sampler thread runs. Throttled inside
        # publish(); never raises.
        progress.sample()

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            self.beat()

    def start(self) -> "HeartbeatLease":
        self.beat()
        self._thread = threading.Thread(
            target=self._loop, name="grit-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_beat: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if final_beat:
            self.beat()

    def __enter__(self) -> "HeartbeatLease":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def _in_cluster_handle():
    """A KubeCluster against the pod-mounted serviceaccount config, or
    None when this process is not running in a cluster (no
    KUBERNETES_SERVICE_HOST / token). Never raises: liveness plumbing
    must not take down the agent it reports on."""
    try:
        from grit_tpu.kube.client import (  # noqa: PLC0415
            KubeCluster,
            KubeConfig,
        )

        return KubeCluster(KubeConfig.in_cluster())
    except Exception as exc:  # noqa: BLE001 — degrade to no lease, loudly
        log.warning(
            "heartbeat lease: %s set but no usable in-cluster config "
            "(%s) — the Job's grit.dev/heartbeat will not renew and the "
            "watchdog falls back to phase deadlines only",
            JOB_NAME_ENV, exc)
        return None


def lease_from_env(cluster=None) -> HeartbeatLease | None:
    """Build the lease the environment asks for, or None.

    Preference order: explicit ``GRIT_HEARTBEAT_FILE`` (harness and
    node-local runs), then Job coordinates (``GRIT_JOB_NAME``, stamped
    by the AgentManager) renewing the Job annotation through ``cluster``
    — or, when no handle is injected, through a KubeCluster built from
    the pod's serviceaccount (the production in-cluster path)."""
    period = config.HEARTBEAT_PERIOD_S.get()
    path = config.HEARTBEAT_FILE.get()
    if path:
        return HeartbeatLease(file_renewer(path), period=period)
    job = config.JOB_NAME.get()
    if job:
        if cluster is None:
            cluster = _in_cluster_handle()
        if cluster is not None:
            ns = config.JOB_NAMESPACE.get()
            return HeartbeatLease(job_annotation_renewer(cluster, job, ns),
                                  period=period)
    return None
