"""Agent abort driver: resume a quiesced source after a failed migration.

The invariant CRIUgpu and the CRIU migration literature treat as what
makes checkpointing deployable at all: *a failed migration never strands
the source*. grit-tpu's agents already resume on their own error paths
(``runtime_checkpoint_pod``'s finally block), but a KILLED agent — OOM,
node pressure, injected ``kill`` fault — runs no error path, leaving the
workload parked at the agentlet barrier and the cgroup possibly frozen.
This driver is the manager's recovery arm for exactly that case: the
watchdog creates an ``--action abort`` agent Job on the source node, and
:func:`run_abort`:

1. unfreezes every paused container of the target pod (cgroup resume);
2. unquiesces every workload through its agentlet (device resume) — the
   source resumes training from live HBM state, no restore involved;
3. clears the dead attempt's partial dump state (``<name>-work`` dirs in
   the host work dir) so a later retry starts clean;
4. poisons-then-clears the destination stage dir when one is given
   (harness/CLI concurrent flows, where source and destination share a
   filesystem): the stage journal gets a ``failed`` marker FIRST — any
   restore pipeline mid-consume dies loudly via SnapshotIntegrityError,
   never reads a half-staged tree — then the sentinel and staged content
   are removed. The poisoned journal itself stays, as the tombstone.

Every step is best-effort and independent: an unreachable agentlet on one
pid must not stop the cgroup resume of another. The result dict reports
what actually happened; ``grit_source_resume_seconds`` records the wall
time to a resumable source and ``grit_migration_aborts_total``
(driver=agent) counts executions.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from dataclasses import dataclass, field

from grit_tpu.agent.checkpoint import (
    DeviceCheckpointHook,
    NoopDeviceHook,
    resume_pod_workloads,
)
from grit_tpu.agent.copy import StageJournal
from grit_tpu.cri.runtime import FakeRuntime
from grit_tpu.metadata import (
    DOWNLOAD_STATE_FILE,
    FLIGHT_LOG_FILE,
    PROF_FILE_PREFIX,
    STAGE_JOURNAL_FILE,
    WORK_SUFFIX,
)
from grit_tpu.obs import flight
from grit_tpu.obs.metrics import MIGRATION_ABORTS, SOURCE_RESUME_SECONDS

log = logging.getLogger(__name__)


@dataclass
class AbortOptions:
    pod_name: str
    pod_namespace: str
    pod_uid: str = ""
    # Source host work dir <host-path>/<ns>/<ckpt-name>: partial dump
    # state from the dead attempt is cleared here.
    work_dir: str = ""
    # Destination staging dir to poison-and-clear, when reachable from
    # this process (harness/CLI). The managed flow leaves this empty —
    # the manager tears the restore Job down instead, and the restore
    # path's own stale-state clearing handles the next attempt.
    stage_dir: str = ""
    # Gang slice migration: the SHARED PVC work dir holding the gang
    # ledger. When set (the manager stamps it into every per-host abort
    # Job via --dst-dir + slice env; the harness passes it directly),
    # run_abort records the slice-wide ABORT — every parked destination
    # of the gang poisons-and-clears instead of ever un-parking.
    gang_shared_dir: str = ""


@dataclass
class AbortOutcome:
    resumed_containers: list[str] = field(default_factory=list)
    resumed_pids: list[int] = field(default_factory=list)
    resume_errors: list[str] = field(default_factory=list)
    cleared_work_dirs: list[str] = field(default_factory=list)
    stage_poisoned: bool = False
    resume_seconds: float = 0.0


def poison_and_clear_stage(stage_dir: str) -> bool:
    """Destination half of an abort. Order is load-bearing: journal
    ``failed`` marker first (live consumers fail loudly, never read a
    half tree), then the sentinel (nothing new may start from this dir),
    then the staged content. Returns False when there was nothing to do."""
    if not stage_dir or not os.path.isdir(stage_dir):
        return False
    try:
        StageJournal(stage_dir).fail("migration aborted: source resumed")
    except OSError as exc:
        log.warning("abort: could not poison stage journal in %s: %s",
                    stage_dir, exc)
    for entry in sorted(os.listdir(stage_dir)):
        if entry in (STAGE_JOURNAL_FILE, FLIGHT_LOG_FILE) \
                or entry.startswith(PROF_FILE_PREFIX):
            # The poisoned journal is the tombstone; the flight log and
            # the profiler's per-phase folded stacks are the evidence —
            # an aborted migration is exactly the one whose destination
            # timeline (and CPU breakdown) gritscope must still read.
            continue
        path = os.path.join(stage_dir, entry)
        try:
            if os.path.isdir(path) and not os.path.islink(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
        except OSError as exc:
            log.warning("abort: could not clear staged %s: %s", path, exc)
    # Explicit double-check: the sentinel is the one file whose survival
    # would spawn a replacement pod over a poisoned dir.
    sentinel = os.path.join(stage_dir, DOWNLOAD_STATE_FILE)
    if os.path.exists(sentinel):
        try:
            os.unlink(sentinel)
        except OSError as exc:
            log.warning("abort: sentinel %s survived clearing: %s",
                        sentinel, exc)
    return True


def _clear_partial_dumps(work_dir: str, outcome: AbortOutcome) -> None:
    """Remove ``<container>-work`` dirs a dead dump left behind. Committed
    snapshot dirs (already renamed) stay — they are valid data a PVC-path
    retry can reuse."""
    if not work_dir or not os.path.isdir(work_dir):
        return
    for entry in sorted(os.listdir(work_dir)):
        if not entry.endswith(WORK_SUFFIX):
            continue
        path = os.path.join(work_dir, entry)
        if not os.path.isdir(path):
            continue
        try:
            shutil.rmtree(path)
            outcome.cleared_work_dirs.append(path)
        except OSError as exc:
            log.warning("abort: could not clear partial dump %s: %s",
                        path, exc)


def run_abort(
    runtime: FakeRuntime,
    opts: AbortOptions,
    device_hook: DeviceCheckpointHook | None = None,
) -> AbortOutcome:
    """Resume the source pod's workloads and clear failed-attempt state.

    Finding no containers is SUCCESS, not failure: the pod may have been
    rescheduled or completed since the migration died, and an abort Job
    that fails on an already-gone pod would wedge the manager's abort
    state machine on the happy case.
    """
    hook = device_hook or NoopDeviceHook()
    outcome = AbortOutcome()
    if opts.work_dir:
        flight.configure(opts.work_dir, "source")
    flight.emit("abort.start", pod=opts.pod_name)
    t0 = time.monotonic()

    if opts.gang_shared_dir:
        # Record the slice-wide ABORT FIRST (best-effort, like every
        # other step): parked gang destinations learn within one ledger
        # poll and poison-and-clear; peers' source aborts are driven by
        # their own per-host abort Jobs.
        try:
            from grit_tpu.agent.slicerole import (  # noqa: PLC0415
                GangLedger,
                SliceRole,
                gang_shared_dir,
            )

            # Normalized like every other ledger entry point: a caller
            # reusing a checkpoint leg's per-host '<shared>/host-<k>'
            # dir must still hit the SHARED ledger the destinations
            # poll, or the abort never reaches them.
            GangLedger(gang_shared_dir(opts.gang_shared_dir),
                       SliceRole.from_env()).abort(
                f"migration aborted: source {opts.pod_namespace}/"
                f"{opts.pod_name} resuming")
        except Exception as exc:  # noqa: BLE001 — abort keeps going
            log.warning("abort: could not record gang ledger ABORT in "
                        "%s: %s", opts.gang_shared_dir, exc)

    ids, pids, errors = resume_pod_workloads(
        runtime, opts.pod_name, opts.pod_namespace, hook)
    outcome.resumed_containers = ids
    outcome.resumed_pids = pids
    outcome.resume_errors = errors

    outcome.resume_seconds = time.monotonic() - t0
    SOURCE_RESUME_SECONDS.set(outcome.resume_seconds)

    _clear_partial_dumps(opts.work_dir, outcome)
    outcome.stage_poisoned = poison_and_clear_stage(opts.stage_dir)

    MIGRATION_ABORTS.inc(driver="agent")
    flight.emit("abort.end", pod=opts.pod_name,
                resume_s=round(outcome.resume_seconds, 4),
                stage_poisoned=outcome.stage_poisoned,
                errors=len(outcome.resume_errors))
    if outcome.resume_errors:
        log.warning("abort for %s/%s finished with resume errors: %s",
                    opts.pod_namespace, opts.pod_name, outcome.resume_errors)
    return outcome
