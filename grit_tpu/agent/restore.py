"""Agent restore driver: stage PVC data onto the node, then signal readiness.

Parity: reference ``pkg/gritagent/restore/restore.go:14-21`` — download
PVC→hostPath, then drop the ``download-state`` sentinel that releases the
CRI interceptor's PullImage gate.

Pre-staging (the destination half of pre-copy, no reference analogue):
once the source's live pre-copy pass has landed on the PVC, the
destination agent can download the multi-GB base *while the source still
trains* (:func:`run_prestage` — no sentinel, so the interceptor gate stays
closed). The blackout-path :func:`run_restore` then passes the returned
capture as ``prestaged`` and ships only what changed since — the delta,
the CRIU image, metadata.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass

from grit_tpu import faults
from grit_tpu.agent.copy import (
    StageJournal,
    TransferStats,
    WireError,
    WireReceiver,
    create_sentinel_file,
    transfer_data,
    tree_state,
)
from grit_tpu.api import config
from grit_tpu.metadata import (
    DOWNLOAD_STATE_FILE,
    PVC_TEE_COMPLETE_FILE,
    STAGE_JOURNAL_FILE,
)
from grit_tpu.obs import flight, progress
from grit_tpu.obs.metrics import WIRE_FALLBACKS

log = logging.getLogger(__name__)


def _clear_stale_stage_state(dst_dir: str) -> None:
    """Remove a previous (possibly failed) attempt's download-state
    sentinel and stage journal before re-staging ``dst_dir``. Sentinel
    first: a lingering sentinel spawns the replacement pod immediately,
    and without a journal its reads would be ungated against the
    re-stage's half-written files."""
    for name in (DOWNLOAD_STATE_FILE, STAGE_JOURNAL_FILE):
        path = os.path.join(dst_dir, name)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass


@dataclass
class RestoreOptions:
    src_dir: str  # PVC source  /mnt/pvc-data/<ns>/<ckpt>
    dst_dir: str  # host work path <host-path>/<ns>/<ckpt>


def _clone_ordinal() -> int | None:
    """This restore leg's RestoreSet clone ordinal (the controller
    stamps grit.dev/clone-ordinal into the agent Job env), or None for
    a plain restore. Every clone of a fan-out derives the SAME progress
    uid from the shared snapshot name — the ordinal riding the progress
    snapshot is what lets `gritscope watch --restoreset` tell live
    per-clone files apart."""
    k = int(config.CLONE_ORDINAL.get())
    return k if k >= 0 else None


def run_prestage(opts: RestoreOptions) -> dict[str, tuple[int, int]]:
    """Warm the destination with everything currently on the PVC, WITHOUT
    dropping the sentinel (the pod must not start from a pre-copy base
    alone). Returns the shipped capture for :func:`run_restore`."""
    from grit_tpu.obs import trace

    with trace.span("agent.prestage"):
        faults.fault_point("agent.restore.prestage")
        # Capture BEFORE the download: the source agent writes this PVC
        # concurrently (that is the point of pre-staging), and a file
        # landing mid-download must re-ship in the blackout pass, never
        # be skipped as "already staged". A file that changes during the
        # download flips its (size, mtime) off this capture — also the
        # safe direction.
        shipped = tree_state(opts.src_dir)
        # count_progress=False: a codec-on PVC holds COMPRESSED
        # containers, and counting their on-disk bytes against the raw
        # totals the wire commit later declares would park the
        # destination's progress at the compression ratio forever. The
        # receiver credits prestaged files at RAW size once the commit
        # verifies them from disk.
        transfer_data(opts.src_dir, opts.dst_dir, direction="download",
                      count_progress=False)
        return shipped


def run_restore(
    opts: RestoreOptions,
    prestaged: dict[str, tuple[int, int]] | None = None,
    dest_valid: dict[str, int] | None = None,
) -> TransferStats:
    from grit_tpu.obs import trace

    # A journal left by a previous (possibly failed) streamed attempt
    # would gate — or loudly poison — the restore pipeline against a
    # stage that is no longer streaming. This pass ships every byte
    # before the sentinel, so there is nothing to wait on. The stale
    # SENTINEL must go too, and first: with the journal gone it is the
    # only thing holding back a replacement pod, and a pod it spawns
    # mid-restage would read half-staged files completely ungated.
    _clear_stale_stage_state(opts.dst_dir)
    flight.configure(opts.dst_dir, "destination")
    tracker = progress.adopt(
        progress.uid_from_dir(opts.dst_dir), progress.ROLE_DESTINATION,
        publish_dir=opts.dst_dir, clone=_clone_ordinal())
    tracker.set_phase("stage")
    with trace.span("agent.stage"):
        faults.fault_point("agent.restore.stage")
        flight.emit("stage.start", streamed=False)
        stats = None
        try:
            stats = transfer_data(opts.src_dir, opts.dst_dir,
                                  direction="download",
                                  skip_unchanged=prestaged,
                                  dest_valid=dest_valid)
        finally:
            flight.emit(
                "stage.end", streamed=False, ok=stats is not None,
                **({"bytes": stats.bytes, "files": stats.files,
                    "skipped": stats.skipped}
                   if stats is not None else {}))
    create_sentinel_file(opts.dst_dir)
    tracker.publish()
    return stats


@dataclass
class StreamedRestore:
    """Handle for an in-flight streamed stage. The sentinel is already
    down when the caller holds one of these; :meth:`wait` joins the
    background transfer and returns (or raises) its outcome."""

    thread: threading.Thread
    _box: dict

    def wait(self, timeout: float | None = None) -> TransferStats:
        """Join the background transfer. ``timeout=None`` no longer means
        forever: the default deadline (``GRIT_STAGE_STREAM_TIMEOUT_S``,
        900 s) turns a stage whose source stopped producing into a loud
        TimeoutError instead of an agent Job that spins until someone
        notices the migration never finished."""
        if timeout is None:
            timeout = config.STAGE_STREAM_TIMEOUT_S.get()
        self.thread.join(timeout)
        if self.thread.is_alive():
            raise TimeoutError(
                f"streamed stage still running after {timeout}s")
        if "error" in self._box:
            raise self._box["error"]
        return self._box["stats"]

    @property
    def done(self) -> bool:
        return not self.thread.is_alive()


def run_restore_streamed(
    opts: RestoreOptions,
    prestaged: dict[str, tuple[int, int]] | None = None,
) -> StreamedRestore:
    """Chunk-streamed staging: the pipelined-restore half of the blackout.

    Metadata ships first (snapshot MANIFEST/COMMIT, carried executable
    cache, CRIU image, config/spec dumps) and the ``download-state``
    sentinel drops as soon as that priority set is complete — so the
    restored pod starts, pays its interpreter/import time, and begins
    placing arrays through the stage journal while the bulk HBM chunks
    are still in flight from the PVC. The serial alternative
    (:func:`run_restore`) finishes every byte before the pod may start.

    Failure semantics: a transfer error before the priority set lands
    raises here; a later one surfaces BOTH in :meth:`StreamedRestore.wait`
    and — via the journal's ``failed`` marker — as a loud
    ``SnapshotIntegrityError`` in the consuming restore, never a hang or
    a partially-accepted state.
    """
    from grit_tpu.obs import trace

    # A previous attempt's sentinel would spawn the replacement pod
    # before even the metadata priority set of THIS attempt has landed.
    _clear_stale_stage_state(opts.dst_dir)
    flight.configure(opts.dst_dir, "destination")
    tracker = progress.configure(
        progress.uid_from_dir(opts.dst_dir), progress.ROLE_DESTINATION,
        publish_dir=opts.dst_dir, clone=_clone_ordinal())
    tracker.set_phase("stage_stream")
    journal = StageJournal(opts.dst_dir)
    ready = threading.Event()
    box: dict = {}
    stream_ctx = trace.current_context()

    def _ship() -> None:
        try:
            faults.fault_point("agent.restore.stream")
            with trace.span("agent.stage_stream", parent=stream_ctx):
                flight.emit("stage.start", streamed=True)
                try:
                    box["stats"] = transfer_data(
                        opts.src_dir, opts.dst_dir, direction="download",
                        skip_unchanged=prestaged, journal=journal,
                        priority_event=ready,
                    )
                finally:
                    stats = box.get("stats")
                    flight.emit(
                        "stage.end", streamed=True, ok=stats is not None,
                        **({"bytes": stats.bytes, "files": stats.files}
                           if stats is not None else {}))
            journal.complete()
        except BaseException as exc:  # noqa: BLE001 — relayed to wait()
            # Record the real error FIRST: journal.fail appends to the
            # same (possibly full — ENOSPC is a likely original cause)
            # disk and may itself raise, which must not eat the cause.
            box["error"] = exc
            try:
                journal.fail(f"{type(exc).__name__}: {exc}")
            except OSError:
                pass  # consumers fall back to the stage timeout
        finally:
            ready.set()

    thread = threading.Thread(
        target=_ship, name="grit-stage-stream", daemon=True)
    thread.start()
    ready.wait()
    if "error" in box:
        # ready is set from _ship's finally, so the thread is at most a
        # few statements from exiting — but join unbounded and a wedged
        # interpreter teardown pins the agent; bound it and move on (the
        # thread is a daemon, the error below is the outcome either way).
        thread.join(timeout=5.0)
        if thread.is_alive():
            log.warning("stage-stream thread still alive after its error "
                        "was recorded; proceeding with the raise")
        raise box["error"]
    create_sentinel_file(opts.dst_dir)
    return StreamedRestore(thread=thread, _box=box)


# -- wire-mode restore: single-hop source→destination stream ------------------


@dataclass
class WireRestore:
    """Handle for an in-flight wire-mode stage (the destination half of
    GRIT_MIGRATION_PATH=wire). The receiver is already listening and its
    endpoint is published into the checkpoint's PVC work dir; the source
    agent dials it and streams the checkpoint straight into ``dst_dir``
    through the stage journal, cutting both PVC legs out of the blackout.
    """

    receiver: WireReceiver
    opts: RestoreOptions
    # Whether the PVC-tee marker already existed when the listener came
    # up. A pre-existing marker is ambiguous: the sequenced-jobs case (a
    # wire-mode checkpoint ALREADY finished; abort fast) looks identical
    # to a stale marker from a previous attempt whose retry source is
    # about to dial — so it only triggers the fast abort after a short
    # grace (GRIT_WIRE_ABORT_GRACE_S, default 10 s) with no connection.
    # A marker appearing FRESH mid-wait is unambiguous (the source just
    # finished on the PVC path without dialing us) and aborts at once.
    marker_preexisting: bool = False

    @property
    def endpoint(self) -> str:
        return self.receiver.endpoint

    def wait(self, timeout: float | None = None,
             drop_sentinel: bool = True) -> TransferStats:
        """Join the wire session; the sentinel drops only on a verified
        commit. Raises :class:`WireError` on any failure — call
        :meth:`fallback` then (loud PVC path, never partial state).
        ``drop_sentinel=False`` keeps the sentinel up after a verified
        commit — the gang slice restore parks *prepared* and drops it
        only once the slice-wide commit record lands.

        Fast abort for sequenced agent Jobs: if the source's PVC-tee
        marker appears while NO sender ever dialed in, the source already
        finished on the PVC path (the manager creates the restore Job
        only after the Checkpoint completes, so a wire-mode source ran —
        and marked the tee — before this receiver even existed). Raising
        immediately hands control to :meth:`fallback` instead of idling
        out the full wire timeout on a peer that will never come."""
        t0 = time.monotonic()
        if timeout is None:
            # Bounded by default: a wire session whose peer never comes
            # (or died after connecting) must end in a loud WireError →
            # fallback, not an agent Job polling forever.
            timeout = config.WIRE_RESTORE_TIMEOUT_S.get()
        deadline = t0 + timeout
        marker = os.path.join(self.opts.src_dir, PVC_TEE_COMPLETE_FILE)
        grace = config.WIRE_ABORT_GRACE_S.get()
        while True:
            faults.fault_point("agent.restore.wire_wait", wrap=WireError)
            if self.receiver.poll() is not None:
                # Terminal either way: wait() returns stats or raises.
                stats = self.receiver.wait(timeout=0)
                if drop_sentinel:
                    create_sentinel_file(self.opts.dst_dir)
                tracker = progress.get(progress.ROLE_DESTINATION)
                if tracker is not None:
                    tracker.publish()
                return stats
            if not self.receiver.ever_connected and os.path.isfile(marker) \
                    and (not self.marker_preexisting
                         or time.monotonic() - t0 > grace):
                self.receiver.close()
                raise WireError(
                    "source completed on the PVC path without dialing "
                    "the wire (sequenced agent jobs) — stage from the PVC")
            if time.monotonic() > deadline:
                msg = f"wire session timed out after {timeout}s"
                self.receiver.fail(msg)
                raise WireError(msg)
            time.sleep(0.1)

    def fallback(self, timeout: float | None = None) -> TransferStats:
        """Wire died: re-stage everything from the PVC. Waits up to
        ``timeout`` (default GRIT_WIRE_TEE_WAIT_S, 30 s) for the source's
        durability-tee marker (a wire-mode source drops it once the PVC
        tree is complete, wire or no wire), then runs the serial stage —
        which clears the failed journal and overwrites any partially
        wire-staged bytes. A missing marker is not fatal: a source
        running the classic path never writes one, and there the
        manager's sequencing (restore Job after Checkpoint completion)
        already guarantees a complete PVC tree.

        Files the failed wire leg FULLY landed and verified (every
        frame's CRC-of-raw checked — compressed frames included) are
        not re-shipped: they pass as ``dest_valid`` into the stage,
        which skips each one whose raw identity still matches the PVC
        source. A late fallback after a mostly-complete wire leg costs
        only the missing tail, not the whole tree again."""
        verified = self.receiver.verified_files()
        self.receiver.close()
        WIRE_FALLBACKS.inc(stage="receive")
        if timeout is None:
            timeout = config.WIRE_TEE_WAIT_S.get()
        marker = os.path.join(self.opts.src_dir, PVC_TEE_COMPLETE_FILE)
        deadline = time.monotonic() + timeout
        while not os.path.isfile(marker):
            if time.monotonic() > deadline:
                log.warning(
                    "wire fallback: no PVC tee marker after %.0fs — "
                    "assuming the source ran the classic path (PVC "
                    "complete before this Job) and staging as-is", timeout)
                break
            time.sleep(0.2)
        log.warning("wire stage failed or never started; re-staging %s "
                    "from the PVC (%d wire-verified file(s) kept)",
                    self.opts.dst_dir, len(verified))
        return run_restore(self.opts, dest_valid=verified or None)


def run_restore_wire(opts: RestoreOptions,
                     prestage: bool = False) -> WireRestore:
    """Start the destination half of a wire-mode migration: a
    :class:`WireReceiver` over ``dst_dir`` writing through the stage
    journal (the PR-1 restore pipeline consumes chunks as they land),
    endpoint published into the PVC work dir for the source agent to
    find. Returns immediately; callers :meth:`WireRestore.wait` for the
    commit (→ sentinel) and :meth:`WireRestore.fallback` on failure.

    ``prestage=True`` first copies whatever the PVC already holds into
    ``dst_dir`` (no sentinel) — the destination half of pre-copy: a
    wire-mode source skips its live-shipped base files on the wire and
    the commit verifies them from this prestaged disk, so the blackout
    stream carries only the delta. A no-op when the PVC dir is empty or
    absent (plain, non-pre-copy checkpoints)."""
    _clear_stale_stage_state(opts.dst_dir)
    flight.configure(opts.dst_dir, "destination")
    tracker = progress.configure(
        progress.uid_from_dir(opts.dst_dir), progress.ROLE_DESTINATION,
        publish_dir=opts.dst_dir, clone=_clone_ordinal())
    if prestage and os.path.isdir(opts.src_dir):
        tracker.set_phase("prestage")
        run_prestage(opts)
    tracker.set_phase("wire_recv")
    marker_preexisting = os.path.isfile(
        os.path.join(opts.src_dir, PVC_TEE_COMPLETE_FILE))
    journal = StageJournal(opts.dst_dir)
    receiver = WireReceiver(opts.dst_dir, journal=journal)
    receiver.publish(opts.src_dir)
    return WireRestore(receiver=receiver, opts=opts,
                       marker_preexisting=marker_preexisting)
