"""Agent restore driver: stage PVC data onto the node, then signal readiness.

Parity: reference ``pkg/gritagent/restore/restore.go:14-21`` — download
PVC→hostPath, then drop the ``download-state`` sentinel that releases the
CRI interceptor's PullImage gate.
"""

from __future__ import annotations

from dataclasses import dataclass

from grit_tpu.agent.copy import TransferStats, create_sentinel_file, transfer_data


@dataclass
class RestoreOptions:
    src_dir: str  # PVC source  /mnt/pvc-data/<ns>/<ckpt>
    dst_dir: str  # host work path <host-path>/<ns>/<ckpt>


def run_restore(opts: RestoreOptions) -> TransferStats:
    from grit_tpu.obs import trace

    with trace.span("agent.stage"):
        stats = transfer_data(opts.src_dir, opts.dst_dir,
                              direction="download")
    create_sentinel_file(opts.dst_dir)
    return stats
