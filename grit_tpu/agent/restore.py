"""Agent restore driver: stage PVC data onto the node, then signal readiness.

Parity: reference ``pkg/gritagent/restore/restore.go:14-21`` — download
PVC→hostPath, then drop the ``download-state`` sentinel that releases the
CRI interceptor's PullImage gate.

Pre-staging (the destination half of pre-copy, no reference analogue):
once the source's live pre-copy pass has landed on the PVC, the
destination agent can download the multi-GB base *while the source still
trains* (:func:`run_prestage` — no sentinel, so the interceptor gate stays
closed). The blackout-path :func:`run_restore` then passes the returned
capture as ``prestaged`` and ships only what changed since — the delta,
the CRIU image, metadata.
"""

from __future__ import annotations

from dataclasses import dataclass

from grit_tpu.agent.copy import (
    TransferStats,
    create_sentinel_file,
    transfer_data,
    tree_state,
)


@dataclass
class RestoreOptions:
    src_dir: str  # PVC source  /mnt/pvc-data/<ns>/<ckpt>
    dst_dir: str  # host work path <host-path>/<ns>/<ckpt>


def run_prestage(opts: RestoreOptions) -> dict[str, tuple[int, int]]:
    """Warm the destination with everything currently on the PVC, WITHOUT
    dropping the sentinel (the pod must not start from a pre-copy base
    alone). Returns the shipped capture for :func:`run_restore`."""
    from grit_tpu.obs import trace

    with trace.span("agent.prestage"):
        # Capture BEFORE the download: the source agent writes this PVC
        # concurrently (that is the point of pre-staging), and a file
        # landing mid-download must re-ship in the blackout pass, never
        # be skipped as "already staged". A file that changes during the
        # download flips its (size, mtime) off this capture — also the
        # safe direction.
        shipped = tree_state(opts.src_dir)
        transfer_data(opts.src_dir, opts.dst_dir, direction="download")
        return shipped


def run_restore(
    opts: RestoreOptions,
    prestaged: dict[str, tuple[int, int]] | None = None,
) -> TransferStats:
    from grit_tpu.obs import trace

    with trace.span("agent.stage"):
        stats = transfer_data(opts.src_dir, opts.dst_dir,
                              direction="download",
                              skip_unchanged=prestaged)
    create_sentinel_file(opts.dst_dir)
    return stats
