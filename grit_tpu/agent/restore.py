"""Agent restore driver: stage PVC data onto the node, then signal readiness.

Parity: reference ``pkg/gritagent/restore/restore.go:14-21`` — download
PVC→hostPath, then drop the ``download-state`` sentinel that releases the
CRI interceptor's PullImage gate.

Pre-staging (the destination half of pre-copy, no reference analogue):
once the source's live pre-copy pass has landed on the PVC, the
destination agent can download the multi-GB base *while the source still
trains* (:func:`run_prestage` — no sentinel, so the interceptor gate stays
closed). The blackout-path :func:`run_restore` then passes the returned
capture as ``prestaged`` and ships only what changed since — the delta,
the CRIU image, metadata.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from grit_tpu.agent.copy import (
    StageJournal,
    TransferStats,
    create_sentinel_file,
    transfer_data,
    tree_state,
)
from grit_tpu.metadata import DOWNLOAD_STATE_FILE, STAGE_JOURNAL_FILE


def _clear_stale_stage_state(dst_dir: str) -> None:
    """Remove a previous (possibly failed) attempt's download-state
    sentinel and stage journal before re-staging ``dst_dir``. Sentinel
    first: a lingering sentinel spawns the replacement pod immediately,
    and without a journal its reads would be ungated against the
    re-stage's half-written files."""
    for name in (DOWNLOAD_STATE_FILE, STAGE_JOURNAL_FILE):
        path = os.path.join(dst_dir, name)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass


@dataclass
class RestoreOptions:
    src_dir: str  # PVC source  /mnt/pvc-data/<ns>/<ckpt>
    dst_dir: str  # host work path <host-path>/<ns>/<ckpt>


def run_prestage(opts: RestoreOptions) -> dict[str, tuple[int, int]]:
    """Warm the destination with everything currently on the PVC, WITHOUT
    dropping the sentinel (the pod must not start from a pre-copy base
    alone). Returns the shipped capture for :func:`run_restore`."""
    from grit_tpu.obs import trace

    with trace.span("agent.prestage"):
        # Capture BEFORE the download: the source agent writes this PVC
        # concurrently (that is the point of pre-staging), and a file
        # landing mid-download must re-ship in the blackout pass, never
        # be skipped as "already staged". A file that changes during the
        # download flips its (size, mtime) off this capture — also the
        # safe direction.
        shipped = tree_state(opts.src_dir)
        transfer_data(opts.src_dir, opts.dst_dir, direction="download")
        return shipped


def run_restore(
    opts: RestoreOptions,
    prestaged: dict[str, tuple[int, int]] | None = None,
) -> TransferStats:
    from grit_tpu.obs import trace

    # A journal left by a previous (possibly failed) streamed attempt
    # would gate — or loudly poison — the restore pipeline against a
    # stage that is no longer streaming. This pass ships every byte
    # before the sentinel, so there is nothing to wait on. The stale
    # SENTINEL must go too, and first: with the journal gone it is the
    # only thing holding back a replacement pod, and a pod it spawns
    # mid-restage would read half-staged files completely ungated.
    _clear_stale_stage_state(opts.dst_dir)
    with trace.span("agent.stage"):
        stats = transfer_data(opts.src_dir, opts.dst_dir,
                              direction="download",
                              skip_unchanged=prestaged)
    create_sentinel_file(opts.dst_dir)
    return stats


@dataclass
class StreamedRestore:
    """Handle for an in-flight streamed stage. The sentinel is already
    down when the caller holds one of these; :meth:`wait` joins the
    background transfer and returns (or raises) its outcome."""

    thread: threading.Thread
    _box: dict

    def wait(self, timeout: float | None = None) -> TransferStats:
        self.thread.join(timeout)
        if self.thread.is_alive():
            raise TimeoutError(
                f"streamed stage still running after {timeout}s")
        if "error" in self._box:
            raise self._box["error"]
        return self._box["stats"]

    @property
    def done(self) -> bool:
        return not self.thread.is_alive()


def run_restore_streamed(
    opts: RestoreOptions,
    prestaged: dict[str, tuple[int, int]] | None = None,
) -> StreamedRestore:
    """Chunk-streamed staging: the pipelined-restore half of the blackout.

    Metadata ships first (snapshot MANIFEST/COMMIT, carried executable
    cache, CRIU image, config/spec dumps) and the ``download-state``
    sentinel drops as soon as that priority set is complete — so the
    restored pod starts, pays its interpreter/import time, and begins
    placing arrays through the stage journal while the bulk HBM chunks
    are still in flight from the PVC. The serial alternative
    (:func:`run_restore`) finishes every byte before the pod may start.

    Failure semantics: a transfer error before the priority set lands
    raises here; a later one surfaces BOTH in :meth:`StreamedRestore.wait`
    and — via the journal's ``failed`` marker — as a loud
    ``SnapshotIntegrityError`` in the consuming restore, never a hang or
    a partially-accepted state.
    """
    from grit_tpu.obs import trace

    # A previous attempt's sentinel would spawn the replacement pod
    # before even the metadata priority set of THIS attempt has landed.
    _clear_stale_stage_state(opts.dst_dir)
    journal = StageJournal(opts.dst_dir)
    ready = threading.Event()
    box: dict = {}

    def _ship() -> None:
        try:
            with trace.span("agent.stage_stream"):
                box["stats"] = transfer_data(
                    opts.src_dir, opts.dst_dir, direction="download",
                    skip_unchanged=prestaged, journal=journal,
                    priority_event=ready,
                )
            journal.complete()
        except BaseException as exc:  # noqa: BLE001 — relayed to wait()
            # Record the real error FIRST: journal.fail appends to the
            # same (possibly full — ENOSPC is a likely original cause)
            # disk and may itself raise, which must not eat the cause.
            box["error"] = exc
            try:
                journal.fail(f"{type(exc).__name__}: {exc}")
            except OSError:
                pass  # consumers fall back to the stage timeout
        finally:
            ready.set()

    thread = threading.Thread(
        target=_ship, name="grit-stage-stream", daemon=True)
    thread.start()
    ready.wait()
    if "error" in box:
        thread.join()
        raise box["error"]
    create_sentinel_file(opts.dst_dir)
    return StreamedRestore(thread=thread, _box=box)
