from grit_tpu.agent.app import main

main()
