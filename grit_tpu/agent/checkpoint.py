"""Agent checkpoint driver: dump a pod's containers, then upload.

Parity: reference ``pkg/gritagent/checkpoint/{checkpoint.go,runtime.go}``:
CRI list → per-container pause → task checkpoint (CRIU image dir) → rootfs
rw-layer diff tar → newest kubelet log save → atomic work-dir rename →
``TransferData`` to the PVC. Two reference TODOs are implemented here, not
inherited: multi-container pods are paused *all together before any dump* so
the pod snapshot is mutually consistent (runtime.go:63 TODO), and
``config.dump``/``spec.dump`` are written (runtime.go:145 TODO).

TPU delta: between pause and the process dump, the device hook quiesces the
XLA:TPU runtime and snapshots HBM into ``<container>/hbm/`` — the role
CRIU's ``cuda_plugin.so`` plays in the reference (SURVEY §5 "device state").
"""

from __future__ import annotations

import glob
import json
import logging
import os
import shutil
import threading
from dataclasses import dataclass
from typing import Protocol

import time

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.obs import flight, progress
from grit_tpu.obs.metrics import (
    BLACKOUT_SECONDS,
    CHECKPOINTS_TOTAL,
    WIRE_FALLBACKS,
    WIRE_OVERLAP_FRACTION,
)
from grit_tpu.agent.copy import (
    TransferStats,
    WireError,
    WireSender,
    read_wire_endpoint,
    transfer_data,
    tree_state,
)
from grit_tpu.cri.runtime import FakeRuntime, TaskState
from grit_tpu.metadata import (
    CHECKPOINT_DIRECTORY,
    CONFIG_DUMP,
    CONTAINER_LOG_FILE,
    PVC_TEE_COMPLETE_FILE,
    ROOTFS_DIFF_TAR,
    SNAPSHOT_FORMAT,
    SPEC_DUMP,
    WIRE_ENDPOINT_FILE,
    WORK_SUFFIX,
    crc32_file,
    manifest_data_file_signature,
    stage_timeout_s,
)

log = logging.getLogger(__name__)


class DeviceCheckpointHook(Protocol):
    """Accelerator-state hook invoked inside the pause window.

    ``dump`` must leave everything needed to reattach device state in
    ``dest_dir`` (the container's checkpoint dir); ``resume`` is called after
    a leave-running dump. The TPU implementation lives in
    :mod:`grit_tpu.device`; CPU-only pods (BASELINE config 1) use
    :class:`NoopDeviceHook`.
    """

    def dump(self, pid: int, dest_dir: str, base: str | None = None,
             mirror: str | None = None,
             wire: dict | None = None) -> dict | None: ...

    def predump(self, pid: int, dest_dir: str,
                mirror: str | None = None,
                base: str | None = None) -> None: ...

    def resume(self, pid: int) -> None: ...


class NoopDeviceHook:
    def dump(self, pid: int, dest_dir: str, base: str | None = None,  # noqa: ARG002
             mirror: str | None = None,  # noqa: ARG002
             wire: dict | None = None) -> dict | None:  # noqa: ARG002
        # No device state: a wire request is trivially satisfied (nothing
        # to stream), so wire mode keeps working for CPU-only pods.
        return {"ok": True, "files": {}} if wire is not None else None

    def predump(self, pid: int, dest_dir: str,  # noqa: ARG002
                mirror: str | None = None,  # noqa: ARG002
                base: str | None = None) -> None:  # noqa: ARG002
        return

    def resume(self, pid: int) -> None:  # noqa: ARG002
        return


@dataclass
class CheckpointOptions:
    pod_name: str
    pod_namespace: str
    pod_uid: str
    work_dir: str  # host work path <host-path>/<ns>/<ckpt-name>
    dst_dir: str  # PVC destination
    kubelet_log_root: str = "/var/log/pods"
    leave_running: bool = True
    # Pre-copy live migration: dump + upload a full HBM snapshot while the
    # workload keeps training, then dump only the delta inside the blackout
    # window (classic iterative pre-copy; no reference analogue — CRIU's
    # opaque process images cannot be diffed).
    pre_copy: bool = False
    # Streaming upload: HBM dumps tee a committed byte-identical copy
    # directly into dst_dir while they write, collapsing the upload leg
    # into the dump's wall-clock (the post-dump transfer then skips the
    # mirrored bytes). Safe default: a failed mirror self-abandons and
    # the transfer ships everything.
    stream_upload: bool = True
    # Migration data path: "pvc" (double hop through the checkpoint PVC)
    # or "wire" (direct source→destination stream; the PVC upload becomes
    # an asynchronous durability tee off the blackout path). "" resolves
    # through GRIT_MIGRATION_PATH, defaulting to pvc. Any wire failure
    # falls back to the pvc path loudly — never a lost checkpoint.
    migration_path: str = ""


def resolved_migration_path(configured: str = "") -> str:
    """``pvc`` | ``wire`` from the explicit option or GRIT_MIGRATION_PATH;
    unknown values degrade to pvc with a loud warning (an operator typo
    must not strand a drain-triggered migration)."""
    path = configured or config.MIGRATION_PATH.get()
    if path not in ("pvc", "wire"):
        log.warning("unknown migration path %r; using pvc", path)
        return "pvc"
    return path


# Sibling of the container's checkpoint dir; survives the per-container
# work-dir rmtree/rename cycle so the delta's relative base reference stays
# valid on both the dump and the staged restore side.
PRECOPY_SUFFIX = "-precopy"
HBM_SUBDIR = "hbm"  # mirrors grit_tpu.device.hook.HBM_SUBDIR (no jax import)


def precopy_dir(work_dir: str, container_name: str) -> str:
    return os.path.join(work_dir, container_name + PRECOPY_SUFFIX)


def _precopy_base(work_dir: str, container_name: str) -> str | None:
    """The committed pre-copied HBM snapshot for this container, if any.

    COMMIT-sentinel check is inlined (one isfile) so the CPU-only agent
    path never imports the jax-backed snapshot module.
    """
    base = os.path.join(precopy_dir(work_dir, container_name), HBM_SUBDIR)
    return base if os.path.isfile(os.path.join(base, "COMMIT")) else None


def run_precopy(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    device_hook: DeviceCheckpointHook,
) -> None:
    """Phase 1 of pre-copy: full HBM dump of every container's workload —
    no cgroup freeze, no CRIU, training continues. With the workload's
    GRIT_SNAP_SPECULATE on (default) the hook's predump is a NON-PARKING
    speculative probe: the agentlet snapshots a cloned generation while
    the loop keeps stepping, so this pass no longer costs even a step
    boundary; otherwise it is a momentary quiesce + immediate resume.
    The caller ships the result to the PVC while the workload runs."""

    containers = runtime.list_containers(
        opts.pod_name, opts.pod_namespace, TaskState.RUNNING
    )
    if not containers:
        raise RuntimeError(
            f"no running containers for pod {opts.pod_namespace}/{opts.pod_name}"
        )
    os.makedirs(opts.work_dir, exist_ok=True)
    for container in containers:
        faults.fault_point("agent.checkpoint.predump")
        dest = precopy_dir(opts.work_dir, container.name)
        if os.path.exists(dest):
            shutil.rmtree(dest)  # re-run: a fresh base beats a stale one
        os.makedirs(dest)
        task = runtime.get_task(container.id)
        device_hook.predump(
            task.pid, dest,
            mirror=(os.path.join(opts.dst_dir,
                                 container.name + PRECOPY_SUFFIX)
                    if opts.stream_upload else None),
        )


def _commit_token(path: str) -> tuple[int, int] | None:
    """(inode, mtime_ns) identity of a dst COMMIT sentinel, or None."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_ino, st.st_mtime_ns)


def _mirror_tokens(opts: CheckpointOptions) -> dict[str, tuple[int, int]]:
    """Identity of every pre-existing ``<entry>/hbm/COMMIT`` under
    ``dst_dir``, captured at run start. A mirror that commits during THIS
    run replaces the snapshot dir atomically (new inode), so comparing
    against these tokens distinguishes this run's streamed bytes from a
    previous Job attempt's leftovers."""
    tokens: dict[str, tuple[int, int]] = {}
    if not os.path.isdir(opts.dst_dir):
        return tokens
    for entry in os.listdir(opts.dst_dir):
        tok = _commit_token(
            os.path.join(opts.dst_dir, entry, HBM_SUBDIR, "COMMIT"))
        if tok is not None:
            tokens[entry] = tok
    return tokens


def _mirror_commit_files(commit_path: str) -> dict | None:
    """The ``{rel: {size, sig|crc}}`` identity map a streaming mirror's
    COMMIT records (snapshot.py ``_commit_mirror``): line 1 the snapshot
    format, line 2 a JSON ``{"files": ...}``. None → absent, legacy, or
    malformed — callers then ship everything (the safe direction)."""
    try:
        with open(commit_path) as f:
            header = f.readline().strip()
            payload = f.readline()
        if header != SNAPSHOT_FORMAT or not payload.strip():
            return None
        files = json.loads(payload).get("files")
        return files if isinstance(files, dict) else None
    except (OSError, ValueError):
        return None


def _mirrored_skip(
    opts: CheckpointOptions, pre_tokens: dict[str, tuple[int, int]],
) -> dict[str, tuple[int, int]]:
    """Source-side skip entries for HBM files the dump's streaming mirror
    placed at ``dst_dir`` *during this run*. Three gates, all required:
    the dst twin's COMMIT identity changed since ``pre_tokens`` was
    captured (a prior attempt's same-sized leftovers never skip — the
    retry contract of transfer_data's ``skip_unchanged``); the mirror
    COMMIT *records* the file; and the recorded content identity matches
    the source's — per-chunk CRC signature recomputed from the source
    MANIFEST for data files (metadata only, no multi-GB re-read), whole-
    file crc32 for the small metadata files. Size equality alone was the
    ADVICE-r5 hole: a same-size-different-bytes twin could ship stale.
    Entries the mirror does not carry (compile-cache, CRIU image, logs)
    have no recorded identity and ship normally."""
    skip: dict[str, tuple[int, int]] = {}
    if not opts.stream_upload or not os.path.isdir(opts.work_dir):
        return skip
    for entry in os.listdir(opts.work_dir):
        hbm_src = os.path.join(opts.work_dir, entry, HBM_SUBDIR)
        hbm_dst = os.path.join(opts.dst_dir, entry, HBM_SUBDIR)
        if not os.path.isdir(hbm_src):
            continue
        tok = _commit_token(os.path.join(hbm_dst, "COMMIT"))
        if tok is None or tok == pre_tokens.get(entry):
            continue  # no mirror, or a previous attempt's — ship it all
        recorded = _mirror_commit_files(os.path.join(hbm_dst, "COMMIT"))
        if recorded is None:
            continue  # pre-identity mirror COMMIT: ship it all
        try:
            with open(os.path.join(hbm_src, "MANIFEST.json")) as f:
                src_manifest = json.load(f)
        except (OSError, ValueError):
            continue
        for rel, st in tree_state(hbm_src).items():
            meta = recorded.get(rel)
            if not isinstance(meta, dict) or meta.get("size") != st[0]:
                continue
            try:
                if "sig" in meta:  # bulk data file: verify via manifest
                    if manifest_data_file_signature(
                            src_manifest, rel) != meta["sig"]:
                        continue
                elif "crc" in meta:
                    if crc32_file(os.path.join(hbm_src, rel)) != meta["crc"]:
                        continue
                else:
                    continue  # no content identity recorded → ship
            except OSError:
                continue
            skip[os.path.join(entry, HBM_SUBDIR, rel)] = st
    return skip


# Scratch dir one convergence round dumps its live delta into before it
# is flattened into the rolling '-precopy' base (then removed).
PRECOPY_ROUND_SUFFIX = "-precopy-round"


def _precopy_measurable_bytes(
    opts: CheckpointOptions, runtime: FakeRuntime,
) -> tuple[int, str]:
    """``(physical_bytes, status)`` of the pod's committed pre-copy
    bases. ``status``: ``"ok"`` — rounds can delta against them;
    ``"none"`` — no container pre-copied any device state (CPU-only
    pods: a clean stop, nothing to refine); ``"unreadable"`` — a base
    exists but lacks a readable manifest (device hooks that do not
    produce the snapshot format — a loud degrade, rounds skipped)."""
    from grit_tpu import deltachain

    total = 0
    seen = False
    for container in runtime.list_containers(
            opts.pod_name, opts.pod_namespace, TaskState.RUNNING):
        base = _precopy_base(opts.work_dir, container.name)
        if base is None:
            continue
        seen = True
        try:
            total += deltachain.manifest_physical_nbytes(base)
        except (OSError, ValueError, KeyError):
            return 0, "unreadable"
    return (total, "ok") if seen else (0, "none")


def _dump_precopy_round(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    hook: DeviceCheckpointHook,
) -> list[tuple[str, str, str, int]]:
    """One live delta round: delta dump against each container's rolling
    pre-copy base — a non-parking speculative probe when the workload
    speculates (see :func:`run_precopy`), a momentary quiesce otherwise.
    Returns ``[(base_hbm, round_hbm, round_dir, delta_bytes)]`` — the
    caller decides whether to flatten and ship the round or discard it
    (dirty rate above link rate)."""
    from grit_tpu import deltachain

    pending: list[tuple[str, str, str, int]] = []
    for container in runtime.list_containers(
            opts.pod_name, opts.pod_namespace, TaskState.RUNNING):
        base = _precopy_base(opts.work_dir, container.name)
        if base is None:
            continue  # never pre-copied (no device state): nothing to refine
        round_dir = os.path.join(
            opts.work_dir, container.name + PRECOPY_ROUND_SUFFIX)
        if os.path.exists(round_dir):
            shutil.rmtree(round_dir)
        os.makedirs(round_dir)
        task = runtime.get_task(container.id)
        hook.predump(task.pid, round_dir, base=base)
        round_hbm = os.path.join(round_dir, HBM_SUBDIR)
        if not os.path.isfile(os.path.join(round_hbm, "COMMIT")):
            shutil.rmtree(round_dir, ignore_errors=True)
            continue
        pending.append((base, round_hbm, round_dir,
                        deltachain.manifest_physical_nbytes(round_hbm)))
    return pending


def _dirty_rate_exceeds_link(dirty_rate: float,
                             link_rate: float | None) -> str | None:
    """The shared dirty-vs-link exit predicate: the stop message when
    the workload dirties at least as fast as the link ships (pre-copy
    can never catch up), else None. One formatter for the loop's
    pre-ship discard and :func:`precopy_should_continue`, so the two
    sites cannot drift."""
    if link_rate is None or dirty_rate < link_rate:
        return None
    return (f"dirty rate {dirty_rate / 1e6:.2f} MB/s >= link rate "
            f"{link_rate / 1e6:.2f} MB/s — pre-copy cannot catch up")


#: Stop reasons that are the plan WORKING (loop finished its job), not a
#: degrade worth a warning / a `degraded` report.
_PRECOPY_CLEAN_STOPS = ("round cap", "converged")


def precopy_should_continue(
    next_round: int, max_rounds: int, delta_bytes: int,
    prev_delta: int | None, dirty_rate: float, link_rate: float | None,
    ratio: float,
) -> tuple[bool, str | None]:
    """The convergence decision, as a pure function: whether round
    ``next_round`` should run given the round just finished. Returns
    ``(go, reason)`` — ``reason`` explains a stop (None while going)."""
    if delta_bytes <= 0:
        return False, "converged: round delta is empty"
    if next_round >= max_rounds:
        return False, f"round cap {max_rounds} reached"
    dirty = _dirty_rate_exceeds_link(dirty_rate, link_rate)
    if dirty is not None:
        return False, dirty
    if prev_delta is not None and delta_bytes >= ratio * prev_delta:
        return False, (
            f"delta stopped shrinking ({delta_bytes} >= "
            f"{ratio:.2f} x {prev_delta})")
    return True, None


def run_precopy_phase(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    device_hook: DeviceCheckpointHook | None = None,
    info: dict | None = None,
    lease=None,
) -> dict[str, tuple[int, int]]:
    """Phase 1 of pre-copy as a bounded convergence loop: a full live
    dump + upload (round 0), then up to ``GRIT_PRECOPY_MAX_ROUNDS - 1``
    live *delta* rounds — each one dumps the bytes dirtied since the
    previous round, flattens them into the rolling ``-precopy`` base
    (:mod:`grit_tpu.deltachain` — the chain stays ≤ 2 hops deep at
    restore), and ships only the changed files. The loop enters blackout
    when a round's delta stops shrinking (``GRIT_PRECOPY_CONVERGENCE_
    RATIO``), when the observed dirty rate reaches the observed upload
    rate (the PhoenixOS exit: pre-copy can never catch up — degrade
    loudly to the single-delta behavior), when a round overruns
    ``GRIT_PRECOPY_ROUND_DEADLINE_S``, or at the round cap. Every round
    renews the agent's heartbeat lease so a long converging pre-copy
    never reads as a wedged Job to the manager watchdog.

    Returns the shipped capture — pass it to :func:`run_checkpoint` as
    ``preshipped`` so the blackout call skips re-running the live phase.
    ``info`` (optional dict) is filled with ``rounds`` (live passes run),
    ``round_deltas`` (physical bytes per round, round 0 = the full pass)
    and ``degraded`` (the stop reason, None only at the round cap)."""
    from grit_tpu import deltachain
    from grit_tpu.obs import trace

    hook = device_hook or NoopDeviceHook()
    flight.configure(opts.work_dir, "source")
    # Adopt, not configure: when run_checkpoint drives this phase it
    # already installed the migration's tracker — replacing it here
    # would strand the driver's handle on a dead object.
    tracker = progress.adopt(
        progress.uid_from_dir(opts.work_dir), progress.ROLE_SOURCE,
        publish_dir=opts.work_dir)
    tracker.set_phase("precopy")
    pre_tokens = _mirror_tokens(opts)
    max_rounds = max(1, int(config.PRECOPY_MAX_ROUNDS.get()))
    ratio = float(config.PRECOPY_CONVERGENCE_RATIO.get())
    deadline_s = float(config.PRECOPY_ROUND_DEADLINE_S.get())
    if lease is None:
        from grit_tpu.agent.lease import lease_from_env  # noqa: PLC0415

        lease = lease_from_env()

    flight.emit("precopy.start", pod=opts.pod_name)
    round_deltas: list[int] = []
    degraded: str | None = None

    # Round 0: the full live pass (identical to the pre-loop behavior).
    faults.fault_point("precopy.round")
    flight.emit("precopy.round.start", round=0)
    tracker.note_round(0)
    prev_cut = time.monotonic()  # the round's consistent-cut moment
    with trace.span("agent.precopy_live_dump"):
        run_precopy(runtime, opts, hook)
    mirror_skip = _mirrored_skip(opts, pre_tokens)
    with trace.span("agent.precopy_upload"):
        stats = transfer_data(
            opts.work_dir, opts.dst_dir, direction="upload",
            skip_unchanged=mirror_skip or None,
        )
    round0_elapsed = time.monotonic() - prev_cut
    full_bytes, base_status = _precopy_measurable_bytes(opts, runtime)
    # Link-rate estimate: CUMULATIVE shipped bytes over cumulative
    # shipping wall. Bytes the streaming mirror landed at dst DURING the
    # dump count too (the upload pass skips them, but they crossed the
    # link — without them a stream-upload round 0 reads as a ~0-byte
    # transfer and the loop degrades on a phantom dirty-rate exit), and
    # their wall is the dump's, so round 0 charges dump+upload. A
    # per-round sample would be dominated by fixed per-transfer
    # overheads once deltas shrink to KBs — the full pass anchors it.
    ship_bytes_total = stats.bytes + sum(
        st[0] for st in mirror_skip.values())
    ship_seconds_total = round0_elapsed
    link_rate = (ship_bytes_total / ship_seconds_total
                 if ship_bytes_total and ship_seconds_total > 0 else None)
    round_deltas.append(full_bytes)
    flight.emit("precopy.round.end", round=0, bytes=full_bytes,
                shipped=True)
    # The live pass defines the first total estimate; the link-rate
    # estimate the loop steers by is published alongside so the fleet
    # scheduler sees the same number the convergence decision uses.
    tracker.set_total(ship_bytes_total)
    if link_rate is not None:
        tracker.set_rates(link_bps=link_rate)
    tracker.publish()
    if lease is not None:
        lease.beat()
    shipped = tree_state(opts.work_dir)

    prev_delta = full_bytes
    rnd = 1
    while rnd < max_rounds:
        if base_status != "ok":
            # "none" (CPU-only pod: no device state to refine) is the
            # plan working — a clean stop, not a degrade; an unreadable
            # base is a loud one.
            if base_status == "unreadable":
                degraded = ("pre-copy base has no readable manifest — "
                            "convergence rounds need the snapshot "
                            "format; staying with the single live pass")
                log.warning("pre-copy convergence: %s", degraded)
            break
        faults.fault_point("precopy.round")
        flight.emit("precopy.round.start", round=rnd)
        tracker.note_round(rnd)
        round_t0 = time.monotonic()
        # Dirty interval: cut to cut — the delta holds every byte the
        # workload dirtied since the PREVIOUS round's quiesce boundary,
        # which spans that round's dump + flatten + upload, not just the
        # gap between uploads.
        dirty_interval = max(round_t0 - prev_cut, 1e-3)
        prev_cut = round_t0
        with trace.span("agent.precopy_round_dump"):
            pending = _dump_precopy_round(runtime, opts, hook)
        delta_bytes = sum(b for _, _, _, b in pending)
        round_deltas.append(delta_bytes)
        dirty_rate = delta_bytes / dirty_interval
        tracker.set_rates(dirty_bps=dirty_rate, link_bps=link_rate)

        dirty_stop = _dirty_rate_exceeds_link(dirty_rate, link_rate)
        if dirty_stop is not None and delta_bytes > 0:
            # The workload dirties faster than the link ships: more
            # rounds would chase their own tail forever. Discard this
            # round unshipped — blackout carries the delta, exactly the
            # pre-loop behavior — and say so loudly.
            for _, _, round_dir, _ in pending:
                shutil.rmtree(round_dir, ignore_errors=True)
            degraded = (f"round {rnd}: {dirty_stop}; degrading to "
                        "single-delta pre-copy")
            log.warning("pre-copy convergence: %s", degraded)
            flight.emit("precopy.round.end", round=rnd, bytes=delta_bytes,
                        shipped=False)
            break

        # Ship the round: flatten into the rolling base (bounded chain),
        # then upload only what changed since the previous round.
        for base, round_hbm, round_dir, _ in pending:
            deltachain.flatten_delta_into_base(base, round_hbm)
            shutil.rmtree(round_dir, ignore_errors=True)
        with trace.span("agent.precopy_upload"):
            up_t0 = time.monotonic()
            stats = transfer_data(
                opts.work_dir, opts.dst_dir, direction="upload",
                skip_unchanged=shipped or None,
            )
            up_s = time.monotonic() - up_t0
        ship_bytes_total += stats.bytes
        ship_seconds_total += up_s
        shipped = tree_state(opts.work_dir)
        tracker.set_total(ship_bytes_total)
        tracker.publish()
        flight.emit("precopy.round.end", round=rnd, bytes=delta_bytes,
                    shipped=True)
        if lease is not None:
            # Rounds renew the lease: the watchdog must read a long
            # converging pre-copy as alive (an overrun phase deadline
            # still classifies retriable — the agent never got to say
            # why, and a fresh attempt restarts the loop from scratch).
            lease.beat()

        round_wall = time.monotonic() - round_t0
        if round_wall > deadline_s:
            degraded = (f"round {rnd} took {round_wall:.1f}s > "
                        f"{config.PRECOPY_ROUND_DEADLINE_S.name}="
                        f"{deadline_s:.0f}s — entering blackout")
            log.warning("pre-copy convergence: %s", degraded)
            break
        # One (dirty, link) pairing per round: the decision uses the same
        # link estimate the pre-ship discard check did — the refreshed
        # (cumulative) estimate only applies from the NEXT round on.
        go, reason = precopy_should_continue(
            rnd + 1, max_rounds, delta_bytes, prev_delta,
            dirty_rate, link_rate, ratio)
        if not go:
            # Hitting the round cap or fully converging is the plan
            # working, not a degrade; every other stop is surfaced.
            if reason and not reason.startswith(_PRECOPY_CLEAN_STOPS):
                degraded = reason
                log.warning("pre-copy convergence: %s", degraded)
            break
        if ship_bytes_total and ship_seconds_total > 0:
            link_rate = ship_bytes_total / ship_seconds_total
        prev_delta = delta_bytes
        rnd += 1

    flight.emit("precopy.end", pod=opts.pod_name, rounds=len(round_deltas))
    if info is not None:
        info.update({
            "rounds": len(round_deltas),
            "round_deltas": round_deltas,
            "degraded": degraded,
        })
    # Capture what the live phase shipped (source-side identity): the
    # blackout upload skips exactly those files — retry-safe, because a
    # fresh Job attempt starts with an empty capture.
    return tree_state(opts.work_dir)


def resume_pod_workloads(
    runtime: FakeRuntime, pod_name: str, pod_namespace: str,
    device_hook: DeviceCheckpointHook,
) -> tuple[list[str], list[int], list[str]]:
    """Best-effort unfreeze + unquiesce of every container in the pod:
    cgroup resume first (a frozen process cannot acknowledge the agentlet
    toggle), then device resume per running pid. Each step is independent
    — one unreachable agentlet must not strand the next container.
    Returns ``(resumed_container_ids, resumed_pids, errors)``."""
    resumed_containers: list[str] = []
    resumed_pids: list[int] = []
    errors: list[str] = []
    flight.emit("resume.start", pod=pod_name)
    containers = runtime.list_containers(pod_name, pod_namespace, state=None)
    for container in containers:
        try:
            task = runtime.get_task(container.id)
        except KeyError:
            continue
        if task.state == TaskState.PAUSED:
            try:
                runtime.resume(container.id)
                resumed_containers.append(container.id)
            except Exception as exc:  # noqa: BLE001 — keep going per container
                errors.append(f"unpause {container.id}: {exc}")
    for container in containers:
        try:
            task = runtime.get_task(container.id)
        except KeyError:
            continue
        if task.state != TaskState.RUNNING:
            continue  # dead/never-started: nothing to unquiesce
        try:
            device_hook.resume(task.pid)
            resumed_pids.append(task.pid)
        except Exception as exc:  # noqa: BLE001 — unreachable agentlet is fine
            errors.append(f"unquiesce pid {task.pid}: {exc}")
    flight.emit("resume.end", pod=pod_name,
                containers=len(resumed_containers),
                pids=len(resumed_pids), errors=len(errors))
    return resumed_containers, resumed_pids, errors


def _wire_connect(opts: CheckpointOptions) -> WireSender | None:
    """Dial the destination's WireReceiver (endpoint published into the
    shared PVC work dir). None → no receiver / connect failure: the
    caller proceeds on the PVC path, loudly."""
    wait_s = config.WIRE_ENDPOINT_WAIT_S.get()
    endpoint = read_wire_endpoint(opts.dst_dir, wait_s=wait_s)
    if endpoint is None:
        log.warning(
            "wire migration requested but no %s appeared under %s within "
            "%.1fs — falling back to the PVC double-hop",
            WIRE_ENDPOINT_FILE, opts.dst_dir, wait_s)
        WIRE_FALLBACKS.inc(stage="connect")
        return None
    try:
        return WireSender(endpoint, streams=config.WIRE_STREAMS.get())
    except WireError as exc:
        log.warning("wire connect to %s failed (%s) — falling back to the "
                    "PVC double-hop", endpoint, exc)
        WIRE_FALLBACKS.inc(stage="connect")
        return None


def _mark_pvc_tee_complete(dst_dir: str) -> None:
    """Wire mode: signal that the PVC now holds the complete checkpoint
    tree (the destination's wire→PVC fallback gates on this). Atomic:
    the sentinel's *existence* is the signal, so it must never be
    observable mid-write (a poll between create and fsync would gate
    the fallback on a tree the tee hasn't finished)."""
    from grit_tpu.metadata import atomic_write_text  # noqa: PLC0415

    atomic_write_text(os.path.join(dst_dir, PVC_TEE_COMPLETE_FILE), "ok")


def run_checkpoint(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    device_hook: DeviceCheckpointHook | None = None,
    preshipped: dict[str, tuple[int, int]] | None = None,
    slice_role=None,
) -> TransferStats:
    """RunCheckpoint (reference checkpoint.go:13-21): runtime checkpoint,
    then upload to the PVC. With ``opts.pre_copy``, a live full dump ships
    first and the blackout dump+upload carries only the delta;
    ``preshipped`` marks that phase as already run (its return value).

    Wire mode (``migration_path="wire"``): the HBM dump streams its
    chunks straight to the destination agent while it drains, the
    remaining checkpoint files follow over the same wire, and the PVC
    upload runs concurrently as a durability tee — off the blackout
    path, which now ends at the destination's commit ack. Any wire
    failure degrades to exactly the PVC flow above, loudly."""

    from grit_tpu.obs import trace

    hook = device_hook or NoopDeviceHook()
    # Gang slice migration: this leg is one replica of a gang — its
    # flight role carries the host ordinal (gritscope's per-host lane
    # key) and its progress snapshot the ord field. Everything else on
    # the leg is byte-identical to the single-host flow.
    flight.configure(opts.work_dir,
                     "source" if slice_role is None
                     else slice_role.flight_role("source"))
    # Live telemetry: fresh tracker per migration leg, but ADOPT a
    # split-phase pre-copy's counters (the harness runs
    # run_precopy_phase separately — zeroing here would erase the live
    # pass from bytesShipped).
    uid = progress.uid_from_dir(opts.work_dir)
    ordinal = slice_role.ordinal if slice_role is not None else None
    tracker = (progress.adopt(uid, progress.ROLE_SOURCE,
                              publish_dir=opts.work_dir, ordinal=ordinal)
               if preshipped is not None else
               progress.configure(uid, progress.ROLE_SOURCE,
                                  publish_dir=opts.work_dir,
                                  ordinal=ordinal))
    path = resolved_migration_path(opts.migration_path)
    if path == "wire":
        # A previous attempt's marker must not release the destination's
        # PVC fallback before THIS attempt's tee completes.
        try:
            os.unlink(os.path.join(opts.dst_dir, PVC_TEE_COMPLETE_FILE))
        except OSError:
            pass
    pre_tokens = _mirror_tokens(opts)
    shipped: dict | None = preshipped
    if opts.pre_copy and shipped is None:
        shipped = run_precopy_phase(runtime, opts, hook)
    wire = _wire_connect(opts) if path == "wire" else None
    # Enclosing lowest-priority flight phase for the agent's whole
    # source-side blackout leg: the glue between the named phases
    # (RPC dispatch, bookkeeping, exception propagation) is agent
    # machinery too — attribution must own it, not report it as a gap.
    flight.emit("source.start", pod=opts.pod_name)
    try:
        # Blackout legs: these two spans are the latency budget's
        # source half.
        try:
            tracker.set_phase("dump")
            with trace.span("agent.quiesce_dump"):
                wire_shipped, overlap_bytes, workload_sent = \
                    runtime_checkpoint_pod(runtime, opts, hook, wire=wire)
        except BaseException as exc:
            # A dump/quiesce failure must not strand the wire: without
            # the fail frame the destination would idle out its full
            # restore timeout on live-but-silent connections instead of
            # failing fast.
            if wire is not None:
                wire.fail(f"checkpoint failed before wire send: {exc}")
                wire.close()
            raise

        try:
            return _ship_checkpoint(runtime, opts, hook, wire, shipped,
                                    pre_tokens, path, wire_shipped,
                                    overlap_bytes, workload_sent)
        except BaseException:
            # Post-dump failure (upload or wire leg): with leave_running
            # off (migration semantics) the workload is still parked
            # from the dump — the stranded-quiesced-source case. Resume
            # it before surfacing the error: the paper invariant is that
            # a failed migration leg never costs the source its training
            # run. (The in-dump failure case is handled by
            # runtime_checkpoint_pod's own finally; leave_running dumps
            # already resumed on success.)
            if not opts.leave_running:
                _ids, _pids, errors = resume_pod_workloads(
                    runtime, opts.pod_name, opts.pod_namespace, hook)
                if errors:
                    log.warning("error-path resume after failed ship: %s",
                                errors)
            raise
    finally:
        flight.emit("source.end", pod=opts.pod_name)
        tracker.publish()  # terminal snapshot for watch/annotation


def _ship_checkpoint(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    hook: DeviceCheckpointHook,
    wire: WireSender | None,
    shipped: dict | None,
    pre_tokens: dict[str, tuple[int, int]],
    path: str,
    wire_shipped: dict[str, int] | None,
    overlap_bytes: int,
    workload_sent: int,
) -> TransferStats:
    """The post-dump transport legs of :func:`run_checkpoint` (upload, or
    wire + PVC durability tee)."""
    from grit_tpu.obs import trace

    if wire is not None:
        # The wire_send phase brackets the WHOLE post-dump wire leg —
        # skip-set computation, tree send, commit (nested), teardown and
        # the bounded tee join — so a chaos abort anywhere in it leaves
        # no unattributed tail.
        flight.emit("wire.send.start",
                    skip=len(wire_shipped) if wire_shipped else 0)
    skip = dict(shipped or {})
    # Files the dump's streaming mirror already landed at dst (it
    # commits atomically, so a committed mirror == shipped bytes).
    skip.update(_mirrored_skip(opts, pre_tokens))

    # Telemetry: the total is now knowable — bytes already counted plus
    # what this leg still ships (tree minus the skip sets). Published
    # BEFORE the transport starts, so the CR shows a finite ETA while
    # frames are in flight, not only in hindsight.
    tracker = progress.get(progress.ROLE_SOURCE)
    if tracker is not None:
        # Same skip semantics as the transports, not key-presence: a
        # skip_unchanged entry only skips while its (size, mtime_ns)
        # still matches — a file dirtied since the pre-copy capture
        # RE-SHIPS and must stay in the total, or bytesShipped runs
        # past totalBytes and the stall verdict disarms mid-tail.
        # Dump-streamed rels (wire_shipped) skip by key, like send_tree.
        wire_rels = set(wire_shipped) if wire_shipped else set()
        remaining = sum(
            st[0] for rel, st in tree_state(opts.work_dir).items()
            if rel not in wire_rels and skip.get(rel) != st)
        tracker.set_total(
            tracker.snapshot()["bytesShipped"] + remaining)
        tracker.set_phase("wire_send" if wire is not None else "upload")
        tracker.publish()

    if wire is None:
        with trace.span("agent.upload"):
            faults.fault_point("agent.checkpoint.upload")
            flight.emit("upload.start")
            stats = None
            try:
                stats = transfer_data(
                    opts.work_dir, opts.dst_dir, direction="upload",
                    skip_unchanged=skip or None,
                )
            finally:
                # Close the bracket on failure too — an unterminated
                # upload would be extended over the abort/resume tail.
                flight.emit(
                    "upload.end", ok=stats is not None,
                    **({"bytes": stats.bytes, "files": stats.files,
                        "skipped": stats.skipped}
                       if stats is not None else {}))
        if path == "wire":
            _mark_pvc_tee_complete(opts.dst_dir)
        return stats

    # Wire leg + concurrent PVC durability tee. The tee reads the same
    # (immutable, post-dump) work dir the wire sends from; whichever
    # finishes last bounds the agent Job, but the destination resumes at
    # the wire ack — the tee is off the blackout path by construction.
    tee_box: dict = {}

    def _tee() -> None:
        try:
            with trace.span("agent.pvc_tee", parent=tee_parent):
                flight.emit("upload.start", tee=True)
                try:
                    tee_box["stats"] = transfer_data(
                        opts.work_dir, opts.dst_dir, direction="upload",
                        skip_unchanged=skip or None,
                        # The wire already counts these bytes as they hit
                        # sockets; the durability tee re-reading the same
                        # tree must not double bytesShipped.
                        count_progress=False,
                    )
                finally:
                    stats = tee_box.get("stats")
                    flight.emit(
                        "upload.end", tee=True, ok=stats is not None,
                        **({"bytes": stats.bytes}
                           if stats is not None else {}))
        except BaseException as exc:  # noqa: BLE001 — re-raised after join
            tee_box["error"] = exc

    # The tee thread's span joins the migration trace (the thread-local
    # parent does not cross thread creation on its own).
    tee_parent = trace.current_context()

    tee = threading.Thread(target=_tee, name="grit-pvc-tee", daemon=True)
    tee.start()
    try:
        if wire_shipped is None:
            # The device leg's wire tee failed mid-dump: the stream has
            # holes the receiver cannot trust — abort the whole session.
            raise WireError("device dump wire tee failed")
        with trace.span("agent.wire_send"):
            faults.fault_point("agent.checkpoint.wire_send")
            wire.send_tree(
                opts.work_dir, skip=set(wire_shipped),
                skip_unchanged=shipped or None)
            # Commit the FULL tree: files skipped as prestaged are
            # verified from the destination's disk by the receiver.
            files = {rel: st[0]
                     for rel, st in tree_state(opts.work_dir).items()}
            files.update(wire_shipped)
            faults.fault_point("agent.checkpoint.commit")
            if tracker is not None:
                tracker.set_phase("commit")
            wire.commit(files, timeout=config.WIRE_COMMIT_TIMEOUT_S.get())
        total_wire = workload_sent + wire.sent_bytes
        if total_wire:
            # Share of this session's wire bytes that were already at a
            # socket while the HBM dump still drained — the dump/send
            # overlap, from the real migration path (bench mirrors it).
            WIRE_OVERLAP_FRACTION.set(overlap_bytes / total_wire)
    except WireError as exc:
        log.warning(
            "wire migration failed mid-stream (%s) — destination falls "
            "back to the PVC path; the durability tee ships everything",
            exc)
        WIRE_FALLBACKS.inc(stage="send")
        wire.fail(str(exc))
    finally:
        wire.close()
        # The durability tee must finish before the marker drops, but an
        # unbounded join on a wedged NFS write pins the agent past every
        # watchdog deadline. Bound it by the stage timeout, logging each
        # interval so a slow-but-alive tee is visible in the Job log.
        deadline = time.monotonic() + stage_timeout_s()
        while tee.is_alive():
            tee.join(timeout=30.0)
            if tee.is_alive():
                if time.monotonic() > deadline:
                    tee_box.setdefault("error", TimeoutError(
                        "PVC durability tee still running after "
                        f"{stage_timeout_s():.0f}s — checkpoint is not "
                        "durable; failing the leg"))
                    break
                log.warning("PVC durability tee still uploading; waiting")
        flight.emit("wire.send.end", bytes=wire.sent_bytes)
    if "error" in tee_box:
        raise tee_box["error"]
    _mark_pvc_tee_complete(opts.dst_dir)
    return tee_box["stats"]


def runtime_checkpoint_pod(
    runtime: FakeRuntime,
    opts: CheckpointOptions,
    device_hook: DeviceCheckpointHook,
    wire: WireSender | None = None,
) -> tuple[dict[str, int] | None, int, int]:
    """RuntimeCheckpointPod (reference runtime.go:34-71).

    With ``wire``, each container's HBM dump streams its chunks to the
    destination as they drain; returns ``(shipped, overlap_bytes,
    workload_sent)`` — ``shipped`` maps ``{rel: nbytes}`` of what crossed
    (for the agent's send_tree skip + commit map), or None when any
    container's wire tee failed (the caller then aborts the wire session
    and the PVC path carries everything); the byte counts feed the
    session's dump/send overlap gauge."""

    containers = runtime.list_containers(
        opts.pod_name, opts.pod_namespace, TaskState.RUNNING
    )
    if not containers:
        raise RuntimeError(
            f"no running containers for pod {opts.pod_namespace}/{opts.pod_name}"
        )
    os.makedirs(opts.work_dir, exist_ok=True)
    wire_shipped: dict[str, int] | None = {} if wire is not None else None
    wire_overlap_bytes = 0
    wire_workload_bytes = 0

    # Phase order is load-bearing:
    #   1. device quiesce+dump for every container — the toggle protocol is
    #      cooperative, so workload threads must still be RUNNING to reach
    #      a step boundary and answer the agentlet socket;
    #   2. cgroup-pause ALL containers — a multi-container pod snapshot
    #      must be a consistent cut (fixes reference TODO runtime.go:63);
    #   3. process dumps (CRIU) under the freeze.
    # (The reference's cuda-checkpoint toggle likewise precedes the CRIU
    # freeze — SURVEY §5 "device state".)
    paused: list[str] = []
    quiesced: list[int] = []
    failed = False
    blackout_start = time.monotonic()
    try:
        for container in containers:
            faults.fault_point("agent.checkpoint.dump")
            work_dir = _prepare_work_dir(opts, container)
            task = runtime.get_task(container.id)
            # Record BEFORE dumping: a dump that fails after quiescing (or a
            # quiesce timeout that leaves the pause request pending) must
            # still get its error-path resume, or the workload stays parked
            # at the barrier forever. Resume is best-effort and tolerates
            # pids that never quiesced.
            quiesced.append(task.pid)
            # Gate on opts.pre_copy: a stale committed '-precopy' sibling
            # in a reused work dir must not silently turn a plain
            # checkpoint into a delta against old data.
            outcome = device_hook.dump(
                task.pid, work_dir,
                base=(_precopy_base(opts.work_dir, container.name)
                      if opts.pre_copy else None),
                # Mirror to the FINAL dst layout (<name>, not <name>-work):
                # the work dir is renamed after the dump, the mirror isn't.
                mirror=(os.path.join(opts.dst_dir, container.name)
                        if opts.stream_upload else None),
                # Only passed in wire mode: hooks predating the wire
                # kwarg keep working on the pvc path unmodified.
                **({"wire": {"endpoint": wire.endpoint,
                             "prefix": f"{container.name}/{HBM_SUBDIR}"}}
                   if wire is not None else {}),
            )
            if wire_shipped is not None:
                if outcome is None:
                    continue  # no device state: nothing crossed the wire
                if not outcome.get("ok"):
                    log.warning(
                        "container %s device dump wire tee failed: %s",
                        container.name, outcome.get("error"))
                    wire_shipped = None
                else:
                    wire_shipped.update(
                        {str(r): int(n)
                         for r, n in outcome.get("files", {}).items()})
                    wire_overlap_bytes += int(
                        outcome.get("dump_overlap_bytes", 0))
                    wire_workload_bytes += int(
                        outcome.get("sent_bytes", 0))
        # One criu_dump bracket over freeze + process dumps + image
        # finalize: the whole under-the-freeze stretch is process-dump
        # machinery, and attribution must own it end to end. The end
        # event closes on failure too (finally), or the unterminated
        # interval would stretch over the recovery tail.
        flight.emit("criu.dump.start", containers=len(containers))
        criu_ok = False
        try:
            for container in containers:
                runtime.pause(container.id)
                paused.append(container.id)
            for container in containers:
                _checkpoint_container(runtime, container, opts)
            criu_ok = True
        finally:
            flight.emit("criu.dump.end", containers=len(containers),
                        ok=criu_ok)
    except BaseException:
        failed = True
        raise
    finally:
        # Resume when leave-running was requested, and ALWAYS on failure —
        # a failed checkpoint must not strand quiesced workloads parked at
        # the agentlet barrier (this is the "agent's error-path resume" the
        # toggle protocol relies on).
        if opts.leave_running or failed:
            flight.emit("resume.start", pod=opts.pod_name, failed=failed)
            for cid in paused:
                try:
                    runtime.resume(cid)
                except Exception:  # noqa: BLE001 - resume best-effort
                    pass
            # Device resume strictly after unfreeze: a frozen process
            # cannot acknowledge the toggle.
            for pid in quiesced:
                try:
                    device_hook.resume(pid)
                except Exception:  # noqa: BLE001
                    pass
            flight.emit("resume.end", pod=opts.pod_name, failed=failed)
        BLACKOUT_SECONDS.set(time.monotonic() - blackout_start)
        CHECKPOINTS_TOTAL.inc(outcome="failed" if failed else "succeeded")
    return wire_shipped, wire_overlap_bytes, wire_workload_bytes


def _prepare_work_dir(opts: CheckpointOptions, container) -> str:
    """Fresh ``<name>-work`` dir for this container's image (device dump
    lands here first, before the freeze)."""
    work_dir = os.path.join(opts.work_dir, container.name) + WORK_SUFFIX
    if os.path.exists(work_dir):
        shutil.rmtree(work_dir)
    os.makedirs(work_dir)
    return work_dir


def _checkpoint_container(
    runtime: FakeRuntime, container, opts: CheckpointOptions,
) -> None:
    """runtimeCheckpointContainer (reference runtime.go:90-157): dump into
    ``<name>-work`` (already holding the device snapshot), atomically
    rename to ``<name>`` on success."""

    final_dir = os.path.join(opts.work_dir, container.name)
    work_dir = final_dir + WORK_SUFFIX

    # CRIU-image dir (reference writeCriuCheckpoint :177-186).
    image_dir = os.path.join(work_dir, CHECKPOINT_DIRECTORY)
    criu_work = os.path.join(work_dir, "criu-work")
    runtime.checkpoint_task(container.id, image_dir, criu_work)

    # rootfs rw-layer diff, streamed to disk — never buffered in agent
    # memory while the pod is paused (reference writeRootFsDiffTar
    # :188-224).
    runtime.write_rootfs_diff(container.id,
                              os.path.join(work_dir, ROOTFS_DIFF_TAR))

    # config.dump / spec.dump (reference TODO runtime.go:145 — implemented).
    with open(os.path.join(work_dir, CONFIG_DUMP), "w") as f:
        json.dump({"id": container.id, "name": container.name,
                   "image": container.spec.image}, f)
    with open(os.path.join(work_dir, SPEC_DUMP), "w") as f:
        json.dump({"annotations": container.spec.annotations,
                   "args": container.spec.args}, f)

    # Newest kubelet container log (reference writeContainerLog :230-272).
    log_src = newest_container_log(
        opts.kubelet_log_root, opts.pod_namespace, opts.pod_name, opts.pod_uid,
        container.name,
    )
    if log_src:
        shutil.copyfile(log_src, os.path.join(work_dir, CONTAINER_LOG_FILE))

    # Atomic finalize (reference :147-152).
    if os.path.exists(final_dir):
        shutil.rmtree(final_dir)
    os.rename(work_dir, final_dir)


def newest_container_log(
    log_root: str, namespace: str, pod_name: str, pod_uid: str, container_name: str
) -> str | None:
    """Pick the lexically-newest ``*.log`` in the kubelet container log dir
    ``<root>/<ns>_<pod>_<uid>/<container>/`` (reference getPodLogPath
    :226-228 + writeContainerLog :230-272; its table test covers missing
    dir / empty dir / non-log files — mirrored in our tests)."""

    log_dir = os.path.join(log_root, f"{namespace}_{pod_name}_{pod_uid}", container_name)
    if not os.path.isdir(log_dir):
        return None
    logs = sorted(glob.glob(os.path.join(log_dir, "*.log")))
    return logs[-1] if logs else None
