"""grit-agent: the node-side data mover job.

Parity: reference ``cmd/grit-agent`` + ``pkg/gritagent`` — a one-shot CLI
(``--action checkpoint|restore``) that drives the container runtime to dump a
pod, moves checkpoint bytes between the node's host path and the checkpoint
PVC, and drops the ``download-state`` sentinel the CRI interceptor polls.
"""

from grit_tpu.agent.app import main, run  # noqa: F401
