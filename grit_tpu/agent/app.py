"""grit-agent CLI: one-shot dispatch on ``--action``.

Parity: reference ``cmd/grit-agent/app/{app.go,options/options.go}`` — flags
with env-var fallbacks (``ACTION``, ``TARGET_NAMESPACE``, ``TARGET_NAME``,
``TARGET_UID``), default runtime endpoint ``/run/containerd/containerd.sock``,
default kubelet log path ``/var/log/pods`` (options.go:45-59); dispatch to
checkpoint / restore (app.go:60-71). Run as ``python -m grit_tpu.agent``.
"""

from __future__ import annotations

import argparse
import os
import sys

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.agent.checkpoint import (
    CheckpointOptions,
    resolved_migration_path,
    run_checkpoint,
)
from grit_tpu.agent.copy import WireError
from grit_tpu.agent.lease import lease_from_env
from grit_tpu.agent.restore import (
    RestoreOptions,
    run_restore,
    run_restore_streamed,
    run_restore_wire,
)
from grit_tpu.agent.termination import (
    EXIT_OK,
    classify_exception,
    clear_termination,
    exit_code_for,
    write_termination,
)
from grit_tpu.obs import trace

DEFAULT_RUNTIME_ENDPOINT = "/run/containerd/containerd.sock"
DEFAULT_KUBELET_LOG_PATH = "/var/log/pods"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="grit-agent")
    env = os.environ
    p.add_argument("--action", default=env.get("ACTION", ""),
                   choices=["checkpoint", "restore", "cleanup", "abort", ""])
    p.add_argument("--src-dir", default="")
    p.add_argument("--dst-dir", default="")
    p.add_argument("--host-work-path", default="")
    p.add_argument("--runtime-endpoint", default=DEFAULT_RUNTIME_ENDPOINT)
    p.add_argument("--kubelet-log-path", default=DEFAULT_KUBELET_LOG_PATH)
    p.add_argument("--target-namespace", default=env.get("TARGET_NAMESPACE", "default"))
    p.add_argument("--target-name", default=env.get("TARGET_NAME", ""))
    p.add_argument("--target-uid", default=env.get("TARGET_UID", ""))
    p.add_argument("--metrics-port", type=int,
                   default=int(env.get("METRICS_PORT", "0")),
                   help="serve /metrics during the run (0 = disabled)")
    p.add_argument("--pre-copy", action="store_true",
                   default=env.get("PRE_COPY", "") == "true",
                   help="checkpoint in two passes: live full HBM dump + "
                        "upload while the workload runs, then a delta-only "
                        "dump inside the blackout window")
    p.add_argument("--standby", action="store_true",
                   default=env.get("STANDBY", "") == "true",
                   help="preemption-armed standby: after the round-0 full "
                        "dump the agent stays resident, keeping the "
                        "destination's flattened base warm with governed "
                        "delta rounds, until a fire signal (grit.dev/fire "
                        "Job annotation, .grit-fire file, SIGTERM) runs "
                        "only the final delta + blackout")
    p.add_argument("--stream-restore", action="store_true",
                   default=env.get("STREAM_RESTORE", "") == "true",
                   help="stage with chunk-streamed journaling: the "
                        "download-state sentinel drops as soon as the "
                        "metadata priority set lands, so the restored pod "
                        "starts (and begins placing arrays through the "
                        "stage journal) while bulk HBM chunks are still "
                        "in flight from the PVC")
    p.add_argument("--migration-path",
                   default=config.MIGRATION_PATH.raw() or "",
                   choices=["pvc", "wire", ""],
                   help="migration data path: pvc = double hop through the "
                        "checkpoint PVC (default); wire = direct source-to-"
                        "destination stream (the checkpoint agent dials the "
                        "restore agent's published endpoint and ships "
                        "chunks as the dump drains; the PVC upload runs as "
                        "an async durability tee off the blackout path). "
                        "Wire failures fall back to pvc loudly")
    p.add_argument("--criu-pid", type=int,
                   default=int(env.get("CRIU_PID", "0")),
                   help="checkpoint this raw pid with real CRIU instead of "
                        "going through a container runtime (the "
                        "tuning-job-style node validation path)")
    p.add_argument("--slice-hosts", type=int,
                   default=int(config.SLICE_HOSTS.get()),
                   help="gang slice migration: host count of the slice "
                        "this agent leg belongs to (>1 runs the gang "
                        "protocol — cross-host quiesce barrier, shared "
                        "ledger, all-or-nothing gang commit, slice-wide "
                        "abort); 0/1 = the single-host flow")
    p.add_argument("--slice-ordinal", type=int,
                   default=int(config.SLICE_ORDINAL.get()),
                   help="this agent leg's host ordinal within the slice "
                        "(0-based)")
    return p


def run(argv: list[str], runtime=None, device_hook=None) -> int:
    """Dispatch (reference app.go:60-71). ``runtime`` is injected in tests;
    on a real node it is the containerd adapter for --runtime-endpoint."""

    opts = build_parser().parse_args(argv)
    # Validate any armed fault points NOW — syntax AND point names: a
    # typo'd GRIT_FAULT_POINTS must fail the Job loudly (terminal —
    # FaultSyntaxError is in the non-retriable set) instead of silently
    # disarming a chaos run.
    faults.validate_fault_points(config.FAULT_POINTS.get())
    # Every agent log line carries the migration uid/role once the
    # driver configures the flight recorder — node logs join gritscope
    # timelines by uid instead of by wall-clock grep. The agent owns
    # its process, so it may install a stderr handler when none exists
    # (the workload-side installs must not — see logctx).
    from grit_tpu.obs.logctx import install_log_correlation  # noqa: PLC0415

    install_log_correlation(ensure_handler=True)
    metrics_srv = None
    if opts.metrics_port:
        from grit_tpu.obs import start_metrics_server  # noqa: PLC0415

        metrics_srv = start_metrics_server(opts.metrics_port)
    # Periodic observability sampler: keeps the progress gauges and the
    # codec queue depth fresh between events for the whole run (clean
    # bounded-join shutdown in the finally).
    from grit_tpu.obs import sampler as obs_sampler  # noqa: PLC0415

    obs_sampler.start()
    # Heartbeat lease: proof-of-life for the manager watchdog while the
    # agent works (no-op unless the environment asks for one).
    lease = lease_from_env()
    if lease is not None:
        lease.start()
    try:
        return _dispatch(opts, runtime, device_hook)
    finally:
        if lease is not None:
            lease.stop()
        obs_sampler.stop()
        if metrics_srv is not None:
            metrics_srv.shutdown()


def run_classified(argv: list[str], runtime=None, device_hook=None) -> int:
    """:func:`run` wrapped in the termination contract (what ``main``
    executes): failures are classified retriable-vs-terminal, recorded in
    the work dir's termination-reason file for the manager watchdog, and
    mapped to the distinct exit codes — instead of one opaque nonzero
    status burning Job backoffLimit on terminal causes."""
    opts = build_parser().parse_args(argv)
    work_dir = opts.host_work_path or opts.src_dir
    clear_termination(work_dir)  # this attempt speaks for itself
    try:
        return run(argv, runtime=runtime, device_hook=device_hook)
    except BaseException as exc:
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            raise
        reason, retriable = classify_exception(exc)
        write_termination(work_dir, reason, str(exc), retriable,
                          action=opts.action)
        print(f"grit-agent: {exc}", file=sys.stderr)
        return exit_code_for(retriable)


def _slice_role(opts):
    """The gang identity from the CLI flags (env-backed defaults), or
    None for the single-host flow. Flags are re-exported into the env
    so the device hook (which asks the agentlet for the slice cut) and
    the ledger see the same identity the driver runs with."""
    if opts.slice_hosts <= 1:
        return None
    from grit_tpu.agent.slicerole import SliceRole  # noqa: PLC0415

    os.environ[config.SLICE_HOSTS.name] = str(opts.slice_hosts)
    os.environ[config.SLICE_ORDINAL.name] = str(opts.slice_ordinal)
    return SliceRole(ordinal=opts.slice_ordinal, hosts=opts.slice_hosts)


def _dispatch(opts, runtime, device_hook) -> int:
    slice_role = _slice_role(opts)
    if opts.action == "checkpoint":
        if runtime is None and opts.criu_pid:
            from grit_tpu.cri.criu import CriuProcessRuntime, criu_available
            from grit_tpu.cri.minicriu import (
                MiniCriuProcessRuntime,
                minicriu_available,
            )
            from grit_tpu.cri.runtime import Container, OciSpec, Sandbox

            ok, why = criu_available()
            if ok:
                runtime = CriuProcessRuntime()
            elif minicriu_available():
                # Engine fallback: the in-tree ptrace C/R engine serves
                # the raw-pid path on hosts without a criu binary (same
                # driver flow; scope documented in cri/minicriu.py).
                runtime = MiniCriuProcessRuntime()
            else:
                raise RuntimeError(
                    f"--criu-pid requires usable criu (or the minicriu "
                    f"engine): {why}")
            runtime.add_sandbox(Sandbox(
                id="sb0", pod_name=opts.target_name,
                pod_namespace=opts.target_namespace, pod_uid=opts.target_uid,
            ))
            runtime.attach_process(
                Container(id="c0", sandbox_id="sb0", name="main",
                          spec=OciSpec(image="raw-process")),
                opts.criu_pid,
            )
        if runtime is None:
            # Production path: CRI gRPC discovery + shim TTRPC task ops
            # (reference runtime.go:46-224 loads the containerd client
            # here).
            from grit_tpu.cri.grpc_runtime import GrpcCriRuntime  # noqa: PLC0415

            endpoint = opts.runtime_endpoint
            if "://" not in endpoint:
                endpoint = "unix://" + endpoint
            runtime = GrpcCriRuntime(cri_endpoint=endpoint)
        if device_hook is None:
            # Per-pid auto-dispatch: TPU toggle path for workloads running
            # an agentlet, no-op for CPU-only pods.
            from grit_tpu.device.hook import AutoDeviceHook  # noqa: PLC0415

            device_hook = AutoDeviceHook()
        ckpt_opts = CheckpointOptions(
            pod_name=opts.target_name,
            pod_namespace=opts.target_namespace,
            pod_uid=opts.target_uid,
            work_dir=opts.host_work_path or opts.src_dir,
            dst_dir=opts.dst_dir,
            kubelet_log_root=opts.kubelet_log_path,
            pre_copy=opts.pre_copy or opts.standby,
            migration_path=opts.migration_path,
        )
        if opts.standby and slice_role is not None:
            # Terminal, not silent: an armed standby's governed rounds
            # would need the gang barrier per probe — not built yet.
            raise RuntimeError(
                "--standby with --slice-hosts > 1 is not supported: "
                "gang standby needs per-round barrier coordination")
        if opts.standby:
            # Preemption-armed standby: the Job stays resident, armed,
            # until the fire protocol ends it — SIGTERM (the kubelet's
            # shutdown notice) included.
            from grit_tpu.agent.standby import (  # noqa: PLC0415
                arm_sigterm_fire,
                run_standby_checkpoint,
            )

            arm_sigterm_fire()
            with trace.span(
                    "agent.standby", parent=trace.extract_parent(),
                    pod=f"{opts.target_namespace}/{opts.target_name}"):
                run_standby_checkpoint(runtime, ckpt_opts,
                                       device_hook=device_hook)
            return 0
        # The agent's spans join the migration trace the manager minted
        # (TRACEPARENT env in the Job spec, W3C convention).
        with trace.span("agent.checkpoint", parent=trace.extract_parent(),
                        pod=f"{opts.target_namespace}/{opts.target_name}"):
            if slice_role is not None:
                from grit_tpu.agent.slicerole import (  # noqa: PLC0415
                    run_slice_checkpoint,
                )

                run_slice_checkpoint(runtime, ckpt_opts, role=slice_role,
                                     device_hook=device_hook)
            else:
                run_checkpoint(
                    runtime,
                    ckpt_opts,
                    device_hook=device_hook,
                )
        return 0
    if opts.action == "restore":
        with trace.span("agent.restore", parent=trace.extract_parent()):
            ropts = RestoreOptions(src_dir=opts.src_dir, dst_dir=opts.dst_dir)
            if slice_role is not None:
                from grit_tpu.agent.slicerole import (  # noqa: PLC0415
                    gang_commit_staged,
                    run_slice_restore,
                )

                if resolved_migration_path(opts.migration_path) == "wire":
                    # Wire gang leg: this host pair's own wire session
                    # (per-stream sockets, GRIT_WIRE_IFACES striping —
                    # the N×N shape), received WITHOUT dropping the
                    # sentinel; the gang-commit park follows. Wire
                    # failure falls back to the PVC gang path, loudly.
                    handle = run_restore_wire(ropts, prestage=True)
                    try:
                        handle.wait(
                            timeout=config.WIRE_RESTORE_TIMEOUT_S.get(),
                            drop_sentinel=False)
                    except WireError as exc:
                        print(f"grit-agent: wire slice restore failed "
                              f"({exc}); falling back to the PVC gang "
                              "path", file=sys.stderr)
                        handle.receiver.close()
                        # Like the single-host fallback(): wait for the
                        # source's durability-tee marker before staging.
                        # Without it the fallback can stage a PVC tree
                        # the source is STILL uploading, verify partial-
                        # against-partial, park prepared — and the gang
                        # later commits an incomplete restore once the
                        # source's dumped marker lands.
                        import time as _time  # noqa: PLC0415

                        from grit_tpu.metadata import (  # noqa: PLC0415
                            PVC_TEE_COMPLETE_FILE,
                        )

                        marker = os.path.join(ropts.src_dir,
                                              PVC_TEE_COMPLETE_FILE)
                        deadline = _time.monotonic() \
                            + config.WIRE_TEE_WAIT_S.get()
                        while not os.path.isfile(marker) \
                                and _time.monotonic() < deadline:
                            _time.sleep(0.2)
                        if not os.path.isfile(marker):
                            print("grit-agent: no PVC tee marker after "
                                  f"{config.WIRE_TEE_WAIT_S.get():.0f}s — "
                                  "staging what the PVC holds",
                                  file=sys.stderr)
                        run_slice_restore(ropts, role=slice_role)
                        return 0
                    gang_commit_staged(ropts, slice_role)
                else:
                    run_slice_restore(ropts, role=slice_role)
                return 0
            if resolved_migration_path(opts.migration_path) == "wire":
                # Single-hop path: listen for the source's direct stream;
                # the Job IS the receive vehicle. prestage pulls whatever
                # the PVC already holds (the pre-copy base a wire-mode
                # source will skip on the wire) before listening. Any
                # wire failure falls back to staging from the PVC
                # durability tee, loudly.
                handle = run_restore_wire(ropts, prestage=True)
                timeout = config.WIRE_RESTORE_TIMEOUT_S.get()
                try:
                    handle.wait(timeout=timeout)
                except WireError as exc:
                    print(f"grit-agent: wire restore failed ({exc}); "
                          "falling back to the PVC path", file=sys.stderr)
                    handle.fallback()
            elif opts.stream_restore:
                # The Job stays alive until the last chunk lands — it IS
                # the transfer vehicle; only the sentinel drops early.
                run_restore_streamed(ropts).wait()
            else:
                run_restore(ropts)
        return 0
    if opts.action == "cleanup":
        from grit_tpu.agent.cleanup import CleanupOptions, run_cleanup  # noqa: PLC0415

        with trace.span("agent.cleanup", parent=trace.extract_parent()):
            run_cleanup(CleanupOptions(
                work_dir=opts.host_work_path or opts.src_dir,
                dst_dir=opts.dst_dir,
            ))
        return 0
    if opts.action == "abort":
        # Recovery arm (manager watchdog → --action abort Job on the
        # source node): resume the quiesced source workload from live
        # HBM state and clear the dead attempt's partial dump.
        from grit_tpu.agent.abort import AbortOptions, run_abort  # noqa: PLC0415

        if runtime is None:
            from grit_tpu.cri.grpc_runtime import GrpcCriRuntime  # noqa: PLC0415

            endpoint = opts.runtime_endpoint
            if "://" not in endpoint:
                endpoint = "unix://" + endpoint
            runtime = GrpcCriRuntime(cri_endpoint=endpoint)
        if device_hook is None:
            from grit_tpu.device.hook import AutoDeviceHook  # noqa: PLC0415

            device_hook = AutoDeviceHook()
        with trace.span("agent.abort", parent=trace.extract_parent(),
                        pod=f"{opts.target_namespace}/{opts.target_name}"):
            run_abort(
                runtime,
                AbortOptions(
                    pod_name=opts.target_name,
                    pod_namespace=opts.target_namespace,
                    pod_uid=opts.target_uid,
                    work_dir=opts.host_work_path or opts.src_dir,
                    # Slice aborts record the gang ledger's ABORT in the
                    # shared PVC dir: every parked destination of the
                    # gang poisons-and-clears instead of un-parking.
                    gang_shared_dir=(opts.dst_dir
                                     if slice_role is not None else ""),
                ),
                device_hook=device_hook,
            )
        return 0
    print("grit-agent: --action must be checkpoint, restore, cleanup "
          "or abort", file=sys.stderr)
    return 2


def main() -> None:
    rc = run_classified(sys.argv[1:])
    if rc != EXIT_OK:
        sys.exit(rc)


if __name__ == "__main__":
    main()
