"""Streaming data mover: parallel, chunked, checksummed file transfer.

Replaces the reference's naive per-file worker pool
(``pkg/gritagent/copy/copy.go:17-64``) per SURVEY §7.E: the PVC copy is the
blackout bottleneck (126–341 MB/s measured in the reference; §6), so this
mover parallelises *within* large files (chunk-ranged reads/writes into a
preallocated target) as well as across files, overlapping read and write I/O.
The reference's racy error-slice append (copy.go:19,48 — noted in SURVEY §2.1)
is fixed by collecting errors through the executor's future results.

A native C++ engine (``native/datamover``) provides the same interface for
the latency-critical path; :func:`transfer_data` picks it up automatically
when the shared library has been built (``engine="auto"``).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from grit_tpu.obs.metrics import TRANSFER_BYTES, TRANSFER_SECONDS
from grit_tpu.metadata import DOWNLOAD_STATE_FILE, STAGE_JOURNAL_FILE

DEFAULT_WORKERS = 10  # reference copy.go:20 uses a 10-goroutine pool
CHUNK_SIZE = 16 * 1024 * 1024
# Files larger than this are split into parallel chunk copies.
PARALLEL_FILE_THRESHOLD = 64 * 1024 * 1024


class StageJournal:
    """Writer side of the streamed-staging protocol.

    The journal lives at ``<dst_dir>/.grit-stage-journal`` and carries one
    flushed JSON line per event::

        {"file": rel, "staged": n}                contiguous-from-0 bytes ready
        {"file": rel, "staged": n, "done": true}  file fully staged
        {"complete": true} | {"failed": msg}      terminal line

    The device-side reader (``grit_tpu.device.snapshot._StageMonitor``)
    polls it so the restore pipeline can consume a chunk the moment its
    byte range has landed — while later chunks are still crossing from the
    PVC. Large files copied chunk-parallel report a *waterline* (the
    longest complete prefix), which matches consumption order: snapshot
    data files are read front-to-back in manifest order.
    """

    def __init__(self, dst_dir: str) -> None:
        os.makedirs(dst_dir, exist_ok=True)
        self.path = os.path.join(dst_dir, STAGE_JOURNAL_FILE)
        self._f = open(self.path, "w")
        self._lock = threading.Lock()
        self._water: dict[str, int] = {}
        self._pending: dict[str, dict[int, int]] = {}
        self._closed = False

    def _emit(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def note_file(self, rel: str, size: int) -> None:
        """One file fully staged (small copy, or skipped-as-unchanged —
        either way its bytes are valid at the destination)."""
        with self._lock:
            if not self._closed:
                self._emit({"file": rel, "staged": size, "done": True})

    def note_chunk(self, rel: str, offset: int, length: int,
                   size: int) -> None:
        """One chunk of a large file landed; advances (and publishes) the
        file's contiguous waterline."""
        with self._lock:
            if self._closed:
                return
            done = self._pending.setdefault(rel, {})
            done[offset] = length
            water = self._water.get(rel, 0)
            while water in done:
                water += done.pop(water)
            self._water[rel] = water
            if water >= size:
                self._pending.pop(rel, None)
                self._emit({"file": rel, "staged": water, "done": True})
            elif water > 0:
                self._emit({"file": rel, "staged": water})

    def complete(self) -> None:
        with self._lock:
            if not self._closed:
                self._emit({"complete": True})
                self._closed = True
                self._f.close()

    def fail(self, msg: str) -> None:
        """Terminal failure marker: consumers blocked on a never-arriving
        chunk fail loudly instead of hanging out their timeout."""
        with self._lock:
            if not self._closed:
                self._emit({"failed": msg})
                self._closed = True
                self._f.close()


def _stage_priority(rel: str) -> int:
    """Staging order for streamed restores: snapshot metadata first (the
    restore side cannot even plan without MANIFEST/COMMIT), then the
    carried executable cache (needed before the first compile), then the
    remaining small metadata (CRIU image, config/spec dumps), and the bulk
    HBM data files last — they are exactly what the restore pipeline can
    consume incrementally."""
    base = os.path.basename(rel)
    if base in ("COMMIT", "MANIFEST.json") or base.startswith("index-h"):
        return 0
    parts = rel.replace("\\", "/").split("/")
    if "xla_cache" in parts or "compile-cache" in parts:
        return 1
    if not base.startswith("data-h"):
        return 2
    return 3


# Files below this staging priority gate the early sentinel drop: once they
# are all staged the restored pod may start (its restore pipeline waits on
# the rest through the journal).
_DATA_PRIORITY = 3


@dataclass
class TransferStats:
    files: int = 0
    bytes: int = 0
    seconds: float = 0.0
    skipped: int = 0  # files unchanged since an earlier pass (skip_unchanged)
    errors: list[str] = field(default_factory=list)

    @property
    def gbps(self) -> float:
        return self.bytes / self.seconds / 1e9 if self.seconds > 0 else 0.0


def tree_state(src_dir: str) -> dict[str, tuple[int, int]]:
    """``{relpath: (size, mtime_ns)}`` of every file under ``src_dir`` —
    the source-side identity a later :func:`transfer_data` pass can skip
    against (see ``skip_unchanged``)."""
    out = {}
    for path, rel in _iter_files(src_dir):
        st = os.stat(path)
        out[rel] = (st.st_size, st.st_mtime_ns)
    return out


def _iter_files(src: str):
    for root, _dirs, files in os.walk(src):
        for name in files:
            path = os.path.join(root, name)
            yield path, os.path.relpath(path, src)


def _copy_small(src_path: str, dst_path: str) -> int:
    os.makedirs(os.path.dirname(dst_path), exist_ok=True)
    shutil.copyfile(src_path, dst_path)
    shutil.copymode(src_path, dst_path)
    return os.path.getsize(dst_path)


def _copy_chunk(src_path: str, dst_path: str, offset: int, length: int) -> int:
    with open(src_path, "rb") as fsrc, open(dst_path, "r+b") as fdst:
        fsrc.seek(offset)
        fdst.seek(offset)
        remaining = length
        while remaining > 0:
            buf = fsrc.read(min(CHUNK_SIZE, remaining))
            if not buf:
                # Source shrank since it was sized: a silent short copy would
                # leave zero-filled holes in the preallocated destination.
                raise IOError(
                    f"short read: {src_path} ended {remaining} bytes early "
                    f"(chunk at offset {offset}, length {length})"
                )
            fdst.write(buf)
            remaining -= len(buf)
        return length


def file_sha256(path: str, chunk: int = CHUNK_SIZE) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while buf := f.read(chunk):
            h.update(buf)
    return h.hexdigest()


def transfer_data(
    src_dir: str,
    dst_dir: str,
    workers: int = DEFAULT_WORKERS,
    verify: bool = False,
    engine: str = "auto",
    direction: str = "upload",
    skip_unchanged: dict[str, tuple[int, int]] | None = None,
    journal: StageJournal | None = None,
    priority_event: threading.Event | None = None,
) -> TransferStats:
    """Copy the tree at ``src_dir`` into ``dst_dir`` (created if missing).

    Parity: reference ``TransferData`` copy.go:17-64, with chunk-parallel
    large files and optional end-to-end sha256 verification. Raises
    ``RuntimeError`` listing all failures if any file failed (the control
    plane surfaces this as a failed agent Job).

    ``skip_unchanged`` is a :func:`tree_state` capture taken right after an
    earlier transfer *in this same run*: files whose (size, mtime_ns) still
    match it were shipped then and are skipped now. The skip decision is
    purely source-side, so a retried agent Job (fresh process → empty
    capture for pass 1) always re-ships everything it produced — no stale
    destination file can survive a retry, unlike dest-existence checks.
    The pre-copy flow uses this so the blackout upload does not re-ship
    the multi-GB base uploaded while the workload was still running.

    ``journal`` switches on chunk-streamed staging: files ship in
    :func:`_stage_priority` order and every completed file (and every
    large-file waterline advance) is published through the journal so a
    concurrent restore pipeline can consume arrays mid-transfer.
    ``priority_event`` is set the moment every non-bulk-data file has
    landed (and always before this function returns) — the early-sentinel
    gate of :func:`grit_tpu.agent.restore.run_restore_streamed`.
    """

    if skip_unchanged or journal is not None:
        # The skip set / journal are per-run source-side protocol the
        # native tree mover doesn't consume; the python path still
        # chunk-parallelizes the large files that DO ship.
        engine = "python"
    if engine == "auto":
        try:
            from grit_tpu.native import datamover  # noqa: PLC0415

            if datamover.available():
                stats = datamover.transfer_data(
                    src_dir, dst_dir, workers=workers, verify=verify
                )
                _record_transfer(stats, direction)
                return stats
        except ImportError:
            pass

    if not os.path.isdir(src_dir):
        raise FileNotFoundError(f"source dir {src_dir} does not exist")
    os.makedirs(dst_dir, exist_ok=True)
    start = time.monotonic()
    stats = TransferStats()

    files = list(_iter_files(src_dir))
    if journal is not None:
        # Metadata before bulk data, deterministic within a class — the
        # consumption order of a streamed restore (see _stage_priority).
        files.sort(key=lambda pr: (_stage_priority(pr[1]), pr[1]))

    prio_lock = threading.Lock()
    prio_left = (
        {rel for _, rel in files if _stage_priority(rel) < _DATA_PRIORITY}
        if priority_event is not None else set()
    )

    def _file_done(rel: str) -> None:
        if priority_event is None:
            return
        with prio_lock:
            prio_left.discard(rel)
            if not prio_left:
                priority_event.set()

    # (src, dst, offset, length, rel, size); offset < 0 = whole small file.
    tasks: list[tuple[str, str, int, int, str, int]] = []
    chunk_left: dict[str, int] = {}  # big files: outstanding chunk count
    chunk_lock = threading.Lock()
    finalize: list[tuple[str, str]] = []  # (src, dst) mode/verify fixups
    for src_path, rel in files:
        dst_path = os.path.join(dst_dir, rel)
        st = os.stat(src_path)
        size = st.st_size
        if skip_unchanged and skip_unchanged.get(rel) == (size, st.st_mtime_ns):
            stats.skipped += 1
            if journal is not None:
                # Skipped == shipped by an earlier pass: its destination
                # bytes are valid, so consumers must not wait on it.
                journal.note_file(rel, size)
            _file_done(rel)
            continue
        if size >= PARALLEL_FILE_THRESHOLD:
            os.makedirs(os.path.dirname(dst_path), exist_ok=True)
            with open(dst_path, "wb") as f:
                f.truncate(size)  # preallocate so chunks can land in parallel
            off = 0
            n_chunks = 0
            while off < size:
                length = min(CHUNK_SIZE, size - off)
                tasks.append((src_path, dst_path, off, length, rel, size))
                off += length
                n_chunks += 1
            chunk_left[rel] = n_chunks
            finalize.append((src_path, dst_path))
        else:
            tasks.append((src_path, dst_path, -1, size, rel, size))
        stats.files += 1

    if priority_event is not None and not prio_left:
        priority_event.set()

    def run_task(task: tuple[str, str, int, int, str, int]) -> int:
        src_path, dst_path, offset, length, rel, size = task
        if offset < 0:
            n = _copy_small(src_path, dst_path)
            if journal is not None:
                journal.note_file(rel, n)
            _file_done(rel)
            return n
        n = _copy_chunk(src_path, dst_path, offset, length)
        if journal is not None:
            journal.note_chunk(rel, offset, length, size)
        with chunk_lock:
            chunk_left[rel] -= 1
            file_complete = chunk_left[rel] == 0
        if file_complete:
            _file_done(rel)
        return n

    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = [pool.submit(run_task, t) for t in tasks]
        for fut, task in zip(futures, tasks):
            try:
                stats.bytes += fut.result()
            except Exception as exc:  # noqa: BLE001 - collected, not racy
                stats.errors.append(f"{task[0]}: {exc}")

    for src_path, dst_path in finalize:
        try:
            shutil.copymode(src_path, dst_path)
            if verify and file_sha256(src_path) != file_sha256(dst_path):
                stats.errors.append(f"{dst_path}: checksum mismatch")
        except Exception as exc:  # noqa: BLE001
            stats.errors.append(f"{dst_path}: {exc}")

    stats.seconds = time.monotonic() - start
    if stats.errors:
        raise RuntimeError("transfer failed: " + "; ".join(stats.errors))
    _record_transfer(stats, direction)
    return stats


def _record_transfer(stats: TransferStats, direction: str) -> None:
    TRANSFER_BYTES.inc(stats.bytes, direction=direction)
    TRANSFER_SECONDS.inc(stats.seconds, direction=direction)


def create_sentinel_file(dir_path: str) -> str:
    """Drop ``download-state`` marking staged data complete (reference
    copy.go:92-102). fsync'd so the interceptor's poll can't observe a
    torn write ordering."""

    os.makedirs(dir_path, exist_ok=True)
    path = os.path.join(dir_path, DOWNLOAD_STATE_FILE)
    with open(path, "w") as f:
        f.write("ok")
        f.flush()
        os.fsync(f.fileno())
    return path
