"""Streaming data mover: parallel, chunked, checksummed file transfer.

Replaces the reference's naive per-file worker pool
(``pkg/gritagent/copy/copy.go:17-64``) per SURVEY §7.E: the PVC copy is the
blackout bottleneck (126–341 MB/s measured in the reference; §6), so this
mover parallelises *within* large files (chunk-ranged reads/writes into a
preallocated target) as well as across files, overlapping read and write I/O.
The reference's racy error-slice append (copy.go:19,48 — noted in SURVEY §2.1)
is fixed by collecting errors through the executor's future results.

A native C++ engine (``native/datamover``) provides the same interface for
the latency-critical path; :func:`transfer_data` picks it up automatically
when the shared library has been built (``engine="auto"``).
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import queue
import shutil
import socket
import struct
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass, field

from grit_tpu import faults
from grit_tpu import codec as transport_codec
from grit_tpu.api import config
from grit_tpu.native import wire as native_wire
from grit_tpu.obs.metrics import (
    CODEC_WAIT_SECONDS,
    TRANSFER_BYTES,
    TRANSFER_SECONDS,
    WIRE_BYTES,
    WIRE_FRAME_SEND_SECONDS,
    WIRE_NATIVE_BYTES,
    WIRE_SECONDS,
    WIRE_STALL_SECONDS,
)
from grit_tpu.metadata import (
    DOWNLOAD_STATE_FILE,
    FIRE_FILE,
    FLIGHT_LOG_FILE,
    PROF_FILE_PREFIX,
    PROGRESS_FILE,
    SLICE_LEDGER_DIRNAME,
    STAGE_JOURNAL_FILE,
    stage_timeout_s,
)
from grit_tpu.obs import flight, progress

log = logging.getLogger(__name__)

DEFAULT_WORKERS = 10  # reference copy.go:20 uses a 10-goroutine pool
CHUNK_SIZE = 16 * 1024 * 1024
# Files larger than this are split into parallel chunk copies.
PARALLEL_FILE_THRESHOLD = 64 * 1024 * 1024


def advance_waterline(pending: dict[int, int], water: int,
                      offset: int, length: int) -> int:
    """Record an out-of-order ``(offset, length)`` arrival and return the
    new contiguous-from-0 waterline. The single source of truth for both
    waterline trackers (StageJournal's published lines and WireReceiver's
    completion accounting): ``pending`` holds not-yet-contiguous pieces
    and is drained as the prefix closes."""
    pending[offset] = length
    while water in pending:
        water += pending.pop(water)
    return water


class StageJournal:
    """Writer side of the streamed-staging protocol.

    The journal lives at ``<dst_dir>/.grit-stage-journal`` and carries one
    flushed JSON line per event::

        {"file": rel, "staged": n}                contiguous-from-0 bytes ready
        {"file": rel, "staged": n, "done": true}  file fully staged
        {"complete": true} | {"failed": msg}      terminal line

    The device-side reader (``grit_tpu.device.snapshot._StageMonitor``)
    polls it so the restore pipeline can consume a chunk the moment its
    byte range has landed — while later chunks are still crossing from the
    PVC. Large files copied chunk-parallel report a *waterline* (the
    longest complete prefix), which matches consumption order: snapshot
    data files are read front-to-back in manifest order.
    """

    def __init__(self, dst_dir: str) -> None:
        os.makedirs(dst_dir, exist_ok=True)
        self.path = os.path.join(dst_dir, STAGE_JOURNAL_FILE)
        self._f = open(self.path, "w")
        self._lock = threading.Lock()
        self._water: dict[str, int] = {}
        self._pending: dict[str, dict[int, int]] = {}
        self._closed = False

    def _emit(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def note_file(self, rel: str, size: int) -> None:
        """One file fully staged (small copy, or skipped-as-unchanged —
        either way its bytes are valid at the destination)."""
        with self._lock:
            if not self._closed:
                self._emit({"file": rel, "staged": size, "done": True})

    def note_chunk(self, rel: str, offset: int, length: int,
                   size: int | None = None) -> None:
        """One chunk of a large file landed; advances (and publishes) the
        file's contiguous waterline. ``size=None`` means the total is not
        yet known (a wire stream fed straight from an in-flight dump):
        only waterline advances are published, and the producer marks the
        file done via :meth:`note_file` once its length is final."""
        with self._lock:
            if self._closed:
                return
            water = advance_waterline(
                self._pending.setdefault(rel, {}),
                self._water.get(rel, 0), offset, length)
            self._water[rel] = water
            if size is not None and water >= size:
                self._pending.pop(rel, None)
                self._emit({"file": rel, "staged": water, "done": True})
            elif water > 0:
                self._emit({"file": rel, "staged": water})

    def complete(self) -> None:
        with self._lock:
            if not self._closed:
                self._emit({"complete": True})
                self._closed = True
                self._f.close()

    def fail(self, msg: str) -> None:
        """Terminal failure marker: consumers blocked on a never-arriving
        chunk fail loudly instead of hanging out their timeout."""
        with self._lock:
            if not self._closed:
                self._emit({"failed": msg})
                self._closed = True
                self._f.close()


def _stage_priority(rel: str) -> int:
    """Staging order for streamed restores: snapshot metadata first (the
    restore side cannot even plan without MANIFEST/COMMIT), then the
    carried executable cache (needed before the first compile), then the
    remaining small metadata (CRIU image, config/spec dumps), and the bulk
    HBM data files last — they are exactly what the restore pipeline can
    consume incrementally."""
    base = os.path.basename(rel)
    if base.endswith(transport_codec.SIDECAR_SUFFIX):
        # Codec sidecars are the decode map of their container data file:
        # metadata class, and transfer_data additionally ships them in a
        # synchronous pre-pass so container detection is race-free.
        return 0
    if base in ("COMMIT", "MANIFEST.json") or base.startswith("index-h"):
        return 0
    parts = rel.replace("\\", "/").split("/")
    if "xla_cache" in parts or "compile-cache" in parts:
        return 1
    if not base.startswith("data-h"):
        return 2
    return 3


# Files below this staging priority gate the early sentinel drop: once they
# are all staged the restored pod may start (its restore pipeline waits on
# the rest through the journal).
_DATA_PRIORITY = 3


@dataclass
class TransferStats:
    files: int = 0
    bytes: int = 0
    seconds: float = 0.0
    skipped: int = 0  # files unchanged since an earlier pass (skip_unchanged)
    errors: list[str] = field(default_factory=list)

    @property
    def gbps(self) -> float:
        return self.bytes / self.seconds / 1e9 if self.seconds > 0 else 0.0


def tree_state(src_dir: str) -> dict[str, tuple[int, int]]:
    """``{relpath: (size, mtime_ns)}`` of every file under ``src_dir`` —
    the source-side identity a later :func:`transfer_data` pass can skip
    against (see ``skip_unchanged``)."""
    out = {}
    for path, rel in _iter_files(src_dir):
        st = os.stat(path)
        out[rel] = (st.st_size, st.st_mtime_ns)
    return out


def _iter_files(src: str):
    for root, _dirs, files in os.walk(src):
        if SLICE_LEDGER_DIRNAME in _dirs:
            # Gang slice-migration ledger: per-host prepared/commit/abort
            # markers appear WHILE transfers run, and shipping them would
            # replay a stale gang outcome into the next attempt's ledger.
            # Pruned as a whole directory.
            _dirs.remove(SLICE_LEDGER_DIRNAME)
        for name in files:
            if name == FLIGHT_LOG_FILE or name.startswith(PROGRESS_FILE) \
                    or name.startswith(PROF_FILE_PREFIX) \
                    or name == FIRE_FILE:
                # Flight log + progress snapshot + profiler artifacts are
                # node-local observability and change WHILE transfers
                # run: shipping them would tear wire commit size maps and
                # upload skip captures. Prefix match for the progress
                # file: its atomic-replace tmp twin
                # (`.grit-progress.json.tmp-<pid>`) appears and vanishes
                # on the lease cadence, and a walk that captured it would
                # stat a file os.replace just consumed. Prefix match for
                # the profiler output (`.grit-prof-<phase>.folded`): one
                # file per profiled phase, dropped mid-migration as each
                # bracket closes. Never walked.
                continue
            path = os.path.join(root, name)
            yield path, os.path.relpath(path, src)


def _drop_stale_sidecars(src_dir: str, dst_dir: str) -> None:
    """Remove destination codec sidecars that have no source counterpart:
    raw bytes just landed over what a previous attempt staged as a
    container (codec flipped off between attempts, failed mirror). The
    python engine handles this per file as it copies; the native mover
    never deletes destination files, so it needs this sweep — a stale
    terminated sidecar next to raw bytes makes the snapshot unrestorable."""
    if not os.path.isdir(dst_dir):
        return
    for path, rel in _iter_files(dst_dir):
        if not rel.endswith(transport_codec.SIDECAR_SUFFIX):
            continue
        if not os.path.isfile(os.path.join(src_dir, rel)):
            try:
                os.unlink(path)
            except OSError:
                pass


def _copy_small(src_path: str, dst_path: str) -> int:
    os.makedirs(os.path.dirname(dst_path), exist_ok=True)
    shutil.copyfile(src_path, dst_path)
    shutil.copymode(src_path, dst_path)
    return os.path.getsize(dst_path)


def _copy_chunk(src_path: str, dst_path: str, offset: int, length: int) -> int:
    with open(src_path, "rb") as fsrc, open(dst_path, "r+b") as fdst:
        fsrc.seek(offset)
        fdst.seek(offset)
        remaining = length
        while remaining > 0:
            buf = fsrc.read(min(CHUNK_SIZE, remaining))
            if not buf:
                # Source shrank since it was sized: a silent short copy would
                # leave zero-filled holes in the preallocated destination.
                raise IOError(
                    f"short read: {src_path} ended {remaining} bytes early "
                    f"(chunk at offset {offset}, length {length})"
                )
            # Chaos seam: a truncate spec here models a torn write (power
            # loss, full disk) — the journal/commit integrity machinery
            # must catch the short file, never accept it.
            written = faults.fault_write("agent.copy.chunk_write", buf)
            fdst.write(written)
            if len(written) < len(buf):
                raise IOError(
                    f"short write: {dst_path} accepted {len(written)}/"
                    f"{len(buf)} bytes at offset {offset}")
            remaining -= len(buf)
        return length


def file_sha256(path: str, chunk: int = CHUNK_SIZE) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while buf := f.read(chunk):
            h.update(buf)
    return h.hexdigest()


def transfer_data(
    src_dir: str,
    dst_dir: str,
    workers: int = DEFAULT_WORKERS,
    verify: bool = False,
    engine: str = "auto",
    direction: str = "upload",
    skip_unchanged: dict[str, tuple[int, int]] | None = None,
    journal: StageJournal | None = None,
    priority_event: threading.Event | None = None,
    dest_valid: dict[str, int] | None = None,
    count_progress: bool = True,
) -> TransferStats:
    """Copy the tree at ``src_dir`` into ``dst_dir`` (created if missing).

    Parity: reference ``TransferData`` copy.go:17-64, with chunk-parallel
    large files and optional end-to-end sha256 verification. Raises
    ``RuntimeError`` listing all failures if any file failed (the control
    plane surfaces this as a failed agent Job).

    ``skip_unchanged`` is a :func:`tree_state` capture taken right after an
    earlier transfer *in this same run*: files whose (size, mtime_ns) still
    match it were shipped then and are skipped now. The skip decision is
    purely source-side, so a retried agent Job (fresh process → empty
    capture for pass 1) always re-ships everything it produced — no stale
    destination file can survive a retry, unlike dest-existence checks.
    The pre-copy flow uses this so the blackout upload does not re-ship
    the multi-GB base uploaded while the workload was still running.

    ``journal`` switches on chunk-streamed staging: files ship in
    :func:`_stage_priority` order and every completed file (and every
    large-file waterline advance) is published through the journal so a
    concurrent restore pipeline can consume arrays mid-transfer.
    ``priority_event`` is set the moment every non-bulk-data file has
    landed (and always before this function returns) — the early-sentinel
    gate of :func:`grit_tpu.agent.restore.run_restore_streamed`.

    ``dest_valid`` maps rels whose DESTINATION copy is already complete
    and content-verified (a partial wire leg's fully-received files —
    every frame CRC-of-raw checked): they are skipped when the source's
    raw size (codec-container aware) matches, so a late wire→PVC
    fallback never re-ships bytes the journal already holds verified.
    The verification is receiver-side, so this is retry-safe in the
    direction that matters: an unverified or partial file is never in
    the map and always re-ships.

    ``count_progress`` feeds every landed byte into the live progress
    tracker of this transfer's role (upload → source, download →
    destination) as chunks complete — the PVC durability tee passes
    False so its off-blackout re-read never double-counts bytes the
    wire already shipped.
    """

    faults.fault_point("agent.copy.transfer")
    # Live progress role: bytes count as they land, not as a lump at
    # return — the watchdog's stall detection and `gritscope watch`
    # both read mid-transfer truth.
    track_role = (progress.ROLE_SOURCE if direction == "upload"
                  else progress.ROLE_DESTINATION) if count_progress else None
    if skip_unchanged or dest_valid or journal is not None:
        # The skip set / journal are per-run source-side protocol the
        # native tree mover doesn't consume; the python path still
        # chunk-parallelizes the large files that DO ship.
        engine = "python"
    if engine == "auto":
        try:
            from grit_tpu.native import datamover  # noqa: PLC0415

            if datamover.available():
                stats = datamover.transfer_data(
                    src_dir, dst_dir, workers=workers, verify=verify
                )
                _drop_stale_sidecars(src_dir, dst_dir)
                if track_role is not None:
                    # The native mover has no per-chunk callback; the
                    # lump at completion keeps the telemetry plane lit
                    # (not dark at 0%) on the default production path.
                    progress.add_bytes(track_role, stats.bytes)
                _record_transfer(stats, direction)
                return stats
        except ImportError:
            pass

    if not os.path.isdir(src_dir):
        raise FileNotFoundError(f"source dir {src_dir} does not exist")
    os.makedirs(dst_dir, exist_ok=True)
    start = time.monotonic()
    stats = TransferStats()

    all_files = list(_iter_files(src_dir))

    # Destination-verified skips (wire-fallback): accept only when the
    # source's RAW identity matches what the receiver verified — for a
    # codec container that is the sidecar's decoded size, not the file's.
    dest_ok: set[str] = set()
    if dest_valid:
        for rel, raw_size in dest_valid.items():
            try:
                src_raw = transport_codec.container_raw_size(
                    os.path.join(src_dir, rel))
                if src_raw is None:
                    src_raw = os.path.getsize(os.path.join(src_dir, rel))
                if src_raw == raw_size and os.path.getsize(
                        os.path.join(dst_dir, rel)) == raw_size:
                    dest_ok.add(rel)
            except (OSError, transport_codec.CodecError):
                continue

    sidecars = [pr for pr in all_files
                if pr[1].endswith(transport_codec.SIDECAR_SUFFIX)]
    files = [pr for pr in all_files
             if not pr[1].endswith(transport_codec.SIDECAR_SUFFIX)]
    if journal is not None:
        # Metadata before bulk data, deterministic within a class — the
        # consumption order of a streamed restore (see _stage_priority).
        files.sort(key=lambda pr: (_stage_priority(pr[1]), pr[1]))

    prio_lock = threading.Lock()
    prio_left = (
        {rel for _, rel in all_files
         if _stage_priority(rel) < _DATA_PRIORITY}
        if priority_event is not None else set()
    )

    def _file_done(rel: str) -> None:
        if priority_event is None:
            return
        with prio_lock:
            prio_left.discard(rel)
            if not prio_left:
                priority_event.set()

    # Codec sidecars ship FIRST, synchronously, before any pooled task:
    # a .gritc next to a data file is what marks it as a compressed
    # container, so every reader that can observe any other staged file
    # must already observe the sidecar — container detection stays
    # race-free even mid-stream. They are a few KB; the cost is noise.
    for src_path, rel in sorted(sidecars, key=lambda pr: pr[1]):
        base_rel = rel[:-len(transport_codec.SIDECAR_SUFFIX)]
        st = os.stat(src_path)
        if base_rel in dest_ok:
            # The base file at the destination is verified RAW bytes
            # (wire-received): copying its source sidecar over would
            # relabel those raw bytes as a container. Drop it.
            stats.skipped += 1
            _file_done(rel)
            continue
        if skip_unchanged and \
                skip_unchanged.get(rel) == (st.st_size, st.st_mtime_ns):
            stats.skipped += 1
            if journal is not None:
                journal.note_file(rel, st.st_size)
            _file_done(rel)
            continue
        n = _copy_small(src_path, os.path.join(dst_dir, rel))
        stats.files += 1
        stats.bytes += n
        if track_role is not None:
            progress.add_bytes(track_role, n)
        if journal is not None:
            journal.note_file(rel, n)
        _file_done(rel)

    # (src, dst, offset, length, rel, size); offset < 0 = whole small file.
    tasks: list[tuple[str, str, int, int, str, int]] = []
    chunk_left: dict[str, int] = {}  # big files: outstanding chunk count
    chunk_lock = threading.Lock()
    finalize: list[tuple[str, str]] = []  # (src, dst) mode/verify fixups
    for src_path, rel in files:
        dst_path = os.path.join(dst_dir, rel)
        st = os.stat(src_path)
        size = st.st_size
        if skip_unchanged and skip_unchanged.get(rel) == (size, st.st_mtime_ns):
            stats.skipped += 1
            if journal is not None:
                # Skipped == shipped by an earlier pass: its destination
                # bytes are valid, so consumers must not wait on it.
                journal.note_file(rel, size)
            _file_done(rel)
            continue
        if rel in dest_ok:
            # dest_ok == verified RAW bytes at dst (wire-received): a
            # stale sidecar from an earlier container prestage would
            # relabel them compressed — drop it alongside the skip.
            try:
                os.unlink(dst_path + transport_codec.SIDECAR_SUFFIX)
            except OSError:
                pass
            stats.skipped += 1
            if journal is not None:
                journal.note_file(rel, dest_valid[rel])
            _file_done(rel)
            continue
        if not os.path.isfile(src_path + transport_codec.SIDECAR_SUFFIX):
            # Raw source file: whatever lands at dst is raw bytes, so a
            # sidecar surviving from a previous container-staged attempt
            # (codec flipped off between attempts, or a wire leg that
            # overwrote a prestaged container) must not outlive them.
            try:
                os.unlink(dst_path + transport_codec.SIDECAR_SUFFIX)
            except OSError:
                pass
        if size >= PARALLEL_FILE_THRESHOLD:
            os.makedirs(os.path.dirname(dst_path), exist_ok=True)
            with open(dst_path, "wb") as f:
                f.truncate(size)  # preallocate so chunks can land in parallel
            off = 0
            n_chunks = 0
            while off < size:
                length = min(CHUNK_SIZE, size - off)
                tasks.append((src_path, dst_path, off, length, rel, size))
                off += length
                n_chunks += 1
            chunk_left[rel] = n_chunks
            finalize.append((src_path, dst_path))
        else:
            tasks.append((src_path, dst_path, -1, size, rel, size))
        stats.files += 1

    if priority_event is not None and not prio_left:
        priority_event.set()

    def run_task(task: tuple[str, str, int, int, str, int]) -> int:
        src_path, dst_path, offset, length, rel, size = task
        if offset < 0:
            n = _copy_small(src_path, dst_path)
            if track_role is not None:
                progress.add_bytes(track_role, n)
            if journal is not None:
                journal.note_file(rel, n)
            _file_done(rel)
            return n
        n = _copy_chunk(src_path, dst_path, offset, length)
        if track_role is not None:
            progress.add_bytes(track_role, n)
        if journal is not None:
            journal.note_chunk(rel, offset, length, size)
        with chunk_lock:
            chunk_left[rel] -= 1
            file_complete = chunk_left[rel] == 0
        if file_complete:
            _file_done(rel)
        return n

    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = [pool.submit(run_task, t) for t in tasks]
        for fut, task in zip(futures, tasks):
            try:
                stats.bytes += fut.result()
            except Exception as exc:  # noqa: BLE001 - collected, not racy
                stats.errors.append(f"{task[0]}: {exc}")

    for src_path, dst_path in finalize:
        try:
            shutil.copymode(src_path, dst_path)
            if verify and file_sha256(src_path) != file_sha256(dst_path):
                stats.errors.append(f"{dst_path}: checksum mismatch")
        except Exception as exc:  # noqa: BLE001
            stats.errors.append(f"{dst_path}: {exc}")

    stats.seconds = time.monotonic() - start
    if stats.errors:
        raise RuntimeError("transfer failed: " + "; ".join(stats.errors))
    _record_transfer(stats, direction)
    return stats


def _record_transfer(stats: TransferStats, direction: str) -> None:
    TRANSFER_BYTES.inc(stats.bytes, direction=direction)
    TRANSFER_SECONDS.inc(stats.seconds, direction=direction)


# -- wire transport: direct source→destination migration stream ---------------
#
# GRIT_MIGRATION_PATH=wire replaces the PVC double-hop (source uploads,
# destination downloads — both legs on the blackout path, 126–341 MB/s in
# the reference, SURVEY §6/§7.E) with a single hop: the source agent ships
# length-prefixed, CRC-checked frames straight into the destination's stage
# directory, and the destination's WireReceiver writes them through the
# PR-1 StageJournal so the restore pipeline can consume them the moment
# they land. The producer of the bulk frames is the HBM dump itself
# (snapshot._MirrorWriter hands serialized buffers to a WireDumpSink as
# they drain), so dump → send → land overlap end-to-end. The PVC upload
# is retained as an asynchronous durability tee, off the blackout path.
#
# Frame format (all integers big-endian):
#
#     u32 header_len | header JSON | payload (header["n"] bytes)
#
# Header kinds:
#     {"t":"file",  "rel", "n", "crc"}                 whole small file
#     {"t":"chunk", "rel", "off", "n", "crc"[, "size"]} piece of a large
#         file ("size" present when the total is known up front; absent
#         for dump-fed streams, which terminate with an eof frame)
#     {"t":"eof",   "rel", "total"}                    stream-fed file done
#     {"t":"commit","files": {rel: size}}              session complete —
#         the receiver verifies every listed file fully landed, then acks
#     {"t":"fail",  "msg"}                             source died; abort
#
# ``crc`` is zlib.crc32 over the payload, checked on receive — a torn or
# corrupted frame fails the whole session (never partial acceptance); the
# snapshot's own per-chunk CRCs still verify end-to-end at restore time.
# Multi-stream: the sender round-robins frames over several connections
# (large files split at WIRE_FRAME_BYTES); frames are self-describing
# (rel + offset) so arrival order does not matter. The ack for a commit
# is one JSON line on the committing connection.

WIRE_FRAME_BYTES = 4 * 1024 * 1024
_WIRE_QUEUE_FRAMES = 4  # per-stream send buffer: bounds source memory at
# streams × _WIRE_QUEUE_FRAMES × WIRE_FRAME_BYTES even against a stalled
# consumer (backpressure blocks the producer instead of growing a buffer)

# Native-plane file segments are larger: per segment the sender's
# Python thread runs once (fault check, header build, pace record), so
# bigger segments directly lower the wire_send python-share the plane
# exists to cut (measured 0.63 at 32 MiB vs 0.93 at 4 MiB on the bench
# share pair). Safe against a Python-plane peer because the receiver's
# decode admission is BYTE-bounded, not frame-counted — a mixed-plane
# session holds the same in-flight payload bytes whatever the frame
# size (per-connection recv buffers add streams × segment, bounded by
# the stream count).
WIRE_NATIVE_SEGMENT_BYTES = 32 * 1024 * 1024
# Ring slots must hold the largest staged payload: a codec block that
# refused to compress ships raw at WIRE_FRAME_BYTES, plus codec framing
# headroom.
_WIRE_NATIVE_SLOT_BYTES = WIRE_FRAME_BYTES + (1 << 20)


class _FileSegment:
    """A (path, offset, length) payload in the Python-plane send queue:
    the worker ships it with ``socket.sendfile`` instead of a bytes
    object riding the queue — the fallback plane's raw file frames skip
    the read-into-Python round-trip for the payload (the CRC pass still
    reads the bytes; that is the remaining gap the native plane closes).
    """

    __slots__ = ("path", "off", "n")

    def __init__(self, path: str, off: int, n: int) -> None:
        self.path = path
        self.off = off
        self.n = n


def _file_crc32_py(path: str, off: int, n: int) -> int:
    """zlib CRC32 of a file range, read in bounded chunks (pure-Python
    plane; the native plane computes this without surfacing the bytes)."""
    crc = 0
    with open(path, "rb") as f:
        f.seek(off)
        remaining = n
        while remaining > 0:
            buf = f.read(min(1 << 20, remaining))
            if not buf:
                raise WireError(
                    f"{path} shrank mid-crc ({n - remaining}/{n} bytes "
                    f"at offset {off})")
            crc = zlib.crc32(buf, crc)
            remaining -= len(buf)
    return crc & 0xFFFFFFFF


def _wire_ifaces() -> list[str]:
    """GRIT_WIRE_IFACES as a list (multi-NIC striping; empty = none)."""
    return [i.strip() for i in str(config.WIRE_IFACES.get()).split(",")
            if i.strip()]


def _dial_stream(host: str, port: int, timeout: float,
                 iface: str | None) -> socket.socket:
    """One wire stream connection, optionally pinned to a NIC. The pin
    must land before connect; a refused pin (SO_BINDTODEVICE needs
    CAP_NET_RAW) logs loudly and dials unpinned — a striping misconfig
    must degrade to yesterday's single-NIC behavior, not kill the
    migration. Like ``socket.create_connection`` (which this replaces
    so the pin can land pre-connect), every getaddrinfo result is
    tried in order: a hostname endpoint whose first A record is
    unreachable (node draining, per-AZ DNS ordering) must dial the
    next, not degrade the whole migration to the PVC double-hop."""
    last_exc: OSError | None = None
    for af, kind, proto, _cn, addr in socket.getaddrinfo(
            host, port, type=socket.SOCK_STREAM):
        s = socket.socket(af, kind, proto)
        if iface:
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_BINDTODEVICE,
                             iface.encode() + b"\0")
            except OSError as exc:
                log.warning(
                    "wire stream: SO_BINDTODEVICE(%s) refused (%s) — "
                    "dialing unpinned", iface, exc)
        s.settimeout(timeout)
        try:
            s.connect(addr)
            return s
        except OSError as exc:
            s.close()
            last_exc = exc
    if last_exc is not None:
        raise last_exc
    raise OSError(f"getaddrinfo returned no addresses for {host!r}")


class WireError(RuntimeError):
    """The wire transport failed — callers fall back to the PVC path."""


def _wire_frame(header: dict, payload: bytes = b"") -> bytes:
    raw = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack(">I", len(raw)) + raw + payload


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(min(1 << 20, n - len(out)))
        if not chunk:
            raise ConnectionError(
                f"wire peer closed mid-frame ({len(out)}/{n} bytes)")
        out += chunk
    return bytes(out)


def _check_rel(rel: str) -> str:
    rel = os.path.normpath(rel)
    if os.path.isabs(rel) or rel.startswith(".."):
        raise WireError(f"wire frame names unsafe path {rel!r}")
    return rel


def _node_address() -> str:
    """This node's primary (peer-reachable) IPv4 address. The UDP-connect
    trick resolves the default route's source address without sending a
    packet; loopback only when the host has no route at all."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 9))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class WireSender:
    """Source half of the wire: frames queued onto ``streams`` parallel
    connections, each drained by a worker thread through a bounded queue.

    A full queue blocks the producer (``stall_s`` accumulates) — the
    slow-consumer contract: source-side buffering is bounded, never
    unbounded growth. Any stream error poisons the whole sender (the
    session is all-or-nothing; the caller falls back to the PVC path).
    """

    def __init__(self, endpoint: str, streams: int = 2,
                 timeout: float = 120.0) -> None:
        host, _, port = endpoint.rpartition(":")
        self.endpoint = endpoint
        self._timeout = timeout
        # Codec stage: send_file/send_bytes compress payloads (adaptive,
        # per frame) through the shared bounded worker pool before they
        # hit the send queues; the dump's own chunks arrive already
        # compressed via WireDumpSink.put_record. "none" keeps the wire
        # byte-identical to the pre-codec protocol.
        self.codec = transport_codec.resolve_codec()
        self._pool = (transport_codec.shared_pool()
                      if self.codec != transport_codec.CODEC_NONE else None)
        self._socks: list[socket.socket] = []
        self._queues: list[queue.Queue] = []
        self._threads: list[threading.Thread] = []
        self._dead: str | None = None
        self._rr = 0
        self._lock = threading.Lock()
        self._closed = False
        self._py_sent_bytes = 0
        self._py_send_s = 0.0
        self._py_stall_s = 0.0
        self.ack_s = 0.0
        self.codec_wait_s = 0.0  # producer blocked on pool results
        ifaces = _wire_ifaces()
        try:
            for k in range(max(1, streams)):
                # Multi-NIC striping: stream k pins to iface k mod N —
                # one socket per stream is already the model, so the
                # stripe is just where each socket's route begins.
                s = _dial_stream(host, int(port), timeout,
                                 ifaces[k % len(ifaces)] if ifaces
                                 else None)
                self._socks.append(s)
        except (OSError, ValueError) as exc:  # ValueError: junk endpoint
            for s in self._socks:
                s.close()
            raise WireError(f"wire connect to {endpoint} failed: {exc}")
        flight.emit("wire.open", endpoint=endpoint,
                    streams=len(self._socks))
        # Native plane: one C ring-buffer send worker per stream. Frame
        # HEADERS are still built here (Python stays the control plane);
        # payloads are staged/sendfile'd natively so they never surface
        # as interpreter objects. enabled() logs the loud degrade when
        # GRIT_WIRE_NATIVE is on but the library is absent.
        self._native: list[native_wire.SendWorker] | None = None
        if native_wire.enabled():
            workers: list[native_wire.SendWorker] = []
            try:
                for s in self._socks:
                    workers.append(native_wire.SendWorker(
                        s, _WIRE_NATIVE_SLOT_BYTES, timeout=timeout))
                self._native = workers
            except OSError as exc:
                for w in workers:
                    w.destroy()
                # Any worker that DID start flipped its socket to
                # blocking mode for the native send loop; the Python
                # plane's workers rely on the send timeout to surface a
                # wedged receiver as OSError → WireError → PVC fallback,
                # so a partial native startup must hand the sockets
                # back timed.
                for s in self._socks:
                    s.settimeout(timeout)
                log.warning(
                    "native wire send plane failed to start (%s) — "
                    "using the Python frame loop", exc)
        if self._native is not None:
            # Progress pacing: frames are enqueued by the producers but
            # SENT by the C workers, and live telemetry (per-stream
            # channel windows, rate agreement vs the receiver) must be
            # timed by the send, not the enqueue — a 4-slot ring of
            # frame-sized slots would otherwise front-run the wire by
            # tens of MB. Each enqueue records (cumulative wire bytes
            # when this frame will have drained, raw bytes to credit);
            # the pacer releases credits as the worker's sent counter
            # passes each watermark.
            self._pace_lock = threading.Lock()
            self._pace: list[collections.deque] = [
                collections.deque() for _ in self._native]
            self._enq_wire = [0] * len(self._native)
            self._pace_stop = threading.Event()
            self._pace_thread = threading.Thread(
                target=self._pace_loop, name="grit-wire-pace",
                daemon=True)
            self._pace_thread.start()
        if self._native is None:
            for k, _s in enumerate(self._socks):
                q: queue.Queue = queue.Queue(maxsize=_WIRE_QUEUE_FRAMES)
                t = threading.Thread(target=self._worker, args=(k, q),
                                     name=f"grit-wire-send-{k}",
                                     daemon=True)
                self._queues.append(q)
                self._threads.append(t)
                t.start()

    def _worker(self, k: int, q: queue.Queue) -> None:
        sock = self._socks[k]
        idle = 0.0
        while True:
            try:
                # Bounded get: a producer that died without the None
                # sentinel (agent SIGKILL mid-dump) must not leave this
                # thread parked forever — log loudly and keep polling
                # (daemon thread; close() still delivers the sentinel).
                frame = q.get(timeout=1.0)
            except queue.Empty:
                idle += 1.0
                if idle % 60.0 == 0.0:
                    log.warning(
                        "wire send stream %d idle for %.0fs with no "
                        "frames and no shutdown sentinel", k, idle)
                continue
            idle = 0.0
            try:
                if frame is None:
                    return
                if self._dead is not None:
                    continue  # drain so producers never block on a dead wire
                header, payload, raw_n = frame
                t0 = time.monotonic()
                # Header and payload as two sends: the payload goes out as
                # whatever buffer the producer handed over (a memoryview
                # straight onto the dump's chunk for the hot path) — no
                # header+payload concatenation copy per frame.
                sock.sendall(header)
                if isinstance(payload, _FileSegment):
                    # Raw file frame: socket.sendfile ships the range
                    # from the page cache (kernel-side; handles the
                    # socket's timeout/non-blocking mode) instead of a
                    # bytes object that rode the queue.
                    with open(payload.path, "rb") as f:
                        sent = sock.sendfile(f, offset=payload.off,
                                             count=payload.n)
                    if sent != payload.n:
                        raise OSError(
                            f"sendfile short: {sent}/{payload.n} bytes "
                            f"of {payload.path}")
                    payload_len = payload.n
                # len(), not truthiness: payloads may be numpy views
                # (zero-copy dump chunks), whose bool() is ambiguous.
                elif len(payload):
                    sock.sendall(payload)
                    payload_len = len(payload)
                else:
                    payload_len = 0
                frame_s = time.monotonic() - t0
                with self._lock:
                    self._py_send_s += frame_s
                    self._py_sent_bytes += len(header) + payload_len
                WIRE_FRAME_SEND_SECONDS.observe(frame_s)
                # Live telemetry: RAW bytes count toward the source
                # leg's progress (per stream — the per-stream throughput
                # the N×N multi-host item will budget by). Raw, not
                # payload: totalBytes comes from raw tree sizes and the
                # destination counts decoded raw bytes, so a codec-on
                # session must not read as forever ~13% complete.
                progress.add_bytes(progress.ROLE_SOURCE, raw_n,
                                   stream=f"wire-{k}")
            except OSError as exc:
                self._dead = self._dead or f"{type(exc).__name__}: {exc}"
            finally:
                q.task_done()

    def _pace_record(self, k: int, wire_len: int, raw_n: int) -> None:
        with self._pace_lock:
            self._enq_wire[k] += wire_len
            if raw_n:
                self._pace[k].append((self._enq_wire[k], raw_n))
        # Opportunistic release on the enqueue cadence: the 20 ms pacer
        # tick alone quantizes a fast (loopback-scale) transfer into one
        # lump at the end, and a GIL-starved pacer thread can slip past
        # the whole live window — the telemetry plane would read 0%
        # until commit. A sent_bytes() read per stream is microseconds
        # against the MB-scale copy that precedes every enqueue.
        self._drain_pace()

    def _drain_pace(self) -> None:
        assert self._native is not None
        for k, w in enumerate(self._native):
            sent = w.sent_bytes()
            credited = 0
            with self._pace_lock:
                q = self._pace[k]
                while q and q[0][0] <= sent:
                    credited += q.popleft()[1]
            if credited:
                progress.add_bytes(progress.ROLE_SOURCE, credited,
                                   stream=f"wire-{k}")

    def _pace_loop(self) -> None:
        while not self._pace_stop.wait(0.02):
            self._drain_pace()
        self._drain_pace()  # final sweep: credit what reached the wire

    # Live stats fold the native workers' counters in as they run (the
    # backpressure/overlap probes read these mid-session); close()
    # freezes them into the _py_* accumulators before destroying the
    # workers.

    @property
    def sent_bytes(self) -> int:
        return self._py_sent_bytes + sum(
            w.sent_bytes() for w in self._native or ())

    @property
    def send_s(self) -> float:
        return self._py_send_s + sum(
            w.send_seconds() for w in self._native or ())

    @property
    def stall_s(self) -> float:
        return self._py_stall_s + sum(
            w.stall_seconds() for w in self._native or ())

    def _pick_native(self) -> tuple[int, "native_wire.SendWorker"]:
        assert self._native is not None
        with self._lock:
            k = self._rr % len(self._native)
            self._rr += 1
        return k, self._native[k]

    def _native_failed(self, exc: OSError) -> WireError:
        self._dead = self._dead or f"{type(exc).__name__}: {exc}"
        return WireError(f"wire send failed: {self._dead}")

    def _enqueue(self, header: dict, payload=b"",
                 raw_n: int | None = None) -> None:
        faults.fault_point("wire.send", wrap=WireError)
        if self._dead is not None:
            raise WireError(f"wire send failed: {self._dead}")
        raw = json.dumps(header, separators=(",", ":")).encode()
        n_raw = raw_n if raw_n is not None else len(payload)
        if self._native is not None:
            # Native plane: the worker's ring is the bounded queue and
            # the C thread is the consumer — a full ring blocks right
            # here (the same backpressure contract; stall seconds are
            # accounted natively and folded in at close).
            k, w = self._pick_native()
            hdr = struct.pack(">I", len(raw)) + raw
            try:
                w.send(hdr, payload)
            except OSError as exc:
                raise self._native_failed(exc)
            WIRE_NATIVE_BYTES.inc(len(payload), path="send_ring")
            self._pace_record(k, len(hdr) + len(payload), n_raw)
            return
        # raw_n: the frame's RAW (pre-codec) byte count for the progress
        # accounting; defaults to the payload length (uncompressed
        # frames), 0 for control frames with no payload.
        frame = (struct.pack(">I", len(raw)) + raw, payload, n_raw)
        with self._lock:
            q = self._queues[self._rr % len(self._queues)]
            self._rr += 1
        t0 = time.monotonic()
        episode = 0.0  # this enqueue's total backpressure block
        while True:
            try:
                q.put(frame, timeout=0.5)
                break
            except queue.Full:
                # Accrue stall incrementally: a producer blocked RIGHT NOW
                # on a slow consumer should already show up in the
                # wire_stream span's stall leg, not only in hindsight.
                now = time.monotonic()
                with self._lock:
                    self._py_stall_s += now - t0
                episode += now - t0
                t0 = now
                if self._dead is not None:
                    raise WireError(f"wire send failed: {self._dead}")
        tail = time.monotonic() - t0
        with self._lock:
            self._py_stall_s += tail
        episode += tail
        if episode > 0.005:
            # Distribution of stall EPISODES (not their sum): many short
            # blocks are healthy pacing, a few long ones are a wedged
            # consumer — the shape is the diagnosis.
            WIRE_STALL_SECONDS.observe(episode)

    # -- payload producers ------------------------------------------------------

    def send_bytes(self, rel: str, data) -> None:
        if self._pool is not None and len(data):
            try:
                used, payload, raw_n, crc_raw = \
                    transport_codec.compress_block(data, self.codec)
            except transport_codec.CodecError as exc:
                # Codec failures travel the wire-failure path: the whole
                # session poisons and the caller falls back to the PVC.
                raise WireError(f"wire codec failed: {exc}") from exc
            header = {"t": "file", "rel": rel, "n": len(payload),
                      "crc": crc_raw}
            if used != transport_codec.CODEC_NONE:
                header["c"] = used
                header["rn"] = raw_n
            self._enqueue(header, payload, raw_n=raw_n)
            return
        self._enqueue(
            {"t": "file", "rel": rel, "n": len(data),
             "crc": zlib.crc32(data) & 0xFFFFFFFF}, data)

    def send_chunk(self, rel: str, offset: int, data,
                   size: int | None = None) -> None:
        if self._native is not None:
            # Fused path: stage() memcpys the payload into the ring slot
            # with the frame CRC computed DURING the copy (one pass
            # through cache), hands the CRC back, and the header built
            # from it is attached by commit(). The payload never exists
            # as an interpreter object past this call.
            faults.fault_point("wire.send", wrap=WireError)
            if self._dead is not None:
                raise WireError(f"wire send failed: {self._dead}")
            k, w = self._pick_native()
            try:
                slot, crc = w.stage(data)
                header = {"t": "chunk", "rel": rel, "off": offset,
                          "n": len(data), "crc": crc}
                if size is not None:
                    header["size"] = size
                raw = json.dumps(header, separators=(",", ":")).encode()
                w.commit(slot, struct.pack(">I", len(raw)) + raw)
            except OSError as exc:
                raise self._native_failed(exc)
            WIRE_NATIVE_BYTES.inc(len(data), path="send_ring")
            self._pace_record(k, len(raw) + 4 + len(data), len(data))
            return
        header = {"t": "chunk", "rel": rel, "off": offset, "n": len(data),
                  "crc": zlib.crc32(data) & 0xFFFFFFFF}
        if size is not None:
            header["size"] = size
        self._enqueue(header, data)

    def send_record(self, rel: str, raw_off: int, payload, codec_name: str,
                    raw_n: int, crc_raw: int,
                    size: int | None = None) -> None:
        """One post-codec block as a chunk frame. ``off``/``size`` are RAW
        coordinates (the receiver's waterline and commit accounting stay
        in raw bytes); ``n`` is the payload actually on the wire, ``crc``
        is the CRC of the RAW bytes, checked after decode."""
        header = {"t": "chunk", "rel": rel, "off": raw_off,
                  "n": len(payload), "crc": crc_raw}
        if codec_name != transport_codec.CODEC_NONE:
            header["c"] = codec_name
            header["rn"] = raw_n
        if size is not None:
            header["size"] = size
        self._enqueue(header, payload, raw_n=raw_n)

    def eof(self, rel: str, total: int) -> None:
        """Terminate a dump-fed (size-unknown) chunk stream."""
        self._enqueue({"t": "eof", "rel": rel, "total": total})

    def _send_file_native(self, rel: str, path: str, size: int) -> int:
        """Raw (codec-off) file shipping on the native plane: per
        segment, the CRC comes from a native pread loop (warming the
        page cache) and the payload rides sendfile(2) out of that cache
        — file bytes never surface in Python; this thread only builds
        one small JSON header per segment."""
        seg_bytes = WIRE_NATIVE_SEGMENT_BYTES
        off = 0
        while off < size or (size == 0 and off == 0):
            n = min(seg_bytes, size - off)
            faults.fault_point("wire.send", wrap=WireError)
            if self._dead is not None:
                raise WireError(f"wire send failed: {self._dead}")
            k, w = self._pick_native()
            try:
                crc = native_wire.file_crc32(path, off, n) if n else 0
                if off == 0 and size <= seg_bytes:
                    header = {"t": "file", "rel": rel, "n": n,
                              "crc": crc}
                else:
                    header = {"t": "chunk", "rel": rel, "off": off,
                              "n": n, "crc": crc, "size": size}
                raw = json.dumps(header, separators=(",", ":")).encode()
                w.send_file(struct.pack(">I", len(raw)) + raw, path,
                            off, n)
            except OSError as exc:
                raise self._native_failed(exc)
            WIRE_NATIVE_BYTES.inc(n, path="send_file")
            self._pace_record(k, len(raw) + 4 + n, n)
            off += n
            if size == 0:
                break
        return size

    def send_file(self, rel: str, path: str) -> int:
        size = os.path.getsize(path)
        if self._native is not None and self._pool is None:
            # Raw file frames never touch Python on the native plane;
            # codec-on files keep the pool path below (compression IS
            # the Python control plane's call), whose compressed
            # payloads still ride the native ring via send_record.
            return self._send_file_native(rel, path, size)
        if size <= WIRE_FRAME_BYTES:
            with open(path, "rb") as f:
                self.send_bytes(rel, f.read())
            return size
        if self._pool is None:
            # Pure-Python plane, codec off: CRC by bounded reads, then
            # the payload ships as a _FileSegment the stream worker
            # sendfile()s — the queue carries (path, off, n), not bytes.
            off = 0
            while off < size:
                n = min(WIRE_FRAME_BYTES, size - off)
                crc = _file_crc32_py(path, off, n)
                self._enqueue(
                    {"t": "chunk", "rel": rel, "off": off, "n": n,
                     "crc": crc, "size": size},
                    _FileSegment(path, off, n), raw_n=n)
                off += n
            return size
        # Large file: frame-sized pieces through the codec pool with a
        # bounded in-order window — compression of frame k+1..k+W overlaps
        # the enqueue/sendall of frame k, and the window bounds memory.
        window: list = []
        max_window = (transport_codec.workers() + 2) if self._pool else 0

        def _drain_one() -> None:
            off, fut = window.pop(0)
            t_wait = time.monotonic()
            try:
                used, payload, raw_n, crc_raw = fut.result(timeout=600.0)
                waited = time.monotonic() - t_wait
                self.codec_wait_s += waited
                CODEC_WAIT_SECONDS.observe(waited)
            except (transport_codec.CodecError, FuturesTimeoutError) as exc:
                # Both travel the wire-failure path: the session poisons
                # and the caller falls back to the PVC tee — a wedged
                # codec pool must not escalate past the wire's failure
                # domain into a failed checkpoint leg.
                raise WireError(f"wire codec failed: {exc}") from exc
            self.send_record(rel, off, payload, used, raw_n, crc_raw,
                             size=size)

        file_codec = self.codec
        with open(path, "rb") as f:
            off = 0
            while off < size:
                data = f.read(min(WIRE_FRAME_BYTES, size - off))
                if not data:
                    raise WireError(f"{path} shrank mid-send at {off}")
                # Codec always on here: the raw (pool-less) large-file
                # path returned above via _FileSegment/sendfile frames.
                if off == 0:
                    # One adaptive decision per file, on its head —
                    # frames then skip the per-block sample.
                    try:
                        file_codec = transport_codec.decide_codec(
                            data, self.codec)
                    except transport_codec.CodecError as exc:
                        raise WireError(
                            f"wire codec failed: {exc}") from exc
                window.append((off, transport_codec.pool_submit(
                    transport_codec.compress_block, data, file_codec,
                    presampled=True, elide_zeros=True)))
                if len(window) >= max_window:
                    _drain_one()
                off += len(data)
        while window:
            _drain_one()
        return size

    def send_tree(
        self,
        src_dir: str,
        skip: set[str] | frozenset[str] = frozenset(),
        skip_unchanged: dict[str, tuple[int, int]] | None = None,
    ) -> dict[str, int]:
        """Ship every file under ``src_dir`` not in ``skip`` (rels already
        streamed by the dump sink) and not matching ``skip_unchanged``
        (files the pre-copy phase landed on the destination via prestage),
        metadata-priority first. Returns ``{rel: size}`` of what was sent.
        """
        files = sorted(_iter_files(src_dir),
                       key=lambda pr: (_stage_priority(pr[1]), pr[1]))
        sent: dict[str, int] = {}
        for path, rel in files:
            if rel in skip:
                continue
            st = os.stat(path)
            if skip_unchanged and \
                    skip_unchanged.get(rel) == (st.st_size, st.st_mtime_ns):
                continue
            sent[rel] = self.send_file(rel, path)
        return sent

    # -- session control --------------------------------------------------------

    def _flush(self, timeout: float | None = None) -> None:
        """Drain every per-stream send queue, bounded: a consumer thread
        wedged in sendall (peer hung, no RST) must surface as a loud
        WireError inside the session — Queue.join has no timeout, so
        wait on the queues' all_tasks_done condition directly."""
        if timeout is None:
            timeout = config.WIRE_FLUSH_TIMEOUT_S.get()
        if self._native is not None:
            for k, w in enumerate(self._native):
                try:
                    w.flush(timeout)
                except OSError as exc:
                    self._dead = self._dead or str(exc)
                    log.error("wire flush: native stream %d failed "
                              "to drain: %s", k, exc)
                    raise WireError(
                        f"wire flush failed (stream {k}): {exc}")
            if self._dead is not None:
                raise WireError(f"wire send failed: {self._dead}")
            # Rings drained: every enqueued watermark is passed, so
            # credit it all NOW, synchronously, before the caller sends
            # the commit frame — the lease/CR publication chain gets the
            # whole commit round-trip to surface a fully-credited
            # tracker instead of racing the pacer thread's next tick.
            self._drain_pace()
            return
        deadline = time.monotonic() + timeout
        for k, q in enumerate(self._queues):
            with q.all_tasks_done:
                while q.unfinished_tasks:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        log.error(
                            "wire flush: stream %d still has %d queued "
                            "frame(s) after %.0fs", k, q.unfinished_tasks,
                            timeout)
                        raise WireError(
                            f"wire flush timed out after {timeout}s "
                            f"(stream {k} wedged)")
                    q.all_tasks_done.wait(min(remaining, 30.0))
        if self._dead is not None:
            raise WireError(f"wire send failed: {self._dead}")

    def commit(self, files: dict[str, int],
               timeout: float | None = None) -> None:
        """Flush every stream, send the commit frame, wait for the
        destination's ack. Raises :class:`WireError` unless the receiver
        confirms every listed file landed intact."""
        self._flush()
        sock = self._socks[0]
        flight.emit("wire.commit.start", files=len(files))
        committed = False
        try:
            self._commit(sock, files, timeout)
            committed = True
        finally:
            # The bracket closes on EVERY exit: an unterminated interval
            # would otherwise extend to the blackout window end at
            # wire_commit priority, swallowing the recovery tail.
            flight.emit("wire.commit.end", files=len(files), ok=committed)

    def _commit(self, sock, files: dict[str, int],
                timeout: float | None) -> None:
        t0 = time.monotonic()
        try:
            # The commit frame carries this process's wall/monotonic pair
            # (and the ack returns the receiver's): the wire-handshake
            # half of gritscope's cross-process clock alignment. Older
            # receivers ignore the extra field.
            frame = _wire_frame({"t": "commit", "files": files,
                                 "clk": flight.clock_pair()})
            # Timeout armed BEFORE the send: _flush drained the rings, so
            # nothing native is mid-send on this fd, and the native
            # handoff's setblocking(True) cleared the dial timeout — an
            # unarmed sendall into a wedged receiver's full TCP window
            # would block forever instead of raising the bounded
            # WireError the PVC fallback needs. (The C worker poll-loops
            # on EAGAIN, so a timeout-mode fd never breaks it anyway.)
            sock.settimeout(timeout if timeout is not None else self._timeout)
            sock.sendall(frame)
            with self._lock:
                self._py_sent_bytes += len(frame)
            buf = b""
            while b"\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise WireError("wire peer closed before ack")
                buf += chunk
        except OSError as exc:
            raise WireError(f"wire commit failed: {exc}")
        finally:
            self.ack_s = time.monotonic() - t0
        ack = json.loads(buf.split(b"\n", 1)[0])
        peer_clk = ack.get("clk")
        if isinstance(peer_clk, dict):
            flight.emit("clock.peer",
                        peer_wall=float(peer_clk.get("wall", 0.0)),
                        peer_mono=float(peer_clk.get("mono", 0.0)),
                        peer_host=str(peer_clk.get("host", "")),
                        peer_pid=int(peer_clk.get("pid", 0)))
        if not ack.get("ok"):
            raise WireError(
                f"destination rejected wire session: {ack.get('error')}")

    def fail(self, msg: str) -> None:
        """Best-effort abort marker so the receiver fails fast instead of
        waiting out its commit timeout."""
        try:
            # Bounded like _commit: the session is already dead and the
            # native handoff left the fd blocking — this path must not
            # pin a failing agent past its watchdog deadlines.
            self._socks[0].settimeout(self._timeout)
            self._socks[0].sendall(_wire_frame({"t": "fail", "msg": msg}))
        except OSError:
            pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._native is not None:
            # Pacer off first (its final sweep credits everything that
            # reached the wire; frames a dead session never sent stay
            # uncredited, like the Python worker's dead-drain).
            self._pace_stop.set()
            self._pace_thread.join(timeout=5.0)
            # Abort before destroy: close() is the end of the session on
            # EVERY path (commit-ack already read, or the session died —
            # flush timeout, receiver WireError, fail()), so queued
            # never-sent segments are abandoned and the socket severed
            # rather than letting destroy's join push them at a wedged
            # peer for up to timeout_s each. Harmless post-ack: the ring
            # is empty and the socket's job is done.
            for w in self._native:
                w.abort()
            # Fold the native workers' counters into the Python-side
            # aggregates BEFORE destroying them — the live properties
            # below read 0 from a destroyed worker, and the wire.close
            # breakdown must read the same whichever plane moved the
            # bytes.
            for w in self._native:
                self._py_sent_bytes += w.sent_bytes()
                self._py_send_s += w.send_seconds()
                self._py_stall_s += w.stall_seconds()
                w.destroy()
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=self._timeout)
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        WIRE_BYTES.inc(self.sent_bytes, role="send")
        WIRE_SECONDS.inc(self.send_s, phase="send")
        WIRE_SECONDS.inc(self.stall_s, phase="stall")
        WIRE_SECONDS.inc(self.ack_s, phase="ack")
        from grit_tpu.obs import trace  # noqa: PLC0415

        trace.record_span(
            "wire_stream", time.time_ns(),
            bytes=self.sent_bytes, streams=len(self._socks),
            send=round(self.send_s, 4), stall=round(self.stall_s, 4),
            ack=round(self.ack_s, 4),
        )
        # The per-leg wire breakdown gritscope folds into the blackout
        # attribution (send vs backpressure stall vs commit-ack wait).
        flight.emit("wire.close", bytes=self.sent_bytes,
                    streams=len(self._socks), send_s=round(self.send_s, 4),
                    stall_s=round(self.stall_s, 4),
                    ack_s=round(self.ack_s, 4),
                    codec_wait_s=round(self.codec_wait_s, 4))

    def __enter__(self) -> "WireSender":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class WireDumpSink:
    """Hand-off from the HBM dump loop to the wire: ``put()`` receives each
    serialized chunk's bytes (via the snapshot ``_MirrorWriter`` tee, in
    data-file write order) and frames them onto the sender.

    Contract mirrors the mirror tee's: a wire failure only disables the
    sink (``ok`` flips false, the PVC path ships the bytes instead) — it
    never fails the dump. Backpressure from the sender's bounded queues
    propagates here, so a slow destination throttles the dump's tee
    thread, not host memory.
    """

    def __init__(self, sender: WireSender, rel: str) -> None:
        self._sender = sender
        self.rel = rel
        self.ok = True
        self.error: str | None = None
        self.nbytes = 0  # RAW bytes streamed (the receiver's accounting)
        self.comp_bytes = 0  # payload bytes actually framed onto the wire
        # Bytes that reached a socket while the dump was still draining —
        # the numerator of the shipped-bytes overlap fraction.
        self.bytes_during_dump = 0

    def put(self, view) -> None:
        if not self.ok:
            return
        try:
            mv = memoryview(view).cast("B")
            off = 0
            while off < len(mv):
                n = min(WIRE_FRAME_BYTES, len(mv) - off)
                # Zero-copy: the memoryview slice rides the queue and the
                # socket write directly; it pins the dump's host buffer
                # until sent, bounded by the per-stream queue depth.
                self._sender.send_chunk(self.rel, self.nbytes,
                                        mv[off:off + n])
                self.nbytes += n
                self.comp_bytes += n
                off += n
        except WireError as exc:
            self.ok = False
            self.error = str(exc)

    def put_record(self, codec_name: str, payload, raw_off: int,
                   raw_n: int, crc_raw: int) -> None:
        """Post-codec hand-off from the mirror's codec stage: one block,
        already compressed (or adaptively left raw), framed with its raw
        coordinates + CRC-of-raw. Same contract as :meth:`put`: wire
        failures only flip ``ok``, never fail the dump."""
        if not self.ok:
            return
        try:
            self._sender.send_record(self.rel, raw_off, payload,
                                     codec_name, raw_n, crc_raw)
            self.nbytes += raw_n
            self.comp_bytes += len(payload)
        except WireError as exc:
            self.ok = False
            self.error = str(exc)

    def mark_failed(self, msg: str) -> None:
        self.ok = False
        self.error = self.error or msg

    def finish(self, ok: bool = True) -> bool:
        """Called when the dump's tee drained its last chunk; sends the
        stream terminator. Returns whether the wire leg stayed healthy."""
        if not ok:
            self.mark_failed("dump tee failed before wire eof")
        if self.ok:
            try:
                self._sender.eof(self.rel, self.nbytes)
                self.bytes_during_dump = self._sender.sent_bytes
            except WireError as exc:
                self.ok = False
                self.error = str(exc)
        return self.ok


class WireReceiver:
    """Destination half of the wire: accepts sender connections, verifies
    every frame's CRC, writes payloads into ``dst_dir``, and publishes
    progress through the streamed-staging journal so the restore pipeline
    can consume chunks as they land.

    Failure semantics (the stale-journal-clear machinery's contract): ANY
    frame error, CRC mismatch, short stream, or peer disconnect before a
    verified commit fails the session — the journal gets its terminal
    ``failed`` marker (consumers raise ``SnapshotIntegrityError``), no
    sentinel is dropped, and the caller falls back to the PVC path.
    """

    def __init__(self, dst_dir: str, host: str | None = None, port: int = 0,
                 journal: StageJournal | None = None) -> None:
        os.makedirs(dst_dir, exist_ok=True)
        self.dst_dir = dst_dir
        self.journal = journal
        host = host or config.WIRE_HOST.get()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # An explicit host (arg or GRIT_WIRE_HOST) pins both the bind
        # interface and the published address. Otherwise listen on all
        # interfaces and publish the node's primary address — the source
        # agent runs on a DIFFERENT node, so a loopback endpoint in the
        # rendezvous file would silently degrade every cross-node
        # migration to the PVC path (agent Jobs run hostNetwork, so the
        # node address is exactly what the peer can reach).
        self._srv.bind((host, port))
        self._srv.listen(16)
        publish_host = host or _node_address()
        self.endpoint = f"{publish_host}:{self._srv.getsockname()[1]}"
        self._cond = threading.Condition()
        self._fds: dict[str, int] = {}
        self._water: dict[str, int] = {}
        self._pending: dict[str, dict[int, int]] = {}
        self._done: dict[str, int] = {}
        self._expected: dict[str, int] | None = None
        self._error: str | None = None
        self._failing = False
        self._complete = False
        self._conns = 0
        self._conn_socks: list[socket.socket] = []
        self._ever_connected = False
        self.recv_bytes = 0
        # Frame decode (decompress + CRC-of-raw verify) runs in the shared
        # codec pool, NOT on the connection threads and NOT under the
        # receiver lock — verify-then-write overlaps across frames and
        # streams. Admission is BYTE-bounded (like the mirror writer's
        # _ByteBoundedQueue), not frame-counted: a native-plane sender
        # ships raw file segments at WIRE_NATIVE_SEGMENT_BYTES (8× a
        # Python-plane frame), and a count bound sized for 4 MiB frames
        # would multiply this receiver's in-flight memory by the frame
        # size ratio in a mixed-plane session. One oversized frame is
        # always admitted (the budget can't deadlock an empty pipeline).
        self._decode_budget = (max(4, transport_codec.workers() * 2)
                               * WIRE_FRAME_BYTES)
        self._decode_bytes = 0
        self._decode_cv = threading.Condition()
        # Frames submitted to the pool but not yet applied, per rel:
        # commit's disk-size acceptance must never fire for a file whose
        # decoded bytes are still in flight (the stale-prestaged-twin
        # would pass on size while the fresh pwrites race the sentinel).
        self._inflight: dict[str, int] = {}
        self._t0 = time.monotonic()
        self._published: str | None = None
        # wire.recv.fail is emitted EXACTLY ONCE per session whatever
        # races — a conn worker failing, the caller tearing the
        # receiver down around a connected-but-uncommitted session, or
        # both at once (the profiler disarms wire_recv on it; a missing
        # event samples forever, a duplicate double-counts the bracket).
        self._fail_emitted = False
        self._pump_stop = False
        self._conn_by_id: dict[int, socket.socket] = {}
        # Conn ids whose reader finished BEFORE the accept loop could
        # store the socket (a dial-and-die peer): the late store must
        # close the dead socket instead of registering it forever.
        self._conn_done_ids: set[int] = set()
        # Native plane: per-connection reader threads decode, CRC-verify
        # and pwrite raw frames in C; this process only consumes (rel,
        # off, n, crc-ok) completions through one pump thread. Control
        # frames and codec payloads pass through to the existing Python
        # handlers — the commit handshake and the codec pool do not move.
        self._native: native_wire.RecvSession | None = None
        if native_wire.enabled():
            try:
                self._native = native_wire.RecvSession(
                    dst_dir, transport_codec.SIDECAR_SUFFIX)
            except OSError as exc:
                log.warning(
                    "native wire receive plane failed to start (%s) — "
                    "using the Python frame loop", exc)
        if self._native is not None:
            threading.Thread(target=self._pump,
                             name="grit-wire-recv-pump",
                             daemon=True).start()
        threading.Thread(target=self._accept_loop,
                         name="grit-wire-accept", daemon=True).start()

    # -- rendezvous -------------------------------------------------------------

    def publish(self, work_dir: str) -> str:
        """Drop the endpoint file into the shared checkpoint work dir (the
        PVC) — the only rendezvous both agents can already see."""
        from grit_tpu.metadata import WIRE_ENDPOINT_FILE  # noqa: PLC0415

        os.makedirs(work_dir, exist_ok=True)
        path = os.path.join(work_dir, WIRE_ENDPOINT_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"endpoint": self.endpoint, "pid": os.getpid()}, f)
        os.replace(tmp, path)
        self._published = path
        return path

    def unpublish(self) -> None:
        if self._published:
            try:
                os.unlink(self._published)
            except OSError:
                pass
            self._published = None

    # -- accept / frame plumbing ------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._cond:
                if self._error is not None or self._failing \
                        or self._complete or self._pump_stop:
                    conn.close()  # session over: no late writers admitted
                    continue
                self._conns += 1
                first = not self._ever_connected
                self._ever_connected = True
                self._conn_socks.append(conn)
            if first:
                flight.emit("wire.recv.open", dir=self.dst_dir,
                            role="destination", endpoint=self.endpoint)
            if self._native is not None:
                try:
                    cid = self._native.add_conn(conn)
                except OSError as exc:
                    self._fail(f"wire receive failed: {exc}")
                    return
                with self._cond:
                    if cid in self._conn_done_ids:
                        # The reader posted its EOF/error and
                        # _conn_finished ran before this store: the
                        # socket is already done — registering it now
                        # would leak it (and its _conn_socks entry) for
                        # the life of the process.
                        self._conn_done_ids.discard(cid)
                        if conn in self._conn_socks:
                            self._conn_socks.remove(conn)
                        try:
                            conn.close()
                        except OSError:
                            pass
                    else:
                        self._conn_by_id[cid] = conn
                    # The native reader started INSIDE add_conn and may
                    # already have posted a completion carrying this id:
                    # wake a pump blocked in _conn_sock on it.
                    self._cond.notify_all()
                continue
            threading.Thread(target=self._conn_worker, args=(conn,),
                             daemon=True).start()

    def _conn_worker(self, conn: socket.socket) -> None:
        try:
            while True:
                try:
                    raw = conn.recv(4)
                except OSError as exc:
                    raise ConnectionError(str(exc))
                if not raw:
                    return  # clean close at a frame boundary
                if len(raw) < 4:
                    raw += _recv_exact(conn, 4 - len(raw))
                (hlen,) = struct.unpack(">I", raw)
                header = json.loads(_recv_exact(conn, hlen))
                payload = _recv_exact(conn, int(header.get("n", 0)))
                self._handle(conn, header, payload)
        except (ConnectionError, OSError, ValueError, WireError) as exc:
            self._fail(f"wire receive failed: {exc}")
        finally:
            conn.close()
            with self._cond:
                self._conns -= 1
                if conn in self._conn_socks:
                    self._conn_socks.remove(conn)
                alone = self._conns == 0 and self._ever_connected
                finished = self._complete or self._error is not None
                self._cond.notify_all()
            if alone and not finished:
                self._fail("wire peer disconnected before commit")

    # -- native completion pump -------------------------------------------------

    def _pump(self) -> None:
        """Single consumer of the native session's completion queue:
        folds natively-applied frames into the waterline/journal/
        progress accounting and routes passed-through frames into the
        existing Python handlers. Ends (and destroys the session) once
        the receiver is closing and the queue has drained."""
        sess = self._native
        assert sess is not None
        try:
            while True:
                ev = sess.next(200)
                if ev is None:
                    if self._pump_stop:
                        return
                    continue
                try:
                    self._pump_event(ev)
                except (WireError, OSError, ValueError, KeyError,
                        struct.error) as exc:
                    self._fail(f"wire receive failed: {exc}")
        finally:
            sess.destroy()

    def _pump_event(self, ev) -> None:
        if ev.kind == native_wire.EV_DATA:
            if not ev.crc_ok:
                raise WireError(
                    f"frame CRC mismatch for {ev.rel!r} "
                    f"(offset {ev.off}, {ev.n} bytes)")
            self._account_native(ev)
            return
        if ev.kind == native_wire.EV_BLOB:
            blob = ev.blob or b""
            (hlen,) = struct.unpack(">I", blob[:4])
            header = json.loads(blob[4:4 + hlen])
            payload = blob[4 + hlen:]
            sock = self._conn_sock(ev.conn)
            if header.get("t") in ("eof", "commit"):
                # Both BLOCK on the waterline/commit condition — they
                # get their own thread (exactly the conn thread they
                # would have occupied on the Python plane) so the pump
                # keeps folding the data completions they wait on.
                threading.Thread(
                    target=self._handle_guarded,
                    args=(sock, header, payload), daemon=True).start()
            else:
                self._handle(sock, header, payload)
            return
        if ev.kind == native_wire.EV_CONN_ERROR:
            # Fail with the reader's specific error BEFORE the conn
            # bookkeeping: _conn_finished would otherwise win the race
            # with its generic "peer disconnected" message.
            self._fail(f"wire receive failed: "
                       f"{ev.err or 'connection error'}")
            self._conn_finished(ev.conn)
            return
        self._conn_finished(ev.conn)  # EV_CONN_CLOSED

    def _conn_sock(self, conn_id: int, timeout: float = 5.0):
        """The Python socket for a native conn id, waiting out the
        registration window: the native reader starts inside add_conn()
        and can post a frame before the accept loop stores the socket —
        a commit handled in that window would otherwise lose its ack."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while conn_id not in self._conn_by_id:
                if time.monotonic() > deadline:
                    return None
                self._cond.wait(timeout=0.05)
            return self._conn_by_id[conn_id]

    def _handle_guarded(self, sock, header: dict, payload: bytes) -> None:
        try:
            self._handle(sock, header, payload)
        except (WireError, OSError, ValueError, KeyError) as exc:
            self._fail(f"wire receive failed: {exc}")

    def _account_native(self, ev) -> None:
        """Bookkeeping for a frame the native plane already verified and
        pwrote: the same waterline/journal/progress movements
        _apply_file/_apply_chunk make after their own pwrite."""
        # The receive-side chaos seam holds on this plane too: an armed
        # wire.recv fault poisons the session exactly as it does when
        # the Python loop handles the frame.
        faults.fault_point("wire.recv", wrap=WireError)
        rel, n = ev.rel, ev.n
        completed = False
        with self._cond:
            if self._error is not None:
                return  # poisoned: late completions change nothing
            if ev.is_file:
                self._done[rel] = n
                self.recv_bytes += n
            else:
                water = advance_waterline(
                    self._pending.setdefault(rel, {}),
                    self._water.get(rel, 0), ev.off, n)
                self._water[rel] = water
                self.recv_bytes += n
                if ev.size is not None and water >= ev.size:
                    self._pending.pop(rel, None)
                    self._done[rel] = water
                    completed = True
            self._cond.notify_all()
        if (ev.is_file or completed) and self._native is not None:
            self._native.close_rel(rel)
        WIRE_NATIVE_BYTES.inc(n, path="recv")
        progress.add_bytes(progress.ROLE_DESTINATION, n,
                           stream="wire-recv")
        if self.journal is not None:
            if ev.is_file:
                self.journal.note_file(rel, n)
            else:
                self.journal.note_chunk(rel, ev.off, n, ev.size)

    def _conn_finished(self, conn_id: int) -> None:
        """Native-plane twin of _conn_worker's finally block."""
        with self._cond:
            sock = self._conn_by_id.pop(conn_id, None)
            if sock is None:
                # Reader beat the accept loop's registration: mark the
                # id done so the late store closes the socket.
                self._conn_done_ids.add(conn_id)
            self._conns -= 1
            if sock is not None and sock in self._conn_socks:
                self._conn_socks.remove(sock)
            alone = self._conns == 0 and self._ever_connected
            finished = self._complete or self._error is not None
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if alone and not finished:
            self._fail("wire peer disconnected before commit")

    def _fd(self, rel: str) -> int:
        # caller holds _cond
        if self._error is not None or self._failing:
            # A failed session must never reopen files: the PVC fallback
            # may be restaging this directory RIGHT NOW, and a late frame
            # pwriting through a lazily-reopened fd would tear its work.
            # _failing covers the claim→publish window while the journal
            # tombstone is still being written.
            raise WireError(
                f"wire session already failed: {self._error or 'failing'}")
        fd = self._fds.get(rel)
        if fd is None:
            path = os.path.join(self.dst_dir, rel)
            os.makedirs(os.path.dirname(path) or self.dst_dir, exist_ok=True)
            # The wire lands DECODED RAW bytes: a codec sidecar left by a
            # prestaged container tree (run_restore_wire(prestage=True)
            # of a codec-on PVC mirror) would relabel them as compressed
            # at restore time — corrupting a fully successful session.
            try:
                os.unlink(path + transport_codec.SIDECAR_SUFFIX)
            except OSError:
                pass
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            self._fds[rel] = fd
        return fd

    def _handle(self, conn: socket.socket, header: dict,
                payload: bytes) -> None:
        t = header.get("t")
        if t == "fail":
            raise WireError(f"source aborted: {header.get('msg')}")
        if t == "commit":
            faults.fault_point("wire.commit", wrap=WireError)
            self._handle_commit(conn, header)
            return
        faults.fault_point("wire.recv", wrap=WireError)
        if t in ("file", "chunk"):
            rel = _check_rel(str(header.get("rel")))
            # Decode (optional decompress) + CRC-of-raw verification run
            # in the shared codec pool: this connection thread goes
            # straight back to its socket, so verify-then-write of frame
            # k overlaps the receive of frame k+1 — and never holds the
            # receiver lock while checksumming. The byte budget bounds
            # in-flight undecoded payload; it releases inside the pool
            # job.
            self._decode_admit(len(payload))
            with self._cond:
                self._inflight[rel] = self._inflight.get(rel, 0) + 1
            try:
                transport_codec.pool_submit(
                    self._decode_apply, dict(header), payload, rel)
            except BaseException:
                self._decode_release(len(payload))
                self._decode_done(rel)
                raise
            return
        if t == "eof":
            rel = _check_rel(str(header.get("rel")))
            total = int(header["total"])
            deadline = time.monotonic() + stage_timeout_s()
            with self._cond:
                # Multi-stream: this file's trailing chunks may still be
                # in flight on sibling connections — eof is the stream's
                # synchronization point, so wait for the waterline to
                # reach the declared total before judging it short.
                while self._water.get(rel, 0) < total \
                        and self._error is None:
                    # Deadline checked every pass: steady notify traffic
                    # from sibling streams' chunks must not postpone it.
                    if time.monotonic() > deadline:
                        break
                    self._cond.wait(timeout=1.0)
                water = self._water.get(rel, 0)
                if water != total or self._pending.get(rel):
                    raise WireError(
                        f"wire stream for {rel} ended short "
                        f"({water}/{total} contiguous bytes)")
                self._done[rel] = total
                fd = self._fds.pop(rel, None)
                if fd is not None:
                    os.close(fd)
                self._cond.notify_all()
            if self._native is not None:
                self._native.close_rel(rel)
            if self.journal is not None:
                self.journal.note_file(rel, total)
            return
        raise WireError(f"unknown wire frame kind {t!r}")

    def _decode_apply(self, header: dict, payload: bytes,
                      rel: str) -> None:
        """Codec-pool half of frame handling: validate the codec id,
        decompress, check the declared raw size and the CRC of the raw
        bytes, then apply the write. ANY failure — unknown codec id,
        decompressed-size mismatch, CRC-of-raw mismatch after a
        successful decompress — poisons the whole session (journal
        failed, no sentinel), exactly like a torn raw frame."""
        try:
            codec_id = str(header.get("c", transport_codec.CODEC_NONE))
            raw_n = (int(header["rn"]) if "rn" in header
                     else len(payload))
            raw = transport_codec.decompress_block(
                codec_id, payload, raw_n, int(header.get("crc", -1)))
            if header.get("t") == "file":
                self._apply_file(rel, raw)
            else:
                self._apply_chunk(rel, int(header["off"]), raw,
                                  header.get("size"))
        except (transport_codec.CodecError, WireError, OSError,
                ValueError, KeyError) as exc:
            self._fail(f"wire receive failed for {rel!r}: {exc}")
        finally:
            self._decode_done(rel)
            self._decode_release(len(payload))

    def _decode_admit(self, n: int) -> None:
        """Block until ``n`` undecoded payload bytes fit in the decode
        budget. A frame larger than the whole budget is admitted once
        the pipeline is empty — oversize must slow the session down,
        never wedge it. Bails on a poisoned session so conn threads
        don't park against a pipeline that stopped draining."""
        with self._decode_cv:
            while self._decode_bytes > 0 \
                    and self._decode_bytes + n > self._decode_budget:
                if self._error is not None or self._failing:
                    raise WireError(
                        f"wire session already failed: "
                        f"{self._error or 'failing'}")
                self._decode_cv.wait(timeout=1.0)
            self._decode_bytes += n

    def _decode_release(self, n: int) -> None:
        with self._decode_cv:
            self._decode_bytes -= n
            self._decode_cv.notify_all()

    def _decode_done(self, rel: str) -> None:
        with self._cond:
            n = self._inflight.get(rel, 1) - 1
            if n <= 0:
                self._inflight.pop(rel, None)
            else:
                self._inflight[rel] = n
            self._cond.notify_all()

    def _apply_file(self, rel: str, payload) -> None:
        with self._cond:
            fd = self._fd(rel)
            os.pwrite(fd, payload, 0)
            os.ftruncate(fd, len(payload))
            os.close(self._fds.pop(rel))
            self._done[rel] = len(payload)
            self.recv_bytes += len(payload)
            self._cond.notify_all()
        progress.add_bytes(progress.ROLE_DESTINATION, len(payload),
                           stream="wire-recv")
        if self.journal is not None:
            self.journal.note_file(rel, len(payload))

    def _apply_chunk(self, rel: str, off: int, payload, size) -> None:
        n = len(payload)
        with self._cond:
            # The pwrite stays under the lock: _fail()/close() (from a
            # sibling connection thread or the wait-timeout path) pop
            # and close these fds, and a pwrite racing that close
            # could land on a reused descriptor — corrupting an
            # unrelated file the PVC fallback just opened. The write
            # is a page-cache memcpy; decode + CRC already happened
            # OUTSIDE the lock, in this pool worker.
            fd = self._fd(rel)
            os.pwrite(fd, payload, off)  # offset-addressed: no seek
            water = advance_waterline(
                self._pending.setdefault(rel, {}),
                self._water.get(rel, 0), off, n)
            self._water[rel] = water
            self.recv_bytes += n
            if size is not None and water >= int(size):
                self._pending.pop(rel, None)
                self._done[rel] = water
                fd = self._fds.pop(rel, None)
                if fd is not None:
                    os.close(fd)
            self._cond.notify_all()
        progress.add_bytes(progress.ROLE_DESTINATION, n,
                           stream="wire-recv")
        if self.journal is not None:
            self.journal.note_chunk(
                rel, off, n, int(size) if size is not None else None)

    def _handle_commit(self, conn: socket.socket, header: dict) -> None:
        files = {_check_rel(str(r)): int(s)
                 for r, s in dict(header.get("files", {})).items()}
        dst_tracker = progress.get(progress.ROLE_DESTINATION)
        if dst_tracker is not None:
            # The commit map is the first moment the destination knows
            # its total (raw bytes; prestaged files included).
            dst_tracker.set_total(sum(files.values()))
        peer_clk = header.get("clk")
        if isinstance(peer_clk, dict):
            # The commit frame carries the sender's clock pair (and the
            # ack below returns ours): gritscope's wire-handshake clock
            # alignment, receiver half.
            flight.emit("clock.peer", dir=self.dst_dir,
                        role="destination",
                        peer_wall=float(peer_clk.get("wall", 0.0)),
                        peer_mono=float(peer_clk.get("mono", 0.0)),
                        peer_host=str(peer_clk.get("host", "")),
                        peer_pid=int(peer_clk.get("pid", 0)))
        deadline = time.monotonic() + stage_timeout_s()

        def _have(rel: str, size: int) -> bool:
            if self._inflight.get(rel):
                # Frames for this file are still in the decode pool: its
                # state is not judgeable yet (a stale same-size twin on
                # disk must not settle the commit under the late pwrites).
                return False
            if self._done.get(rel) == size:
                return True
            # Not wire-shipped: the source skipped it because the
            # destination prestaged it from the PVC during the live
            # pre-copy phase — accept it from disk by size (the restore's
            # per-chunk CRC verification is the content backstop).
            if rel in self._done or rel in self._pending:
                return False  # wire-shipped but wrong/incomplete: not ok
            try:
                path = os.path.join(self.dst_dir, rel)
                if os.path.getsize(path) == size:
                    return True
                # Prestaged from a codec-container PVC tree: the on-disk
                # size is compressed — compare the sidecar's decoded raw
                # size against the source's raw identity instead.
                return transport_codec.container_raw_size(path) == size
            except OSError:
                return False

        def _settled() -> bool:
            if self._error is not None:
                return True
            return all(_have(r, s) for r, s in files.items())

        with self._cond:
            self._expected = files
            while not _settled():
                # Deadline checked every pass (not only on a quiet
                # timeout): continuous chunk notifies from other files
                # must not keep a never-satisfiable commit alive.
                if time.monotonic() > deadline:
                    missing = [r for r, s in files.items()
                               if self._done.get(r) != s][:5]
                    raise WireError(
                        f"commit timed out waiting for {missing}")
                self._cond.wait(timeout=1.0)
            if self._error is not None:
                raise WireError(self._error)
            disk_accepted = [r for r, s in files.items()
                             if self._done.get(r) != s]
            missing = disk_accepted[:50]
            self._complete = True
            self._cond.notify_all()
        if dst_tracker is not None and disk_accepted:
            # Credit the prestage-settled files at their RAW size now
            # that the commit verified them from disk: the prestage
            # download itself deliberately does not count (a codec-on
            # PVC ships compressed containers — counting disk bytes
            # against this raw total would park progress at the
            # compression ratio).
            dst_tracker.add_bytes(
                sum(files[r] for r in disk_accepted), stream="prestaged")
        if self.journal is not None:
            # Prestaged (disk-accepted) files still need their journal
            # record so the completeness story reads whole; complete()
            # below unblocks everything regardless.
            for rel in missing:
                self.journal.note_file(rel, files[rel])
        if self.journal is not None:
            self.journal.complete()
        flight.emit("wire.recv.commit", dir=self.dst_dir,
                    role="destination", files=len(files),
                    bytes=self.recv_bytes)
        try:
            if conn is not None:  # None: native conn never registered
                conn.sendall(json.dumps(
                    {"ok": True,
                     "clk": flight.clock_pair()}).encode() + b"\n")
        except OSError:
            pass  # the data is safe either way; sender falls back loudly

    def _emit_recv_fail(self, msg: str) -> None:
        """The terminal wire.recv.fail event, exactly once per session:
        _fail() and an abandoning close() can race from different
        threads (conn worker vs caller teardown mid-accept), and the
        flight bracket must neither go missing nor double-close."""
        with self._cond:
            if self._fail_emitted:
                return
            self._fail_emitted = True
        flight.emit("wire.recv.fail", dir=self.dst_dir,
                    role="destination", msg=msg[:500])

    def _fail(self, msg: str) -> None:
        with self._cond:
            if self._complete or self._error is not None or self._failing:
                return
            # Claim the failure WITHOUT publishing it: wait() polls on a
            # timed wait, so the moment _error is visible a waiter can
            # raise, return to its caller, and read the journal — which
            # must already carry the failed tombstone by then (the
            # caller's next move is deciding the PVC fallback from it).
            self._failing = True
            for fd in self._fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._fds.clear()
            # Sever live senders NOW: their conn workers exit on the
            # socket error instead of pushing more frames into a
            # directory the PVC fallback may already be restaging
            # (_fd() also refuses to reopen once the fail is claimed).
            for c in self._conn_socks:
                try:
                    c.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        if self._native is not None:
            # Poison the native session (frames in a reader's hands are
            # dropped, not applied), then QUIESCE: join the reader
            # threads so a pwrite already past the abort check cannot
            # land after _fail returns — the caller's next move is the
            # PVC fallback restaging this very directory.
            self._native.abort()
            self._native.quiesce()
        if self.journal is not None:
            try:
                self.journal.fail(msg)
            except OSError:
                pass
        with self._cond:
            self._error = msg
            self._cond.notify_all()
        self._emit_recv_fail(msg)
        self.close(_from_fail=True)

    # -- caller API -------------------------------------------------------------

    def poll(self) -> str | None:
        """Non-blocking session state: "complete", "failed", or None
        (still in flight)."""
        with self._cond:
            if self._error is not None:
                return "failed"
            return "complete" if self._complete else None

    @property
    def ever_connected(self) -> bool:
        with self._cond:
            return self._ever_connected

    def verified_files(self) -> dict[str, int]:
        """``{rel: raw_size}`` of files this session fully landed AND
        content-verified (every frame's CRC-of-raw checked, waterline
        closed at the declared size). Stable even after the session
        failed: a partial or unverified file is never in the map, so a
        wire→PVC fallback can safely skip re-shipping these — the
        "complete-but-compressed partial wire leg" case included, since
        accounting is in raw bytes regardless of the frame codec."""
        with self._cond:
            return dict(self._done)

    def fail(self, msg: str) -> None:
        """Abort the session from the caller side (e.g. a wait-loop
        timeout): journal poisoned, waiters released, listener closed."""
        self._fail(msg)

    def wait(self, timeout: float | None = None) -> TransferStats:
        """Block until the session commits; raises :class:`WireError` on
        any failure (the caller then falls back to the PVC path)."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            while not self._complete and self._error is None:
                if deadline is not None and time.monotonic() > deadline:
                    break
                self._cond.wait(timeout=0.5)
            error = self._error
            complete = self._complete
        self.unpublish()
        if error is not None:
            raise WireError(error)
        if not complete:
            self._fail(f"wire session timed out after {timeout}s")
            raise WireError(f"wire session timed out after {timeout}s")
        stats = TransferStats(
            files=len(self._done), bytes=self.recv_bytes,
            seconds=time.monotonic() - self._t0,
        )
        WIRE_BYTES.inc(stats.bytes, role="recv")
        # Session over: release the listener and its accept thread (a
        # long-lived process runs many migrations).
        self.close()
        return stats

    def close(self, _from_fail: bool = False) -> None:
        abandoned = False
        with self._cond:
            if not _from_fail and self._ever_connected \
                    and not self._complete and self._error is None \
                    and not self._failing:
                # The caller tore the session down around the receiver
                # (a WireError elsewhere -> PVC fallback): a source
                # connected but no commit/fail ever closed the wire
                # session. Record it as failed — it did — so the
                # flight timeline's receive bracket terminates and the
                # phase profiler disarms wire_recv instead of sampling
                # for the remaining life of the process.
                self._error = "receiver closed before commit"
                abandoned = True
        if abandoned:
            self._emit_recv_fail("receiver closed before commit")
        self.unpublish()
        try:
            self._srv.close()
        except OSError:
            pass
        if self._native is not None:
            # Stop the pump after the queue drains and sever the native
            # dup'd conns; a _fail-driven close already aborted AND
            # quiesced the writers. An abandoning close gets the same
            # synchronous quiesce — its caller is about to restage.
            self._pump_stop = True
            if abandoned:
                self._native.abort()
                self._native.quiesce()
            else:
                self._native.shutdown()
            # The pump may exit before draining the readers' final EOF
            # completions, so _conn_finished never closes these: a
            # long-lived agent runs many migrations and must not strand
            # a severed socket per conn until GC.
            with self._cond:
                leftover = list(self._conn_by_id.values())
                self._conn_by_id.clear()
            for c in leftover:
                try:
                    c.close()
                except OSError:
                    pass
        if not _from_fail:
            with self._cond:
                # Shutdown-mid-accept race fix: sever lingering
                # connections on a plain close too, so Python-plane conn
                # workers parked in recv() exit now instead of holding a
                # dead session's sockets for the life of the process
                # (their late _fail no-ops: _error is already set, and
                # the emit helper is once-only either way).
                if abandoned:
                    for c in self._conn_socks:
                        try:
                            c.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                for fd in self._fds.values():
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                self._fds.clear()


def read_wire_endpoint(work_dir: str, wait_s: float = 0.0) -> str | None:
    """The destination-published wire endpoint for this checkpoint, polling
    up to ``wait_s`` for it to appear. None → no receiver is listening
    (the caller falls back to the PVC path, loudly)."""
    from grit_tpu.metadata import WIRE_ENDPOINT_FILE  # noqa: PLC0415

    path = os.path.join(work_dir, WIRE_ENDPOINT_FILE)
    deadline = time.monotonic() + wait_s
    while True:
        try:
            with open(path) as f:
                endpoint = json.load(f).get("endpoint")
            if endpoint:
                return str(endpoint)
        except (OSError, ValueError):
            pass
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.05)


def create_sentinel_file(dir_path: str) -> str:
    """Drop ``download-state`` marking staged data complete (reference
    copy.go:92-102). Atomic tmp+fsync+rename: the interceptor's poll
    keys on existence, so the sentinel must never exist before its
    bytes are durable."""
    from grit_tpu.metadata import atomic_write_text  # noqa: PLC0415

    os.makedirs(dir_path, exist_ok=True)
    path = os.path.join(dir_path, DOWNLOAD_STATE_FILE)
    atomic_write_text(path, "ok")
    return path
