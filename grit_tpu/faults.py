"""Fault-injection registry: named fault points threaded through the stack.

The migration path's robustness claims (abort→resume-source, bounded
retries, loud fallbacks) are unverifiable without a way to make each leg
fail on demand — the role CRIU's own ZDTM error-injection plays for the
reference's checkpoint engine. Every load-bearing seam in grit-tpu carries
a *named fault point*; the chaos suite (``tests/test_faults.py``,
``make test-chaos``) arms them one at a time and asserts the documented
detection + recovery (``docs/failure-modes.md``).

Syntax (env ``GRIT_FAULT_POINTS``, or the ``grit.dev/fault-points``
Checkpoint annotation, which the manager propagates into both agent Jobs
exactly like ``grit.dev/migration-path``)::

    GRIT_FAULT_POINTS=<spec>[,<spec>...]
    spec = <point>:<mode>[:<arg>][:xN]

    modes:
      raise            raise FaultInjected at the point
      delay[:secs]     sleep secs (default 0.1) then continue
      hang[:secs]      sleep secs (default 3600) — simulates a wedged leg
      kill[:code]      os._exit(code) (default 137) — simulates the agent
                       process being SIGKILLed mid-flight (no error-path
                       cleanup runs; only safe in a subprocess agent)
      truncate[:n]     at fault_write() sites: pass only the first n bytes
                       (default 0) through — a torn write
    xN                 arm for the first N hits only (default: every hit)

Example: ``GRIT_FAULT_POINTS=wire.send:raise:x1,device.snapshot.dump:delay:0.5``.

Points are cheap when unarmed: one cached env lookup per call. The parse
cache is keyed on the raw env string, so tests flipping the env between
calls need no explicit reset (``reset()`` clears hit counters too).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from grit_tpu.api import config

FAULT_POINTS_ENV = config.FAULT_POINTS.name

#: Canonical registry of every fault point wired into the tree, grouped by
#: layer. tests/test_faults.py asserts each name appears at a real call
#: site, so this list cannot drift from the code.
KNOWN_POINTS = (
    # agent: checkpoint driver
    "agent.checkpoint.predump",
    "precopy.round",
    # agent: preemption-armed standby (grit_tpu.agent.standby)
    "standby.round",
    "standby.governor",
    "standby.fire",
    "agent.checkpoint.dump",
    "agent.checkpoint.upload",
    "agent.checkpoint.wire_send",
    "agent.checkpoint.commit",
    # gang slice migration (parallel/coordination.py quiesce barrier +
    # agent/slicerole.py gang ledger)
    "slice.barrier",
    "slice.commit",
    "slice.abort",
    # agent: restore driver
    "agent.restore.prestage",
    "agent.restore.stage",
    "agent.restore.stream",
    "agent.restore.wire_wait",
    # agent: data mover / wire transport
    "agent.copy.transfer",
    "agent.copy.chunk_write",
    "wire.send",
    "wire.recv",
    "wire.commit",
    # codec stage (snapshot-transport compression, grit_tpu.codec)
    "codec.compress",
    "codec.decompress",
    # native file data plane (gritio-file): io.drain fires at the dump
    # mirror's native-drain creation seam (raise = this dump's tee runs
    # the Python plane, loudly — the degrade ladder under chaos);
    # io.place fires per native container/batched-raw read (raise = that
    # read degrades to the Python decode path, loudly; the restore stays
    # bit-identical either way).
    "io.drain",
    "io.place",
    # device layer: snap.speculate fires at the start of every
    # speculative (quiesce-free) snapshot pass — the clone + concurrent
    # dump that overlaps execution; raise = this round degrades loudly
    # to the parked dump, bit-identical (the validated-speculation
    # degrade ladder).
    "snap.speculate",
    "device.snapshot.dump",
    "device.snapshot.place",
    "restore.postcopy_fault",
    "device.snapshot.mirror",
    "device.agentlet.quiesce",
    "device.agentlet.dump",
    "device.agentlet.resume",
    # CRIU adapter
    "cri.criu.dump",
    "cri.criu.restore",
    # manager control plane
    "manager.checkpoint.reconcile",
    "manager.restore.reconcile",
    # fleet migration scheduler (manager/fleet/plan_controller.py):
    # fleet.place fires per destination-candidate probe (raise = that
    # destination rejects placement this pass), fleet.budget at each
    # admission decision (raise = admission deferred, member stays
    # queued), fleet.wave at the top of every wave reconcile (raise =
    # workqueue error path — the wave resumes on the retry).
    "fleet.place",
    "fleet.budget",
    "fleet.wave",
    # serving snapshot fan-out (grit_tpu.serving + restoreset
    # controller): serve.drain fires at the serving agentlet's
    # request-drain seam (raise = the drain — and with it the quiesce
    # attempt — fails; the engine keeps serving), serve.verify at the
    # RestoreSet template-verify seam (raise = workqueue error path,
    # the verify retries level-triggered), serve.clone per clone
    # Restore creation (raise = only that clone's creation is skipped
    # this pass; siblings fan out and the clone retries next reconcile).
    "serve.drain",
    "serve.verify",
    "serve.clone",
)

_MODES = ("raise", "delay", "hang", "kill", "truncate")


class FaultInjected(RuntimeError):
    """An armed fault point fired. Deliberately a plain RuntimeError
    subclass: injected faults must travel the same error paths real
    failures do (classification, journal poisoning, error-path resume)."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


@dataclass
class FaultSpec:
    point: str
    mode: str
    arg: float | None = None
    max_hits: int | None = None  # None = every hit


class FaultSyntaxError(ValueError):
    """Malformed GRIT_FAULT_POINTS value. Raised at parse time so an
    operator typo fails the agent loudly instead of silently disarming
    the chaos run it was meant to drive."""


def parse_fault_points(raw: str) -> dict[str, FaultSpec]:
    """``spec[,spec...]`` → {point: FaultSpec}. Empty/blank → {}."""
    specs: dict[str, FaultSpec] = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if len(parts) < 2:
            raise FaultSyntaxError(
                f"fault spec {item!r}: want <point>:<mode>[:<arg>][:xN]")
        point, mode, rest = parts[0], parts[1], parts[2:]
        if mode not in _MODES:
            raise FaultSyntaxError(
                f"fault spec {item!r}: unknown mode {mode!r} "
                f"(known: {', '.join(_MODES)})")
        arg: float | None = None
        max_hits: int | None = None
        for extra in rest:
            if extra.startswith("x") and extra[1:].isdigit():
                max_hits = int(extra[1:])
            else:
                try:
                    arg = float(extra)
                except ValueError as exc:
                    raise FaultSyntaxError(
                        f"fault spec {item!r}: bad arg {extra!r}") from exc
        specs[point] = FaultSpec(point=point, mode=mode, arg=arg,
                                 max_hits=max_hits)
    return specs


def validate_fault_points(raw: str) -> dict[str, FaultSpec]:
    """Strict parse for operator-facing entry points (the agent CLI):
    syntax AND point names are checked against :data:`KNOWN_POINTS`, so a
    misspelled point fails the Job terminally instead of silently
    disarming the chaos run it was meant to drive. (The lazy in-process
    parse stays name-agnostic — tests arm synthetic points freely.)"""
    specs = parse_fault_points(raw)
    unknown = sorted(p for p in specs if p not in KNOWN_POINTS)
    if unknown:
        raise FaultSyntaxError(
            f"unknown fault point(s) {', '.join(unknown)} — see "
            "grit_tpu.faults.KNOWN_POINTS / docs/failure-modes.md")
    return specs


_lock = threading.Lock()
_cache_raw: str | None = None
_cache_specs: dict[str, FaultSpec] = {}
_hits: dict[str, int] = {}


def _active() -> dict[str, FaultSpec]:
    global _cache_raw, _cache_specs
    raw = config.FAULT_POINTS.get()
    with _lock:
        if raw != _cache_raw:
            _cache_specs = parse_fault_points(raw)
            _cache_raw = raw
            _hits.clear()
        return _cache_specs


def reset() -> None:
    """Forget parse cache and hit counters (tests)."""
    global _cache_raw, _cache_specs
    with _lock:
        _cache_raw = None
        _cache_specs = {}
        _hits.clear()


def _take_hit(spec: FaultSpec) -> bool:
    """Count a hit; True if the point should fire this time."""
    with _lock:
        n = _hits.get(spec.point, 0) + 1
        _hits[spec.point] = n
    return spec.max_hits is None or n <= spec.max_hits


def hits(point: str) -> int:
    with _lock:
        return _hits.get(point, 0)


def fault_point(point: str, wrap: type[BaseException] | None = None) -> None:
    """Fire ``point`` if armed. No-op (one env read) otherwise.

    ``wrap`` names the exception type an injected ``raise`` travels as —
    sites whose callers classify by type (the wire transport's WireError
    fallback protocol) pass it so the injected failure takes the same
    recovery path a real one would; the original FaultInjected rides
    along as ``__cause__``.

    ``truncate`` at a non-write site degrades to ``raise``: a spec asking
    for a torn write where no write happens still makes the leg fail,
    which is the intent of arming it at all.
    """
    spec = _active().get(point)
    if spec is None or not _take_hit(spec):
        return
    if spec.mode == "delay":
        time.sleep(spec.arg if spec.arg is not None else 0.1)
    elif spec.mode == "hang":
        time.sleep(spec.arg if spec.arg is not None else 3600.0)
    elif spec.mode == "kill":
        os._exit(int(spec.arg) if spec.arg is not None else 137)
    else:  # raise, or truncate-at-non-write-site
        injected = FaultInjected(point)
        if wrap is not None:
            raise wrap(str(injected)) from injected
        raise injected


def fault_write(point: str, data: bytes) -> bytes:
    """Write-site variant: ``truncate`` returns a clipped buffer (a torn
    write the integrity machinery must catch); every other mode behaves
    like :func:`fault_point`. Returns the (possibly clipped) data."""
    spec = _active().get(point)
    if spec is None:
        return data
    if spec.mode == "truncate":
        if not _take_hit(spec):
            return data
        n = int(spec.arg) if spec.arg is not None else 0
        return data[:n]
    fault_point(point)
    return data
