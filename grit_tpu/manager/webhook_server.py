"""AdmissionReview HTTPS server — the wire form of the admission webhooks.

Parity: reference ``cmd/grit-manager/app/manager.go:124-155`` (TLS webhook
server whose certificate is re-read from the webhook Secret so renewals by
the cert controller take effect without a restart) + the four webhook
endpoints the chart registers (``deploy/charts/grit-tpu/templates/
webhooks.yaml``: /mutate-pod, /mutate-restore, /validate-checkpoint,
/validate-restore).

The admission *logic* lives in :mod:`grit_tpu.manager.webhooks` and is
transport-agnostic (hooks mutate typed objects / raise AdmissionDenied);
this module is the envelope: decode AdmissionReview v1 → typed object →
run the hooks registered on the cluster handle → respond with a base64
JSONPatch (mutating) or allowed/denied (validating).
"""

from __future__ import annotations

import base64
import json
import ssl
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from grit_tpu.kube.cluster import AdmissionDenied
from grit_tpu.kube.codec import kind_info
from grit_tpu.manager.secret_controller import (
    CA_CERT,
    SERVER_CERT,
    SERVER_KEY,
    WEBHOOK_SECRET_NAME,
    WEBHOOK_SECRET_NAMESPACE,
)

# endpoint path → (typed kind, phase) ; mirrors the chart's webhook configs
ROUTES: dict[str, tuple[str, str]] = {
    "/mutate-pod": ("Pod", "mutating"),
    "/mutate-restore": ("Restore", "mutating"),
    "/validate-checkpoint": ("Checkpoint", "validating"),
    "/validate-restore": ("Restore", "validating"),
    "/validate-migrationplan": ("MigrationPlan", "validating"),
    "/validate-restoreset": ("RestoreSet", "validating"),
}


# -- JSON Patch (RFC 6902) ----------------------------------------------------


def _ptr(segments: list[str]) -> str:
    return "/" + "/".join(
        s.replace("~", "~0").replace("/", "~1") for s in segments
    )


def json_patch_diff(before: Any, after: Any, path: list[str] | None = None) -> list[dict]:
    """Minimal RFC 6902 diff: dicts recurse, everything else replaces."""
    path = path or []
    if isinstance(before, dict) and isinstance(after, dict):
        ops: list[dict] = []
        for k in before:
            if k not in after:
                ops.append({"op": "remove", "path": _ptr(path + [k])})
        for k, v in after.items():
            if k not in before:
                ops.append({"op": "add", "path": _ptr(path + [k]), "value": v})
            elif before[k] != v:
                ops.extend(json_patch_diff(before[k], v, path + [k]))
        return ops
    if before != after:
        return [{"op": "replace", "path": _ptr(path), "value": after}]
    return []


def json_patch_apply(
    doc: Any, patch: list[dict], *, create_missing: bool = False
) -> Any:
    """Apply the subset of RFC 6902 that json_patch_diff emits (used by the
    fake apiserver; a real apiserver applies patches itself).

    ``create_missing`` creates absent dict parents along op paths — used when
    replaying hook mutations onto the wire object, which may lack containers
    (e.g. no ``metadata.annotations`` yet) that the normalized encoding
    always materializes."""
    doc = json.loads(json.dumps(doc))
    for op in patch:
        segments = [
            s.replace("~1", "/").replace("~0", "~")
            for s in op["path"].split("/")[1:]
        ]
        parent = doc
        for s in segments[:-1]:
            if isinstance(parent, list):
                parent = parent[int(s)]
            elif create_missing:
                parent = parent.setdefault(s, {})
            else:
                parent = parent[s]
        last = segments[-1]
        if op["op"] == "remove":
            if isinstance(parent, list):
                parent.pop(int(last))
            else:
                parent.pop(last, None)
        else:  # add | replace
            if isinstance(parent, list):
                idx = len(parent) if last == "-" else int(last)
                if op["op"] == "add":
                    parent.insert(idx, op["value"])
                else:
                    parent[idx] = op["value"]
            else:
                parent[last] = op["value"]
    return doc


def _segments(path: str) -> list[str]:
    return [
        s.replace("~1", "/").replace("~0", "~") for s in path.split("/")[1:]
    ]


def _lookup(doc: Any, path: str) -> tuple[bool, Any]:
    cur = doc
    for s in _segments(path):
        if isinstance(cur, list):
            i = int(s)
            if i >= len(cur):
                return False, None
            cur = cur[i]
        elif isinstance(cur, dict) and s in cur:
            cur = cur[s]
        else:
            return False, None
    return True, cur


def lossy_list_ops(ops: list[dict], before_norm: Any, before_wire: Any) -> list[str]:
    """Paths of ops that would ship a list rebuilt from the lossy typed
    encoding. ``json_patch_diff`` recurses dicts but replaces lists
    wholesale — if the normalized list differs from the wire list *before*
    the hook ran, the replacement would silently strip unmodeled fields
    (e.g. container resources/probes). Such a patch must fail loudly, never
    be applied."""
    bad = []
    for op in ops:
        found_n, val_n = _lookup(before_norm, op["path"])
        touches_list = isinstance(val_n, list) or isinstance(
            op.get("value"), list
        )
        if not touches_list:
            continue
        found_w, val_w = _lookup(before_wire, op["path"])
        if found_w:
            if val_w != val_n:
                bad.append(op["path"])
        elif found_n and val_n != []:
            # norm materialized list content the wire never had
            bad.append(op["path"])
    return bad


# -- server -------------------------------------------------------------------


class WebhookServer:
    """Serve the AdmissionReview endpoints over TLS (or plain HTTP in tests).

    ``cluster`` must expose ``mutating_hooks`` / ``validating_hooks`` (the
    dicts :class:`grit_tpu.kube.client.KubeCluster` records) — hooks are
    invoked as ``hook(cluster, typed_obj)`` exactly as the in-memory cluster
    invokes them, so one webhook implementation serves both transports.
    """

    def __init__(
        self,
        cluster,
        port: int = 10350,
        host: str = "0.0.0.0",
        *,
        tls: bool = True,
        cert_refresh_seconds: float = 300.0,
        handshake_timeout: float = 10.0,
    ) -> None:
        self.cluster = cluster
        self.tls = tls
        self.cert_refresh_seconds = cert_refresh_seconds
        self.handshake_timeout = handshake_timeout
        self._cert_lock = threading.Lock()
        self._cert_loaded_at = 0.0
        self._cert_rv = -1
        self._ctx: ssl.SSLContext | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                return

            def do_POST(self):  # noqa: N802
                route = ROUTES.get(self.path.partition("?")[0])
                if route is None:
                    return self._send(404, {"message": "unknown webhook path"})
                n = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(n))
                    response = outer.review(review, *route)
                except Exception as exc:  # noqa: BLE001 - malformed review
                    return self._send(400, {"message": f"bad review: {exc}"})
                return self._send(200, response)

            def _send(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class Server(ThreadingHTTPServer):
            # TLS handshake runs here, in the per-connection thread spawned by
            # ThreadingMixIn — never on the accept loop. A stalled client can
            # only block its own thread (advisor r2: a handshake in accept()
            # would stall all admission requests, and the CR webhooks are
            # fail-closed, wedging CR creation cluster-wide).
            def process_request_thread(self, request, client_address):
                if outer.tls:
                    try:
                        request.settimeout(outer.handshake_timeout)
                        outer._refresh_certs()
                        assert outer._ctx is not None
                        request = outer._ctx.wrap_socket(request, server_side=True)
                        request.settimeout(None)
                    except Exception:  # noqa: BLE001 - bad/stalled client
                        self.shutdown_request(request)
                        return
                super().process_request_thread(request, client_address)

        self._srv = Server((host, port), Handler)
        if tls:
            self._refresh_certs(force=True)
        threading.Thread(
            target=self._srv.serve_forever, name="grit-webhooks", daemon=True
        ).start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def shutdown(self) -> None:
        self._srv.shutdown()

    # -- TLS ----------------------------------------------------------------

    def _refresh_certs(self, force: bool = False) -> None:
        """Re-read the webhook Secret so cert-controller renewals take effect
        without a restart (reference GetCertificate closure,
        app/manager.go:124-155). Called from handler threads; serialized."""
        with self._cert_lock:
            now = time.monotonic()
            if not force and now - self._cert_loaded_at < self.cert_refresh_seconds:
                return
            secret = self.cluster.try_get(
                "Secret", WEBHOOK_SECRET_NAME, WEBHOOK_SECRET_NAMESPACE
            )
            if secret is None:
                if self._ctx is None:
                    raise RuntimeError(
                        f"webhook secret {WEBHOOK_SECRET_NAMESPACE}/"
                        f"{WEBHOOK_SECRET_NAME} not found (run the cert controller first)"
                    )
                return
            self._cert_loaded_at = now
            if secret.metadata.resource_version == self._cert_rv:
                return
            self._cert_rv = secret.metadata.resource_version
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.minimum_version = ssl.TLSVersion.TLSv1_3  # reference: TLS 1.3 only
            with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                    tempfile.NamedTemporaryFile(suffix=".pem") as kf:
                cf.write(secret.data[SERVER_CERT])
                cf.flush()
                kf.write(secret.data[SERVER_KEY])
                kf.flush()
                ctx.load_cert_chain(cf.name, kf.name)
            self._ctx = ctx

    def ca_bundle(self) -> bytes:
        secret = self.cluster.get(
            "Secret", WEBHOOK_SECRET_NAME, WEBHOOK_SECRET_NAMESPACE
        )
        return secret.data[CA_CERT]

    # -- admission ----------------------------------------------------------

    def review(self, review: dict, kind: str, phase: str) -> dict:
        req = review.get("request") or {}
        uid = req.get("uid", "")
        raw_obj = req.get("object") or {}
        raw_obj.setdefault("kind", kind)
        info = kind_info(kind)
        obj = info.decode(raw_obj)
        # Snapshot the normalized encoding BEFORE the hooks run: diffing
        # normalized-before vs normalized-after isolates exactly what the
        # hooks touched — encode() normalization artifacts appear identically
        # on both sides and cancel out (advisor r2: the old annotation/label
        # path allowlist silently dropped any other mutation).
        before_norm = info.encode(obj) if phase == "mutating" else None

        hooks = (
            self.cluster.mutating_hooks if phase == "mutating"
            else self.cluster.validating_hooks
        )
        try:
            for hook, fail_open in hooks.get(kind, []):
                try:
                    hook(self.cluster, obj)
                except AdmissionDenied:
                    if not fail_open:
                        raise
                except Exception:
                    if not fail_open:
                        raise
        except AdmissionDenied as exc:
            return _response(uid, allowed=False, message=str(exc))
        except Exception as exc:  # noqa: BLE001 - fail closed with a reason
            return _response(uid, allowed=False, message=f"webhook error: {exc}")

        if phase == "mutating":
            after_norm = info.encode(obj)
            assert before_norm is not None
            before_norm.pop("status", None)  # admission cannot set status
            after_norm.pop("status", None)
            hook_ops = json_patch_diff(before_norm, after_norm)
            if hook_ops:
                # Replay the hook's changes onto what the apiserver actually
                # sent, then diff against it — so add-vs-replace semantics
                # match the wire object, not our normalized encoding.
                before_wire = json.loads(json.dumps(raw_obj))
                before_wire.pop("status", None)
                bad = lossy_list_ops(hook_ops, before_norm, before_wire)
                if bad:
                    return _response(
                        uid, allowed=False,
                        message=(
                            "mutating hook touched list field(s) the typed "
                            f"codec models lossily for this object: {bad}; "
                            "refusing to emit a patch that would strip "
                            "unmodeled fields"
                        ),
                    )
                after_wire = json_patch_apply(
                    before_wire, hook_ops, create_missing=True
                )
                patch = json_patch_diff(before_wire, after_wire)
                if patch:
                    return _response(uid, allowed=True, patch=patch)
        return _response(uid, allowed=True)


def _response(
    uid: str, *, allowed: bool, message: str = "", patch: list[dict] | None = None
) -> dict:
    resp: dict = {"uid": uid, "allowed": allowed}
    if message:
        resp["status"] = {"message": message}
    if patch is not None:
        resp["patchType"] = "JSONPatch"
        resp["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": resp,
    }
