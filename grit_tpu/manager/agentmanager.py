"""Agent-Job factory: renders the node-side grit-agent Job for a Checkpoint
or Restore CR.

Parity: reference ``pkg/gritmanager/agentmanager/manager.go:55-172`` and the
Job template ConfigMap (``charts/grit-manager/templates/grit-agent-config.yaml``).
The reference keeps the agent's *entire pod spec* as operator-configurable
data in ConfigMap ``grit-agent-config`` (keys ``host-path`` +
``grit-agent-template.yaml``); we keep the same ConfigMap contract with
structured keys (host-path, agent-image, pvc-mount-path) and build the Job
programmatically — same knobs, minus fragile text templating.

Layout contracts preserved exactly:
- host work dir:  ``<host-path>/<namespace>/<checkpoint-name>``  (manager.go:93)
- PVC mount:      ``/mnt/pvc-data/``                             (manager.go:30)
- args: ``--action checkpoint|restore --src-dir --dst-dir --host-work-path``
  with src/dst flipped for restore                               (manager.go:119-138)
- env: ``TARGET_NAMESPACE/TARGET_NAME/TARGET_UID``               (manager.go:140-144)
- Job name ``grit-agent-<cr-name>``, label ``grit.dev/helper=grit-agent``,
  ``nodeName`` pinned to the target node, hostNetwork, containerd socket and
  kubelet log dir mounted (grit-agent-config.yaml).
"""

from __future__ import annotations

import posixpath
from dataclasses import dataclass

from grit_tpu.api import config
from grit_tpu.api.constants import (
    GRIT_AGENT_ACTION_LABEL,
    GRIT_AGENT_LABEL,
    GRIT_AGENT_NAME,
)
from grit_tpu.kube.cluster import Cluster, NotFound
from grit_tpu.kube.objects import (
    Container,
    EnvVar,
    Job,
    JobSpec,
    ObjectMeta,
    OwnerReference,
    PodSpec,
    PodTemplateSpec,
    Volume,
    VolumeMount,
)
from grit_tpu.manager.util import agent_job_name, slice_agent_job_name

AGENT_CONFIGMAP_NAME = "grit-agent-config"
AGENT_CONFIG_NAMESPACE = "grit-system"
PVC_MOUNT_PATH = "/mnt/pvc-data"
DEFAULT_HOST_PATH = "/var/lib/grit"
CONTAINERD_SOCK = "/run/containerd/containerd.sock"
KUBELET_POD_LOG_DIR = "/var/log/pods"


@dataclass
class AgentJobParams:
    cr_name: str
    namespace: str
    action: str  # "checkpoint" | "restore" | "cleanup" | "abort"
    node_name: str
    pvc_claim_name: str | None
    target_pod_name: str
    target_pod_uid: str
    owner: OwnerReference | None = None
    pre_copy: bool = False  # checkpoint action only
    # Preemption-armed standby (checkpoint action only): the Job stays
    # resident keeping the destination base warm until the grit.dev/fire
    # annotation (stamped on this Job by the controller) fires it.
    standby: bool = False
    traceparent: str = ""   # W3C context: the migration's one trace
    # "pvc" | "wire" | "" (unset): the Checkpoint CR's migration-path
    # annotation, propagated into BOTH agent jobs so source and
    # destination agree on the data path (wire needs the restore agent
    # listening while the checkpoint agent dumps).
    migration_path: str = ""
    # GRIT_FAULT_POINTS spec from the CR's grit.dev/fault-points
    # annotation (grit_tpu/faults.py) — propagated into the agent Job
    # env exactly like the migration path, so chaos runs can arm faults
    # in a specific migration's node legs from the control plane.
    fault_points: str = ""
    # Manager clock pair (JSON) from the CR's grit.dev/flight-clock
    # annotation: enables flight recording in the agent Job and anchors
    # gritscope's cross-process clock alignment (obs/flight.py).
    flight_clock: str = ""
    # Gang slice migration: this Job is host `slice_ordinal` of a
    # `slice_hosts`-host gang. The Job is named with the per-host
    # suffix (grit-agent-<cr>-h<k> — its OWN heartbeat lease), and the
    # slice identity + attempt nonce are stamped into its env so the
    # agent leg runs the gang protocol (GangLedger, cross-host quiesce
    # barrier). slice_hosts <= 1 renders the classic single-host Job
    # byte-identically.
    slice_hosts: int = 0
    slice_ordinal: int = 0
    slice_nonce: str = ""
    # Fleet byte shaping (checkpoint action only): the MigrationPlan
    # controller's per-member share of its link budget, actuated as
    # GRIT_MIRROR_MAX_INFLIGHT_MB in the agent env — bounding in-flight
    # mirror/wire bytes bounds the member's sustained rate. 0 = leave
    # the agent's default (unshaped).
    max_inflight_mb: int = 0
    # RestoreSet fan-out (restore action only): this leg's clone
    # ordinal from the Restore CR's grit.dev/clone-ordinal annotation,
    # stamped as GRIT_CLONE_ORDINAL so the agent's progress snapshots
    # carry "clone" — every clone derives the SAME uid from the shared
    # snapshot name, and the ordinal is what lets `gritscope watch
    # --restoreset` key live per-clone files apart. -1 = not a clone.
    clone_ordinal: int = -1


class AgentManager:
    """Factory reading cluster config from the grit-agent ConfigMap."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    def _config(self) -> dict[str, str]:
        try:
            cm = self.cluster.get("ConfigMap", AGENT_CONFIGMAP_NAME, AGENT_CONFIG_NAMESPACE)
            return dict(cm.data)
        except NotFound:
            return {}

    def host_path(self) -> str:
        """reference manager.go:47-53 (GetHostPath)."""

        return self._config().get("host-path", DEFAULT_HOST_PATH)

    def host_work_path(self, namespace: str, cr_name: str) -> str:
        """``<host-path>/<ns>/<name>`` — reference manager.go:93."""

        return posixpath.join(self.host_path(), namespace, cr_name)

    @staticmethod
    def _work_path(host_path: str, namespace: str, cr_name: str) -> str:
        return posixpath.join(host_path, namespace, cr_name)

    def pvc_data_path(self, namespace: str, cr_name: str) -> str:
        """Path of this CR's data inside the PVC mount."""

        return posixpath.join(PVC_MOUNT_PATH, namespace, cr_name)

    def generate_agent_job(self, p: AgentJobParams) -> Job:
        """reference GenerateGritAgentJob manager.go:55-146."""

        cfg = self._config()  # single ConfigMap read for the whole render
        image = cfg.get("agent-image", "grit-tpu/agent:latest")
        host_path = cfg.get("host-path", DEFAULT_HOST_PATH)
        host_work = self._work_path(host_path, p.namespace, p.cr_name)
        pvc_dir = self.pvc_data_path(p.namespace, p.cr_name)
        gang = p.slice_hosts > 1
        job_name = (slice_agent_job_name(p.cr_name, p.slice_ordinal)
                    if gang else agent_job_name(p.cr_name))
        if gang and p.action in ("checkpoint", "restore"):
            # Per-host payload subdir: N hosts' container trees must
            # never collide in one PVC dir. The gang ledger stays at the
            # SHARED root (the agent strips the suffix —
            # slicerole.gang_shared_dir); abort/cleanup Jobs keep the
            # root, which is exactly where the abort's ledger write and
            # the cleanup's whole-tree delete want to be.
            pvc_dir = posixpath.join(pvc_dir,
                                     f"host-{p.slice_ordinal:04d}")

        if p.action in ("checkpoint", "cleanup", "abort"):
            # cleanup deletes both paths; abort resumes the source and
            # clears its partial dump — same orientation as checkpoint.
            src_dir, dst_dir = host_work, pvc_dir
        else:  # restore: direction flipped (manager.go:119-138)
            src_dir, dst_dir = pvc_dir, host_work

        args = [
            "--action", p.action,
            "--src-dir", src_dir,
            "--dst-dir", dst_dir,
            "--host-work-path", host_work,
        ]
        if p.action == "checkpoint" and p.pre_copy:
            args.append("--pre-copy")
        if p.action == "checkpoint" and p.standby:
            args.append("--standby")
        if p.migration_path and p.action in ("checkpoint", "restore"):
            args += ["--migration-path", p.migration_path]
        env = [
            EnvVar("TARGET_NAMESPACE", p.namespace),
            EnvVar("TARGET_NAME", p.target_pod_name),
            EnvVar("TARGET_UID", p.target_pod_uid),
            # Own coordinates, for the heartbeat lease (agent/lease.py):
            # the agent patches grit.dev/heartbeat onto this very Job —
            # per-host slice Jobs each lease their own name, which is
            # what makes the gang's leases per-host for free.
            EnvVar(config.JOB_NAME.name, job_name),
            EnvVar(config.JOB_NAMESPACE.name, p.namespace),
        ]
        if gang:
            env.append(EnvVar(config.SLICE_HOSTS.name, str(p.slice_hosts)))
            env.append(EnvVar(config.SLICE_ORDINAL.name,
                              str(p.slice_ordinal)))
            if p.slice_nonce:
                env.append(EnvVar(config.SLICE_NONCE.name, p.slice_nonce))
        if p.migration_path and p.action in ("checkpoint", "restore"):
            env.append(EnvVar(config.MIGRATION_PATH.name, p.migration_path))
        if p.max_inflight_mb > 0 and p.action == "checkpoint":
            env.append(EnvVar(config.MIRROR_MAX_INFLIGHT_MB.name,
                              str(p.max_inflight_mb)))
        if p.clone_ordinal >= 0 and p.action == "restore":
            env.append(EnvVar(config.CLONE_ORDINAL.name,
                              str(p.clone_ordinal)))
        if p.fault_points and p.action in ("checkpoint", "restore", "abort"):
            env.append(EnvVar(config.FAULT_POINTS.name, p.fault_points))
        if p.traceparent:
            # W3C env convention: the agent's spans join the migration's
            # trace (grit_tpu/obs/trace.py propagation contract).
            env.append(EnvVar("TRACEPARENT", p.traceparent))
        if p.flight_clock:
            # Flight recording is on for this migration: the agent Job
            # records its work/stage-dir flight log, and the manager's
            # clock pair rides along for cross-process alignment.
            env.append(EnvVar(config.FLIGHT.name, "1"))
            env.append(EnvVar(config.FLIGHT_CLOCK.name, p.flight_clock))
        volumes = [
            Volume(name="host-work", host_path=host_path),
            Volume(name="containerd-sock", host_path=CONTAINERD_SOCK),
            Volume(name="pod-logs", host_path=KUBELET_POD_LOG_DIR),
        ]
        mounts = [
            VolumeMount(name="host-work", mount_path=host_path),
            VolumeMount(name="containerd-sock", mount_path=CONTAINERD_SOCK),
            VolumeMount(name="pod-logs", mount_path=KUBELET_POD_LOG_DIR),
        ]
        if p.pvc_claim_name:
            volumes.append(Volume(name="pvc-data", pvc_claim_name=p.pvc_claim_name))
            mounts.append(VolumeMount(name="pvc-data", mount_path=PVC_MOUNT_PATH))

        meta = ObjectMeta(
            name=job_name,
            namespace=p.namespace,
            labels={GRIT_AGENT_LABEL: GRIT_AGENT_NAME,
                    GRIT_AGENT_ACTION_LABEL: p.action},
        )
        if p.owner:
            meta.owner_references.append(p.owner)

        return Job(
            metadata=meta,
            spec=JobSpec(
                backoff_limit=3,  # charts grit-agent-config.yaml
                template=PodTemplateSpec(
                    metadata=ObjectMeta(labels={GRIT_AGENT_LABEL: GRIT_AGENT_NAME}),
                    spec=PodSpec(
                        containers=[
                            Container(
                                name="grit-agent",
                                image=image,
                                command=["grit-agent"],
                                args=args,
                                env=env,
                                volume_mounts=mounts,
                            )
                        ],
                        volumes=volumes,
                        node_name=p.node_name,  # pinned — kubelet-only scheduling
                        host_network=True,
                        restart_policy="Never",
                    ),
                ),
            ),
        )
