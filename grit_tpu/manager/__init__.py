"""grit-manager: the control plane.

Behavioral parity with reference ``pkg/gritmanager/`` — controllers
(checkpoint, restore, secret/cert), admission webhooks (pod, checkpoint,
restore), and the agent-Job factory — assembled by
:func:`grit_tpu.manager.manager.build_manager`.
"""

from grit_tpu.manager.manager import build_manager  # noqa: F401
