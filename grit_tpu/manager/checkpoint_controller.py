"""Checkpoint controller: phase state machine driving pod checkpointing.

Parity: reference ``pkg/gritmanager/controllers/checkpoint/
checkpoint_controller.go`` — phases Created→Pending→Checkpointing→
Checkpointed→Submitting→Submitted/Failed dispatched from a phase→handler map
(:61-67), agent-Job creation on the target node, Job-completion watch,
auto-migration (Restore creation + source pod deletion).
"""

from __future__ import annotations

from collections.abc import Callable

from grit_tpu.obs.metrics import (
    AGENT_JOB_RETRIES,
    MIGRATION_ABORTS,
    PHASE_TRANSITIONS,
    STANDBY_FIRES,
)
from grit_tpu.api.constants import (
    FAULT_POINTS_ANNOTATION,
    FIRE_ANNOTATION,
    GRIT_AGENT_LABEL,
    GRIT_AGENT_NAME,
    MAX_INFLIGHT_MB_ANNOTATION,
    MIGRATION_PATH_ANNOTATION,
    RETRY_AT_ANNOTATION,
)
from grit_tpu import faults
from grit_tpu.manager import watchdog
from grit_tpu.api.types import (
    Checkpoint,
    CheckpointPhase,
    Restore,
    RestorePhase,
    RestoreSpec,
)
from grit_tpu.api.constants import GRIT_AGENT_ACTION_LABEL


def _job_action(job) -> str:
    """The agent Job's purpose, from its action label (empty for jobs
    predating the label — treated as the legacy checkpoint/restore kind
    by callers that only need to exclude 'cleanup')."""
    return job.metadata.labels.get(GRIT_AGENT_ACTION_LABEL, "")


def _max_inflight_mb(ckpt) -> int:
    """The fleet scheduler's byte-shaping share (grit.dev/max-inflight-mb,
    stamped by the plan controller at member admission), forwarded into
    the agent Job as GRIT_MIRROR_MAX_INFLIGHT_MB. 0 = unshaped."""
    raw = ckpt.metadata.annotations.get(MAX_INFLIGHT_MB_ANNOTATION, "")
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 0
from grit_tpu.kube.cluster import AlreadyExists, Cluster, NotFound
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import ObjectMeta, OwnerReference
from grit_tpu.manager.agentmanager import AgentJobParams, AgentManager
from grit_tpu.manager.util import (
    agent_job_name,
    compute_pod_spec_hash,
    cr_candidates_from_agent_job,
    migration_flight_clock,
    migration_traceparent,
    resolve_last_checkpoint_phase,
    slice_agent_job_name,
    sync_progress_status,
    sync_slice_progress_status,
    update_condition,
)
from grit_tpu.obs import flight, trace


class CheckpointController:
    kind = "Checkpoint"

    def __init__(self, agent_manager: AgentManager) -> None:
        self.agent_manager = agent_manager
        self._handlers: dict[CheckpointPhase, Callable[[Cluster, Checkpoint], Result]] = {
            CheckpointPhase.CREATED: self._created,
            CheckpointPhase.PENDING: self._pending,
            CheckpointPhase.CHECKPOINTING: self._checkpointing,
            CheckpointPhase.STANDBY: self._standby,
            CheckpointPhase.FIRING: self._firing,
            CheckpointPhase.CHECKPOINTED: self._checkpointed,
            CheckpointPhase.SUBMITTING: self._submitting,
            CheckpointPhase.SUBMITTED: self._submitted,
            CheckpointPhase.FAILED: self._failed,
        }

    # -- watch wiring (reference Register :290-303) -----------------------------

    def register(self, cluster: Cluster, enqueue: Callable[[Request], None]) -> None:
        def on_job_event(ev) -> None:
            if ev.obj.metadata.labels.get(GRIT_AGENT_LABEL) != GRIT_AGENT_NAME:
                return
            # Both candidates: the raw mapping AND — for per-host slice
            # Jobs (grit-agent-<cr>-h<k>) — the slice CR. A no-op
            # reconcile of a non-CR name is cheap; missing a gang
            # member's completion is not.
            for cr in cr_candidates_from_agent_job(ev.name):
                enqueue(Request(ev.namespace, cr))

        cluster.watch("Job", on_job_event)

    # -- reconcile (reference :72-96) -------------------------------------------

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        # Chaos seam: an injected raise here exercises the workqueue's
        # error path (RECONCILE_ERRORS + requeue-with-backoff).
        faults.fault_point("manager.checkpoint.reconcile")
        ckpt = cluster.try_get("Checkpoint", req.name, req.namespace)
        if ckpt is None:
            return Result()
        phase = ckpt.status.phase or CheckpointPhase.CREATED
        parent = migration_traceparent(cluster, ckpt, "Checkpoint")
        with trace.span(f"manager.checkpoint.{phase.value}", parent=parent,
                        checkpoint=f"{req.namespace}/{req.name}"):
            return self._handlers[phase](cluster, ckpt)

    # -- phase transitions ------------------------------------------------------

    def _set_phase(
        self, cluster: Cluster, ckpt: Checkpoint, phase: CheckpointPhase,
        reason: str, message: str = "", **status_fields,
    ) -> None:
        def mutate(obj: Checkpoint) -> None:
            obj.status.phase = phase
            for k, v in status_fields.items():
                setattr(obj.status, k, v)
            update_condition(obj.status.conditions, phase.value, "True", reason, message)

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate, ckpt.metadata.namespace)
        PHASE_TRANSITIONS.inc(kind="Checkpoint", phase=phase.value)
        # Manager-side flight event: keyed to the CR name (the same uid
        # the agents derive from their work/stage dir basename), so
        # gritscope folds control-plane decisions into the timeline.
        flight.emit("manager.phase", uid=ckpt.metadata.name,
                    kind="Checkpoint", phase=phase.value, reason=reason)

    def _fail(self, cluster: Cluster, ckpt: Checkpoint, reason: str, message: str) -> Result:
        self._set_phase(cluster, ckpt, CheckpointPhase.FAILED, reason, message)
        return Result()

    # -- watchdog: leased phases, bounded retry, abort→resume-source ------------
    #
    # Detection (watchdog.py): Job Failed, stale heartbeat lease, or phase
    # deadline overrun. Retriable verdicts with attempts remaining stamp
    # grit.dev/attempt + grit.dev/retry-at and go FAILED; the _failed
    # handler re-creates the Job once the backoff elapses (or immediately
    # when an operator cleared the failed Job — the manual override).
    # Terminal/exhausted verdicts first drive the abort: an "Aborting"
    # condition records the cause, _drive_abort runs an --action abort
    # agent Job on the source node (agentlet unquiesce → the source pod
    # resumes training from live HBM state), tears down the migration's
    # restore leg, and only then parks the CR in FAILED — the invariant
    # that a failed migration never strands a quiesced source.

    ABORTING_CONDITION = "Aborting"

    @staticmethod
    def _aborting(ckpt: Checkpoint):
        for c in ckpt.status.conditions:
            if c.type == CheckpointController.ABORTING_CONDITION \
                    and c.status == "True":
                return c
        return None

    def _handle_leg_failure(
        self, cluster: Cluster, ckpt: Checkpoint, cause: str, message: str,
    ) -> Result:
        verdict = watchdog.classify_job_failure(
            self.agent_manager, ckpt.metadata.namespace, ckpt.metadata.name,
            cause, message)
        attempt = watchdog.attempt_count(ckpt.metadata)
        if verdict.retriable and attempt < watchdog.max_attempts():
            if cause in watchdog.OVERRUN_CAUSES:
                # The wedged Job is still Active — the retry replaces it,
                # so it goes now (a Failed job instead stays visible until
                # the _failed handler's backoff elapses).
                cluster.try_delete("Job", agent_job_name(ckpt.metadata.name),
                                   ckpt.metadata.namespace)
            delay = watchdog.schedule_retry(
                cluster, "Checkpoint", ckpt.metadata.name,
                ckpt.metadata.namespace, attempt)
            AGENT_JOB_RETRIES.inc(kind="Checkpoint", cause=verdict.cause)
            self._set_phase(
                cluster, ckpt, CheckpointPhase.FAILED, verdict.cause,
                f"{verdict.message} (attempt {attempt + 1}/"
                f"{watchdog.max_attempts()}, retry in {delay:.1f}s)")
            return Result(requeue_after=delay)
        return self._begin_abort(cluster, ckpt, verdict.cause,
                                 verdict.message)

    def _begin_abort(
        self, cluster: Cluster, ckpt: Checkpoint, cause: str, message: str,
    ) -> Result:
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        # The failed/wedged attempt's Job goes first: its name is reused
        # by the abort Job (keeping the Job-watch → CR mapping intact).
        cluster.try_delete("Job", agent_job_name(name), ns)

        def mutate(obj: Checkpoint) -> None:
            update_condition(obj.status.conditions, self.ABORTING_CONDITION,
                             "True", cause, message)

        cluster.patch("Checkpoint", name, mutate, ns)
        return Result(requeue=True)

    def _drive_abort(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        cond = self._aborting(ckpt)
        job = cluster.try_get("Job", agent_job_name(name), ns)
        if job is not None and _job_action(job) != "abort":
            cluster.try_delete("Job", agent_job_name(name), ns)
            return Result(requeue_after=0.2)
        if job is None:
            # Deliberately no fault-point propagation: the recovery arm
            # must be maximally reliable even mid-chaos-run.
            abort_job = self.agent_manager.generate_agent_job(AgentJobParams(
                cr_name=name,
                namespace=ns,
                action="abort",
                node_name=ckpt.status.node_name,
                pvc_claim_name=(ckpt.spec.volume_claim.claim_name
                                if ckpt.spec.volume_claim else None),
                target_pod_name=ckpt.spec.pod_name,
                target_pod_uid=ckpt.status.pod_uid,
                owner=OwnerReference(kind="Checkpoint", name=name,
                                     uid=ckpt.metadata.uid, controller=True),
                traceparent=ckpt.metadata.annotations.get(
                    trace.TRACEPARENT_ANNOTATION, ""),
                flight_clock=migration_flight_clock(
                    cluster, ckpt, "Checkpoint"),
            ))
            try:
                cluster.create(abort_job)
            except AlreadyExists:
                pass
            return Result()  # the Job watch re-enqueues on completion
        if not (job.status.complete() or job.status.is_failed()):
            return Result()
        aborted_ok = job.status.complete()
        # Tear down the migration's restore leg (an auto-migration may
        # have raced a Restore into existence) so nothing keeps staging
        # toward a destination this migration will never reach.
        restore_name = f"{name}-migration"
        cluster.try_delete("Job", agent_job_name(restore_name), ns)
        cluster.try_delete("Restore", restore_name, ns)
        cluster.try_delete("Job", agent_job_name(name), ns)
        MIGRATION_ABORTS.inc(driver="manager")
        parent = migration_traceparent(cluster, ckpt, "Checkpoint")
        if cond is not None and trace.enabled():
            trace.record_span(
                "migration_abort",
                int(cond.last_transition_time * 1e9),
                parent=parent,
                status="OK" if aborted_ok else "ERROR",
                checkpoint=f"{ns}/{name}",
                cause=cond.reason,
            )
        cause = cond.reason if cond is not None else "MigrationAborted"
        message = cond.message if cond is not None else ""
        flight.emit("manager.abort", uid=ckpt.metadata.name,
                    ok=aborted_ok, cause=cause)
        return self._fail(
            cluster, ckpt,
            "MigrationAborted" if aborted_ok else "AbortFailed",
            f"{cause}: {message} (source "
            + ("resumed" if aborted_ok else
               "resume FAILED — operator attention required") + ")",
        )

    # -- gang slice migration ----------------------------------------------------
    #
    # A slice CR (spec.slice_hosts > 1) runs one leased agent Job PER
    # HOST (grit-agent-<cr>-h<k>, each renewing its own heartbeat — the
    # per-host lease is PR 3's lease on the per-host Job), folds every
    # host's state into status.hosts[] and its progress annotation into
    # status.progress.hosts/hostPairs, and finishes all-or-nothing:
    # the CR is Checkpointed only when EVERY host's leg completed, and
    # ANY host's terminal verdict (Job failed, stale lease, progress
    # stall, phase overrun, AgentJobLost) drives the slice-level abort —
    # run_abort on EVERY source host (each abort Job also writes the
    # gang ledger's ABORT record, so parked destinations poison-and-
    # clear instead of ever un-parking), then terminal FAILED. There is
    # no per-host retry: a lone host cannot rejoin a slice whose peers
    # already cut (the barrier is one-shot per attempt), so the gang
    # outcome is the unit of retry and the abort's resume is the safe
    # state to retry FROM.

    @staticmethod
    def _is_slice(ckpt: Checkpoint) -> bool:
        return (ckpt.spec.slice_hosts or 0) > 1

    @staticmethod
    def _slice_pod_name(ckpt: Checkpoint, ordinal: int) -> str:
        # JobSet convention: host k's pod is "<prefix>-<k>".
        return f"{ckpt.spec.pod_name}-{ordinal}"

    def _slice_host_record(self, ckpt: Checkpoint, ordinal: int) -> dict:
        for rec in ckpt.status.hosts:
            if rec.get("ordinal") == ordinal:
                return rec
        return {}

    def _slice_jobs(self, cluster: Cluster, ckpt: Checkpoint) -> dict:
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        return {k: cluster.try_get("Job", slice_agent_job_name(name, k), ns)
                for k in range(ckpt.spec.slice_hosts)}

    def _set_slice_hosts(self, cluster: Cluster, ckpt: Checkpoint,
                         hosts: list[dict]) -> None:
        if ckpt.status.hosts == hosts:
            return

        def mutate(obj: Checkpoint) -> None:
            obj.status.hosts = hosts

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate,
                      ckpt.metadata.namespace)
        ckpt.status.hosts = hosts

    def _slice_created(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if ckpt.spec.auto_migration:
            # The managed restore fan-out (per-host Restore CRs bound to
            # per-host replacement pods) is the follow-up; the gang
            # restore path itself exists (slicerole.run_slice_restore —
            # prepared parking, gang commit) and the harness/CLI drive
            # it concurrently today, exactly like the wire path's
            # sequencing note in _pending.
            return self._fail(
                cluster, ckpt, "SliceAutoMigrationUnsupported",
                "autoMigration on a slice Checkpoint is not yet managed; "
                "drive the restore gang via the agent CLI "
                "(--slice-hosts/--slice-ordinal) or per-host Restores")
        hosts: list[dict] = []
        node0, uid0, hash0 = "", "", ""
        for k in range(ckpt.spec.slice_hosts):
            pod_name = self._slice_pod_name(ckpt, k)
            pod = cluster.try_get("Pod", pod_name, ckpt.metadata.namespace)
            if pod is None:
                return self._fail(
                    cluster, ckpt, "PodNotFound",
                    f"slice host {k}: pod {pod_name} not found")
            if pod.status.phase != "Running" or not pod.spec.node_name:
                return Result(requeue_after=1.0)
            hosts.append({"ordinal": k, "pod": pod_name,
                          "podUid": pod.metadata.uid,
                          "node": pod.spec.node_name,
                          "job": "", "state": "Pending", "reason": ""})
            if k == 0:
                node0 = pod.spec.node_name
                uid0 = pod.metadata.uid
                hash0 = compute_pod_spec_hash(pod.spec)
        self._set_phase(
            cluster, ckpt, CheckpointPhase.PENDING, "SlicePodsResolved",
            node_name=node0, pod_uid=uid0, pod_spec_hash=hash0,
            hosts=hosts)
        return Result()

    def _slice_job_params(self, cluster: Cluster, ckpt: Checkpoint,
                          ordinal: int, action: str) -> AgentJobParams:
        rec = self._slice_host_record(ckpt, ordinal)
        return AgentJobParams(
            cr_name=ckpt.metadata.name,
            namespace=ckpt.metadata.namespace,
            action=action,
            node_name=rec.get("node", ""),
            pvc_claim_name=(ckpt.spec.volume_claim.claim_name
                            if ckpt.spec.volume_claim else None),
            target_pod_name=rec.get("pod",
                                    self._slice_pod_name(ckpt, ordinal)),
            target_pod_uid=rec.get("podUid", ""),
            pre_copy=ckpt.spec.pre_copy,
            migration_path=ckpt.metadata.annotations.get(
                MIGRATION_PATH_ANNOTATION, ""),
            fault_points=ckpt.metadata.annotations.get(
                FAULT_POINTS_ANNOTATION, ""),
            owner=OwnerReference(kind="Checkpoint",
                                 name=ckpt.metadata.name,
                                 uid=ckpt.metadata.uid, controller=True),
            traceparent=ckpt.metadata.annotations.get(
                trace.TRACEPARENT_ANNOTATION, ""),
            flight_clock=migration_flight_clock(cluster, ckpt,
                                                "Checkpoint"),
            slice_hosts=ckpt.spec.slice_hosts,
            slice_ordinal=ordinal,
            # The gang's rendezvous/ledger namespace: the CR's attempt
            # count — every host of one attempt shares it, and a
            # retried gang never meets a failed attempt's leftovers.
            slice_nonce=str(watchdog.attempt_count(ckpt.metadata)),
        )

    def _slice_pending(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        wait = watchdog.retry_wait_remaining(ckpt.metadata)
        if wait > 0:
            return Result(requeue_after=wait)
        for k in range(ckpt.spec.slice_hosts):
            job = self.agent_manager.generate_agent_job(
                self._slice_job_params(cluster, ckpt, k, "checkpoint"))
            try:
                cluster.create(job)
            except AlreadyExists:
                pass
        self._set_phase(cluster, ckpt, CheckpointPhase.CHECKPOINTING,
                        "SliceAgentJobsCreated")
        return Result()

    def _slice_checkpointing(self, cluster: Cluster,
                             ckpt: Checkpoint) -> Result:
        if self._aborting(ckpt) is not None:
            return self._drive_slice_abort(cluster, ckpt)
        jobs = self._slice_jobs(cluster, ckpt)
        hosts: list[dict] = []
        phase_started = watchdog.phase_started_at(
            ckpt.status.conditions, CheckpointPhase.CHECKPOINTING.value)
        failure: tuple[int, str, str] | None = None
        all_complete = True
        for k, job in sorted(jobs.items()):
            rec = dict(self._slice_host_record(ckpt, k))
            rec.setdefault("ordinal", k)
            rec["job"] = slice_agent_job_name(ckpt.metadata.name, k)
            if job is None:
                # The per-host agent may have quiesced its source before
                # the Job was lost: slice-wide abort, never a dead end.
                rec.update(state="Lost", reason="AgentJobLost")
                failure = failure or (k, "AgentJobLost",
                                      f"slice host {k} agent job "
                                      "disappeared")
                all_complete = False
            elif job.status.is_failed():
                verdict = watchdog.classify_job_failure(
                    self.agent_manager, ckpt.metadata.namespace,
                    ckpt.metadata.name, watchdog.AGENT_JOB_FAILED,
                    f"slice host {k} agent job failed")
                rec.update(state="Failed", reason=verdict.cause)
                failure = failure or (k, verdict.cause, verdict.message)
                all_complete = False
            elif job.status.complete():
                rec.update(state="Complete", reason="")
            else:
                cause = watchdog.overrun_cause(job, phase_started,
                                               kind="Checkpoint")
                if cause is not None:
                    rec.update(state="Overrun", reason=cause)
                    failure = failure or (
                        k, cause,
                        f"slice host {k} agent job overran its "
                        f"{watchdog.overrun_noun(cause)}")
                else:
                    rec.update(state="Running", reason="")
                all_complete = False
            hosts.append(rec)
        self._set_slice_hosts(cluster, ckpt, hosts)
        sync_slice_progress_status(cluster, "Checkpoint", ckpt, jobs)
        if failure is not None:
            k, cause, message = failure
            return self._begin_slice_abort(cluster, ckpt, cause, message)
        if not all_complete:
            return Result(requeue_after=watchdog.lease_timeout_s() / 2)
        # Gang complete: every host's leg finished — the CR-level commit.
        pv = (ckpt.spec.volume_claim.claim_name
              if ckpt.spec.volume_claim else "hostpath")
        self._set_phase(
            cluster, ckpt, CheckpointPhase.CHECKPOINTED, "SliceDataUploaded",
            data_path=f"{pv}://{ckpt.metadata.namespace}/"
                      f"{ckpt.metadata.name}")
        return Result()

    def _begin_slice_abort(self, cluster: Cluster, ckpt: Checkpoint,
                           cause: str, message: str) -> Result:
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        # Every host's failed/wedged attempt Job goes first: the names
        # are reused by the per-host abort Jobs (keeping the Job-watch →
        # CR mapping intact).
        for k in range(ckpt.spec.slice_hosts):
            cluster.try_delete("Job", slice_agent_job_name(name, k), ns)

        def mutate(obj: Checkpoint) -> None:
            update_condition(obj.status.conditions, self.ABORTING_CONDITION,
                             "True", cause, message)

        cluster.patch("Checkpoint", name, mutate, ns)
        return Result(requeue=True)

    def _drive_slice_abort(self, cluster: Cluster,
                           ckpt: Checkpoint) -> Result:
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        cond = self._aborting(ckpt)
        jobs = self._slice_jobs(cluster, ckpt)
        pending = False
        for k, job in sorted(jobs.items()):
            if job is not None and _job_action(job) != "abort":
                cluster.try_delete("Job", slice_agent_job_name(name, k), ns)
                return Result(requeue_after=0.2)
            if job is None:
                # One abort Job per SOURCE host: resume that host's
                # workload from live HBM, clear its partial dump — and
                # (slice env stamped) record the gang ledger's ABORT so
                # parked destinations poison-and-clear. Deliberately no
                # fault propagation into the recovery arm.
                abort_job = self.agent_manager.generate_agent_job(
                    self._slice_job_params(cluster, ckpt, k, "abort"))
                try:
                    cluster.create(abort_job)
                except AlreadyExists:
                    pass
                pending = True
            elif not (job.status.complete() or job.status.is_failed()):
                pending = True
        if pending:
            return Result()  # the Job watch re-enqueues on completions
        aborted_ok = all(j is not None and j.status.complete()
                         for j in jobs.values())
        hosts = []
        for k in range(ckpt.spec.slice_hosts):
            rec = dict(self._slice_host_record(ckpt, k))
            rec.setdefault("ordinal", k)
            job = jobs.get(k)
            rec.update(state=("Aborted" if job is not None
                              and job.status.complete() else "AbortFailed"))
            hosts.append(rec)
        self._set_slice_hosts(cluster, ckpt, hosts)
        # Tear down the migration's restore leg(s), then the abort Jobs.
        restore_name = f"{name}-migration"
        cluster.try_delete("Job", agent_job_name(restore_name), ns)
        cluster.try_delete("Restore", restore_name, ns)
        for k in range(ckpt.spec.slice_hosts):
            cluster.try_delete("Job", slice_agent_job_name(name, k), ns)
        MIGRATION_ABORTS.inc(driver="manager")
        cause = cond.reason if cond is not None else "MigrationAborted"
        message = cond.message if cond is not None else ""
        flight.emit("manager.abort", uid=name, ok=aborted_ok, cause=cause,
                    slice_hosts=ckpt.spec.slice_hosts)
        return self._fail(
            cluster, ckpt,
            "MigrationAborted" if aborted_ok else "AbortFailed",
            f"{cause}: {message} (slice-wide abort: every source host "
            + ("resumed" if aborted_ok else
               "resume INCOMPLETE — operator attention required") + ")",
        )

    def _slice_checkpointed(self, cluster: Cluster,
                            ckpt: Checkpoint) -> Result:
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        for k in range(ckpt.spec.slice_hosts):
            job = cluster.try_get("Job", slice_agent_job_name(name, k), ns)
            if job is not None and _job_action(job) != "cleanup":
                cluster.try_delete("Job", slice_agent_job_name(name, k), ns)
        ttl = self._ttl(cluster, ckpt, CheckpointPhase.CHECKPOINTED)
        return ttl if ttl is not None else Result()

    # createdHandler (reference :99-122): bind identity — node, pod UID,
    # pod-spec hash — then go Pending.
    def _created(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._is_slice(ckpt):
            return self._slice_created(cluster, ckpt)
        pod = cluster.try_get("Pod", ckpt.spec.pod_name, ckpt.metadata.namespace)
        if pod is None:
            return self._fail(cluster, ckpt, "PodNotFound",
                              f"pod {ckpt.spec.pod_name} not found")
        if pod.status.phase != "Running" or not pod.spec.node_name:
            return Result(requeue_after=1.0)
        self._set_phase(
            cluster, ckpt, CheckpointPhase.PENDING, "PodResolved",
            node_name=pod.spec.node_name,
            pod_uid=pod.metadata.uid,
            pod_spec_hash=compute_pod_spec_hash(pod.spec),
        )
        return Result()

    # -- standby arm/fire protocol ----------------------------------------------
    #
    # A StandbyCheckpoint (spec.standby) arms instead of completing: the
    # agent Job stays resident after its round-0 dump, governed delta
    # rounds keep the destination base warm, and the CR parks in the
    # Standby phase — unbounded by design (standby_overrun_cause bounds
    # a dead agent or frozen governor instead of the phase deadline).
    # Firing is annotation-driven end to end: the preemption watcher /
    # drain controller / operator stamps grit.dev/fire on the CR, this
    # controller forwards it onto the armed agent Job (the vehicle the
    # agent actually polls), and the CR moves Standby → Firing →
    # Checkpointed as the agent runs only the final delta + blackout.

    @staticmethod
    def _fire_reason(ckpt: Checkpoint) -> str:
        return ckpt.metadata.annotations.get(FIRE_ANNOTATION, "")

    def _forward_fire(self, cluster: Cluster, ckpt: Checkpoint,
                      reason: str) -> Result:
        """Stamp the CR's fire reason onto the armed agent Job and enter
        Firing. Idempotent: re-stamping the same annotation is a no-op
        patch, and a Job re-created by a retry mid-fire gets re-stamped
        by the Firing handler's next pass."""
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace

        def mutate(job) -> None:
            job.metadata.annotations[FIRE_ANNOTATION] = reason

        cluster.patch("Job", agent_job_name(name), mutate, ns)
        # The watcher (reclaim) and the drain controller (cordon) count
        # their fires where they stamp them; a reason neither minted is
        # an operator's direct grit.dev/fire — counted here, the only
        # place every fire funnels through.
        from grit_tpu.manager.drain_controller import (  # noqa: PLC0415
            CORDON_FIRE_REASON,
        )
        from grit_tpu.manager.preemption_watcher import (  # noqa: PLC0415
            RECLAIM_REASON_PREFIXES,
        )

        if not reason.startswith(
                (*RECLAIM_REASON_PREFIXES, CORDON_FIRE_REASON)):
            STANDBY_FIRES.inc(trigger="operator")
        self._set_phase(cluster, ckpt, CheckpointPhase.FIRING,
                        "StandbyFired", reason)
        return Result(requeue=True)

    def _standby(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._aborting(ckpt) is not None:
            return self._drive_abort(cluster, ckpt)
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        job = cluster.try_get("Job", agent_job_name(name), ns)
        if job is None:
            # The armed agent Job vanished: its momentary quiesces may
            # have left the source parked — abort (resume source) rather
            # than dead-ending, exactly like Checkpointing.
            return self._begin_abort(cluster, ckpt, "AgentJobLost",
                                     "standby agent job disappeared")
        if job.status.is_failed():
            return self._handle_leg_failure(
                cluster, ckpt, watchdog.AGENT_JOB_FAILED,
                "standby agent job failed while armed")
        if job.status.complete():
            # The agent only exits zero after a fired final delta
            # committed (e.g. SIGTERM-fired before this controller ever
            # saw a fire annotation): the data is durable — proceed.
            sync_progress_status(cluster, "Checkpoint", ckpt, job)
            pv = (ckpt.spec.volume_claim.claim_name
                  if ckpt.spec.volume_claim else "hostpath")
            self._set_phase(
                cluster, ckpt, CheckpointPhase.CHECKPOINTED,
                "StandbyFiredAndUploaded",
                data_path=f"{pv}://{ns}/{name}")
            return Result()
        sync_progress_status(cluster, "Checkpoint", ckpt, job)
        reason = self._fire_reason(ckpt)
        if reason:
            return self._forward_fire(cluster, ckpt, reason)
        cause = watchdog.standby_overrun_cause(job, kind="Checkpoint")
        if cause is not None:
            return self._handle_leg_failure(
                cluster, ckpt, cause,
                f"armed standby agent overran its "
                f"{watchdog.overrun_noun(cause)}")
        return Result(requeue_after=watchdog.lease_timeout_s() / 2)

    def _firing(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._aborting(ckpt) is not None:
            return self._drive_abort(cluster, ckpt)
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        job = cluster.try_get("Job", agent_job_name(name), ns)
        if job is None:
            return self._begin_abort(cluster, ckpt, "AgentJobLost",
                                     "standby agent job lost mid-fire")
        if job.status.is_failed():
            return self._handle_leg_failure(
                cluster, ckpt, watchdog.AGENT_JOB_FAILED,
                "standby agent job failed mid-fire")
        if not job.status.complete():
            # Re-stamp the fire annotation (idempotent): a retry-created
            # Job between Standby and here must still see the trigger.
            reason = self._fire_reason(ckpt) or "fire"
            if job.metadata.annotations.get(FIRE_ANNOTATION) != reason:
                def mutate(j) -> None:
                    j.metadata.annotations[FIRE_ANNOTATION] = reason
                cluster.patch("Job", agent_job_name(name), mutate, ns)
            sync_progress_status(cluster, "Checkpoint", ckpt, job)
            # Firing is BOUNDED (unlike Standby): the final delta +
            # blackout must land inside the ordinary deadlines.
            cause = watchdog.overrun_cause(
                job,
                watchdog.phase_started_at(ckpt.status.conditions,
                                          CheckpointPhase.FIRING.value),
                kind="Checkpoint")
            if cause is not None:
                return self._handle_leg_failure(
                    cluster, ckpt, cause,
                    f"firing standby agent overran its "
                    f"{watchdog.overrun_noun(cause)}")
            return Result(requeue_after=watchdog.lease_timeout_s() / 2)
        sync_progress_status(cluster, "Checkpoint", ckpt, job)
        pv = (ckpt.spec.volume_claim.claim_name
              if ckpt.spec.volume_claim else "hostpath")
        self._set_phase(cluster, ckpt, CheckpointPhase.CHECKPOINTED,
                        "DataUploaded", data_path=f"{pv}://{ns}/{name}")
        return Result()

    # pendingHandler (reference :126-147): create the checkpoint agent Job
    # pinned to the source node.
    def _pending(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._is_slice(ckpt):
            return self._slice_pending(cluster, ckpt)
        # Backoff gate: after a watchdog-scheduled retry, the next agent
        # Job may not be created before grit.dev/retry-at.
        wait = watchdog.retry_wait_remaining(ckpt.metadata)
        if wait > 0:
            return Result(requeue_after=wait)
        job = self.agent_manager.generate_agent_job(AgentJobParams(
            cr_name=ckpt.metadata.name,
            namespace=ckpt.metadata.namespace,
            action="checkpoint",
            node_name=ckpt.status.node_name,
            pvc_claim_name=(ckpt.spec.volume_claim.claim_name
                            if ckpt.spec.volume_claim else None),
            target_pod_name=ckpt.spec.pod_name,
            target_pod_uid=ckpt.status.pod_uid,
            # Standby implies pre-copy semantics (the fired final delta
            # dumps against the rolling base the arm kept warm).
            pre_copy=ckpt.spec.pre_copy or ckpt.spec.standby,
            standby=ckpt.spec.standby,
            # Known sequencing limit: this manager creates the restore
            # Job only after the Checkpoint completes, so a managed
            # wire-mode source finds no receiver and degrades to the PVC
            # path at connect (~2 s), and the later restore agent
            # fast-aborts on the tee marker instead of listening — wire
            # stays ≈ pvc + ε here. The single-hop win needs the agents
            # CONCURRENT (destination pre-picked, restore Job created at
            # CHECKPOINTING) — the harness/CLI drive that flow today;
            # overlapping the managed Jobs is the follow-up.
            migration_path=ckpt.metadata.annotations.get(
                MIGRATION_PATH_ANNOTATION, ""),
            fault_points=ckpt.metadata.annotations.get(
                FAULT_POINTS_ANNOTATION, ""),
            # Fleet byte shaping: a plan-owned member CR carries its
            # link-budget share; standalone CRs carry nothing (0).
            max_inflight_mb=_max_inflight_mb(ckpt),
            owner=OwnerReference(kind="Checkpoint", name=ckpt.metadata.name,
                                 uid=ckpt.metadata.uid, controller=True),
            traceparent=ckpt.metadata.annotations.get(
                trace.TRACEPARENT_ANNOTATION, ""),
            flight_clock=migration_flight_clock(cluster, ckpt, "Checkpoint"),
        ))
        try:
            cluster.create(job)
        except AlreadyExists:
            pass
        self._set_phase(cluster, ckpt, CheckpointPhase.CHECKPOINTING, "AgentJobCreated")
        return Result()

    # checkpointingHandler (reference :149-176): wait for agent Job result;
    # success records DataPath "<pv>://<ns>/<name>" (:163). Extended with
    # the watchdog: Aborting condition drives the abort machine; a failed
    # Job is classified for bounded retry vs abort; a running Job is
    # checked against its heartbeat lease and phase deadline.
    def _checkpointing(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._is_slice(ckpt):
            return self._slice_checkpointing(cluster, ckpt)
        if self._aborting(ckpt) is not None:
            return self._drive_abort(cluster, ckpt)
        job = cluster.try_get(
            "Job", agent_job_name(ckpt.metadata.name), ckpt.metadata.namespace
        )
        if job is not None and _job_action(job) in ("cleanup", "abort"):
            # A stale job under our name (an orphaned TTL cleanup job, or
            # an abort job from a same-named predecessor CR — we are not
            # aborting, the check above returned): its completion must
            # not be misread as a successful dump. Clear it and recreate.
            cluster.try_delete(
                "Job", agent_job_name(ckpt.metadata.name),
                ckpt.metadata.namespace)
            self._set_phase(cluster, ckpt, CheckpointPhase.PENDING,
                            "StaleJobCleared")
            return Result(requeue=True)
        if job is None:
            # The agent may have quiesced the source before the Job was
            # lost: abort (resume source) rather than dead-ending.
            return self._begin_abort(cluster, ckpt, "AgentJobLost",
                                     "agent job disappeared")
        if job.status.is_failed():
            return self._handle_leg_failure(
                cluster, ckpt, watchdog.AGENT_JOB_FAILED,
                "checkpoint agent job failed")
        if not job.status.complete():
            # Live telemetry: fold the Job's progress annotation into
            # status.progress on this same poll (lease cadence) — the
            # fleet scheduler and `kubectl get` read bytes/rate/ETA off
            # the CR while the migration runs.
            sync_progress_status(cluster, "Checkpoint", ckpt, job)
            if ckpt.spec.standby:
                # Arming: a fire that lands before the arm finishes is
                # forwarded immediately (the agent polls between rounds
                # too — a reclaim notice mid-arm pays whatever base has
                # shipped so far).
                reason = self._fire_reason(ckpt)
                if reason:
                    return self._forward_fire(cluster, ckpt, reason)
                # The agent reports "standby" in its progress snapshot
                # once the round-0 base committed: the CR parks armed.
                rec = watchdog.job_progress(job)
                if rec is not None and rec.get("phase") == "standby":
                    self._set_phase(cluster, ckpt,
                                    CheckpointPhase.STANDBY,
                                    "StandbyArmed")
                    return Result(requeue=True)
            cause = watchdog.overrun_cause(
                job,
                watchdog.phase_started_at(
                    ckpt.status.conditions,
                    CheckpointPhase.CHECKPOINTING.value),
                kind="Checkpoint")
            if cause is not None:
                return self._handle_leg_failure(
                    cluster, ckpt, cause,
                    f"checkpoint agent job overran its "
                    f"{watchdog.overrun_noun(cause)}")
            # Re-enqueued by the Job watch; poll on the lease period too
            # so a silently-wedged agent is noticed without any event.
            return Result(requeue_after=watchdog.lease_timeout_s() / 2)
        # Terminal progress sync: the agent's last lease beat stamped
        # the finished snapshot (lease.stop's final beat runs after the
        # driver returned) — fold it in so a SUCCEEDED CR reads its
        # terminal state, not the last mid-flight sample (a fleet
        # bandwidth sum must not include ghost in-flight migrations).
        sync_progress_status(cluster, "Checkpoint", ckpt, job)
        pv = (ckpt.spec.volume_claim.claim_name
              if ckpt.spec.volume_claim else "hostpath")
        data_path = f"{pv}://{ckpt.metadata.namespace}/{ckpt.metadata.name}"
        self._set_phase(cluster, ckpt, CheckpointPhase.CHECKPOINTED, "DataUploaded",
                        data_path=data_path)
        return Result()

    # checkpointedHandler (reference :205-222): GC the agent Job; enter
    # auto-migration if requested.
    def _checkpointed(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._is_slice(ckpt):
            return self._slice_checkpointed(cluster, ckpt)
        # GC the CHECKPOINT agent job (never a TTL cleanup job that has
        # since reused the name — see _ttl).
        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        job = cluster.try_get("Job", agent_job_name(name), ns)
        if job is not None and _job_action(job) != "cleanup":
            cluster.try_delete("Job", agent_job_name(name), ns)
        if ckpt.spec.auto_migration:
            self._set_phase(cluster, ckpt, CheckpointPhase.SUBMITTING, "AutoMigration")
            return Result(requeue=True)
        # Terminal success for plain checkpoints: with a TTL, eventually
        # GC the data + the CR itself.
        ttl = self._ttl(cluster, ckpt, CheckpointPhase.CHECKPOINTED)
        return ttl if ttl is not None else Result()

    # submittingHandler (reference :225-282): create the Restore carrying the
    # pod's controller ownerRef, then delete the source pod so its owner
    # recreates it (the replacement is matched by the pod webhook).
    def _submitting(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        pod = cluster.try_get("Pod", ckpt.spec.pod_name, ckpt.metadata.namespace)
        owner_ref = pod.metadata.controller_ref() if pod is not None else None
        if pod is not None and owner_ref is None:
            return self._fail(
                cluster, ckpt, "NoControllerOwner",
                "autoMigration requires the pod to be controller-owned",
            )
        restore_name = f"{ckpt.metadata.name}-migration"
        if cluster.try_get("Restore", restore_name, ckpt.metadata.namespace) is None:
            if owner_ref is None:
                # Pod already gone and Restore missing — cannot recover ownerRef.
                return self._fail(cluster, ckpt, "SourcePodLost",
                                  "source pod deleted before Restore was created")
            meta = ObjectMeta(name=restore_name,
                              namespace=ckpt.metadata.namespace)
            # The migration's restore half joins the checkpoint's trace.
            tp = ckpt.metadata.annotations.get(
                trace.TRACEPARENT_ANNOTATION, "")
            if tp:
                meta.annotations[trace.TRACEPARENT_ANNOTATION] = tp
            # ... and its migration data path: the restore agent job must
            # run the same path (wire's receiver half) as the checkpoint.
            mp = ckpt.metadata.annotations.get(MIGRATION_PATH_ANNOTATION, "")
            if mp:
                meta.annotations[MIGRATION_PATH_ANNOTATION] = mp
            # ... and any armed fault points: a chaos run targets the
            # whole migration, both legs.
            fp = ckpt.metadata.annotations.get(FAULT_POINTS_ANNOTATION, "")
            if fp:
                meta.annotations[FAULT_POINTS_ANNOTATION] = fp
            try:
                cluster.create(Restore(
                    metadata=meta,
                    spec=RestoreSpec(checkpoint_name=ckpt.metadata.name,
                                     owner_ref=owner_ref),
                ))
            except AlreadyExists:
                pass
        if pod is not None:
            try:
                cluster.delete("Pod", pod.metadata.name, pod.metadata.namespace)
            except NotFound:
                pass
        self._set_phase(cluster, ckpt, CheckpointPhase.SUBMITTED, "MigrationSubmitted")
        return Result()

    def _submitted(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        ttl = self._ttl(cluster, ckpt, CheckpointPhase.SUBMITTED)
        return ttl if ttl is not None else Result()

    # -- data lifecycle (ttlSecondsAfterFinished; no reference analogue:
    # its checkpoint images accumulate on the PVC forever) ----------------------

    def _ttl(
        self, cluster: Cluster, ckpt: Checkpoint, phase: CheckpointPhase
    ) -> Result | None:
        """TTL GC state machine for a terminal-success checkpoint. None →
        no TTL configured (caller proceeds normally); otherwise the Result
        to return (requeue until due, then cleanup Job → CR deletion)."""
        ttl = ckpt.spec.ttl_seconds_after_finished
        if ttl is None:
            return None
        from grit_tpu.kube.objects import now  # noqa: PLC0415

        name, ns = ckpt.metadata.name, ckpt.metadata.namespace
        # ANY in-flight Restore consuming this checkpoint — the
        # auto-migration's own `<name>-migration`, or a user-created one —
        # reads the CR and the PVC payload: GC must wait until every such
        # Restore is terminal (or failed), no matter how short the TTL.
        # Matching by spec reference, not by name, closes the race where a
        # user restore starts right before cleanup deletes its payload.
        for restore in cluster.list("Restore", ns):
            if restore.spec.checkpoint_name != name:
                continue
            if restore.status.phase not in (
                RestorePhase.RESTORED, RestorePhase.FAILED,
            ):
                return Result(requeue_after=5.0)

        finished_at = max(
            (c.last_transition_time for c in ckpt.status.conditions
             if c.type == phase.value),
            default=0.0,
        )
        remaining = finished_at + ttl - now()
        if remaining > 0:
            return Result(requeue_after=max(remaining, 0.5))

        # The checkpoint agent Job was GC'd at Checkpointed, so the name
        # is free for the cleanup Job — and the existing Job watch maps
        # it back to this CR for completion wakeups.
        job = cluster.try_get("Job", agent_job_name(name), ns)
        if job is None:
            # Pin the cleanup Job to the source node while it is still
            # around and Ready, so the node's host work dir is removed
            # along with the PVC payload (an unpinned Job only reliably
            # reaches the PVC). Fall back to unpinned when the node is
            # gone or unready (drain — the primary migration trigger —
            # usually ends with the node deleted): the host dir died with
            # the node, and the PVC payload is what remains to GC.
            node_name = ""
            src = ckpt.status.node_name
            if src:
                node = cluster.try_get("Node", src, "")
                if node is not None and node.status.ready():
                    node_name = src
            job = self.agent_manager.generate_agent_job(AgentJobParams(
                cr_name=name,
                namespace=ns,
                action="cleanup",
                node_name=node_name,
                pvc_claim_name=(ckpt.spec.volume_claim.claim_name
                                if ckpt.spec.volume_claim else None),
                target_pod_name=ckpt.spec.pod_name,
                target_pod_uid=ckpt.status.pod_uid,
                owner=OwnerReference(kind="Checkpoint", name=name,
                                     uid=ckpt.metadata.uid, controller=True),
                traceparent=ckpt.metadata.annotations.get(
                    trace.TRACEPARENT_ANNOTATION, ""),
            ))
            try:
                cluster.create(job)
            except AlreadyExists:
                pass
            return Result(requeue_after=1.0)
        if _job_action(job) != "cleanup":
            # A stale checkpoint/restore job under this name: wait for its
            # own GC rather than misreading its completion as ours.
            return Result(requeue_after=1.0)
        if job.status.is_failed():
            # Retry: clear the failed job; next pass recreates it.
            cluster.try_delete("Job", agent_job_name(name), ns)
            return Result(requeue_after=30.0)
        if not job.status.complete():
            return Result()  # the Job watch re-enqueues on completion
        cluster.try_delete("Job", agent_job_name(name), ns)
        cluster.try_delete("Checkpoint", name, ns)
        PHASE_TRANSITIONS.inc(kind="Checkpoint", phase="TTLExpired")
        return Result()

    # Failed: recover to the last good phase once the cause clears (reference
    # util.go:218-234 ResolveLastPhaseFromConditions) — e.g. a transient
    # agent-job failure retries from Pending after the operator deletes the
    # failed Job. The watchdog extends this with UNATTENDED recovery: a
    # retriable failure stamped grit.dev/retry-at re-creates the agent Job
    # itself once the backoff elapses — no operator in the loop.
    def _failed(self, cluster: Cluster, ckpt: Checkpoint) -> Result:
        if self._aborting(ckpt) is not None:
            # An aborted migration is terminal by design: the source was
            # resumed (or its resume failed — worse); auto-retrying the
            # checkpoint on top of either would re-quiesce a workload the
            # abort just promised back to training.
            return Result()
        failed = [c for c in ckpt.status.conditions
                  if c.type == CheckpointPhase.FAILED.value
                  and c.status == "True"]
        if failed and failed[-1].reason == "SliceAutoMigrationUnsupported" \
                and self._is_slice(ckpt) and ckpt.spec.auto_migration:
            # A spec-level refusal: nothing heals it but an operator
            # editing the spec — retrying from Created would loop the
            # reconciler forever against the SAME spec. An edited spec
            # (autoMigration dropped) falls through and retries.
            return Result()
        last = resolve_last_checkpoint_phase(ckpt.status.conditions)
        if last == CheckpointPhase.CREATED:
            # Retry once the target pod is Running again (slice CRs:
            # host 0's pod stands in — _slice_created re-resolves all).
            pod_name = (self._slice_pod_name(ckpt, 0)
                        if self._is_slice(ckpt) else ckpt.spec.pod_name)
            pod = cluster.try_get("Pod", pod_name, ckpt.metadata.namespace)
            if pod is None or pod.status.phase != "Running":
                return Result()
        elif last in (CheckpointPhase.PENDING, CheckpointPhase.CHECKPOINTING,
                      CheckpointPhase.STANDBY, CheckpointPhase.FIRING):
            # A failed/lost STANDBY or FIRING attempt re-arms from
            # Pending: the fresh agent re-dumps the base (retry-safe —
            # the PVC's old base is simply replaced), and a persisting
            # grit.dev/fire annotation re-fires the new arm the moment
            # it reports armed.
            job = cluster.try_get(
                "Job", agent_job_name(ckpt.metadata.name), ckpt.metadata.namespace
            )
            if job is not None and job.status.is_failed():
                if RETRY_AT_ANNOTATION not in ckpt.metadata.annotations:
                    # Legacy path: no watchdog-sanctioned retry — wait for
                    # the operator (or the drain controller) to clear the
                    # failed Job.
                    return Result()
                wait = watchdog.retry_wait_remaining(ckpt.metadata)
                if wait > 0:
                    return Result(requeue_after=wait)
                # Backoff elapsed: clear the failed attempt ourselves.
                cluster.try_delete("Job", agent_job_name(ckpt.metadata.name),
                                   ckpt.metadata.namespace)
            elif job is None and any(
                c.type == CheckpointPhase.FAILED.value and c.status == "True"
                and c.reason in watchdog.OVERRUN_CAUSES
                for c in ckpt.status.conditions
            ):
                # The watchdog itself deleted the wedged-but-Active Job
                # (_handle_leg_failure): absence here is OUR doing, not an
                # operator override — the scheduled backoff still applies.
                wait = watchdog.retry_wait_remaining(ckpt.metadata)
                if wait > 0:
                    return Result(requeue_after=wait)
            # Job gone (operator/drain cleared it, or we just did): retry
            # from Pending — job recreation there is idempotent. Consume
            # the retry gate: an operator clearing the Job early is the
            # manual override, and a served backoff must not re-gate the
            # NEXT failure's schedule.
            if RETRY_AT_ANNOTATION in ckpt.metadata.annotations:
                def strip(obj: Checkpoint) -> None:
                    obj.metadata.annotations.pop(RETRY_AT_ANNOTATION, None)
                cluster.patch("Checkpoint", ckpt.metadata.name, strip,
                              ckpt.metadata.namespace)
            last = CheckpointPhase.PENDING
        elif last in (CheckpointPhase.CHECKPOINTED, CheckpointPhase.SUBMITTING):
            # Submitting failures (e.g. NoControllerOwner, SourcePodLost) are
            # not self-healing; stay Failed for the operator.
            return Result()
        else:
            return Result()
        self._set_phase(cluster, ckpt, last, "RetryAfterFailure")
        return Result(requeue=True)
