"""RestoreSet controller: one verified snapshot → N post-copy clones.

TPU-native addition with no reference analogue (its restores are 1→1
recovery): a :class:`~grit_tpu.api.types.RestoreSet` treats a VERIFIED
snapshot — the PVC container tree + sidecars a Checkpoint committed
(PR 5) — as a *template* and fans it out into ``spec.replicas``
set-owned Restore CRs in parallel. Each clone is an ordinary restore
leg end to end: the clone Restore rides the existing pod-webhook
rendezvous (one selector serves the whole set — the atomic
``grit.dev/pod-selected`` claim hands each racing replica pod a
DIFFERENT clone), the restore agent reuses the wire/PVC transports
as-is, and the restored pod's post-copy place (PR 7) means replica N
serves its first request after only the hot set landed, faulting the
cold KV tail in behind traffic. Compile-cache seeding (PR 1) is
amortized across the fan-out for free: every clone seeds from the SAME
snapshot's carried XLA cache, so one source compile pays for N replicas.

Phase machine:

- **Pending**: template verify — the referenced Checkpoint must still
  exist and hold a verified snapshot (admission checked this; the
  level-triggered re-check catches a snapshot deleted or rolled back
  underneath the set). ``serve.verify`` is the chaos seam.
- **Cloning**: ensure one clone Restore per ordinal (``serve.clone``
  fires per creation — an armed fault skips only THAT clone this pass,
  siblings fan out), fold every clone's phase/progress into
  ``status.replicas[]``, publish the fan-out snapshot file, and close
  the ``readyReplicas`` gate.
- **Ready / Degraded / Failed**: terminal. One clone's terminal failure
  never blocks siblings: they go Ready, the set lands Degraded with the
  failed replica's reason recorded, and zero healthy replicas are lost.

A failed clone is NOT retried at the set level: the clone Restore's own
watchdog/lease machinery already ran its bounded retries before the
phase went terminal — by then the failure is real (and the template is
still intact for an operator to fan out a replacement set).
"""

from __future__ import annotations

import json
import os
import time
from collections.abc import Callable

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.api.constants import (
    CLONE_ORDINAL_ANNOTATION,
    FAULT_POINTS_ANNOTATION,
    MIGRATION_PATH_ANNOTATION,
    RESTORESET_ANNOTATION,
    RETRY_AT_ANNOTATION,
)
from grit_tpu.api.types import (
    CheckpointPhase,
    Restore,
    RestorePhase,
    RestoreSet,
    RestoreSetPhase,
    RestoreSpec,
    VERIFIED_SNAPSHOT_PHASES,
)
from grit_tpu.kube.cluster import AdmissionDenied, AlreadyExists, Cluster
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import ObjectMeta, OwnerReference
from grit_tpu.manager.util import update_condition
from grit_tpu.metadata import atomic_write_json, restoreset_status_filename
from grit_tpu.obs import flight, trace
from grit_tpu.obs.metrics import (
    PHASE_TRANSITIONS,
    SERVE_CLONES,
    SERVE_FANOUT_SECONDS,
    SERVE_READY_REPLICAS,
)

# Replica states in status.replicas[] — a closed vocabulary.
REPLICA_PENDING = "Pending"
REPLICA_RESTORING = "Restoring"
REPLICA_READY = "Ready"
REPLICA_FAILED = "Failed"

def clone_restore_name(set_name: str, ordinal: int) -> str:
    """The set-owned clone Restore's name. Ordinal-stable so the agent
    Job naming, the pod rendezvous, and status.replicas[] fan-in all
    key consistently across reconciles."""
    return f"{set_name}-clone-{ordinal}"


class RestoreSetController:
    kind = "RestoreSet"

    # -- watch wiring ---------------------------------------------------------

    def register(self, cluster: Cluster,
                 enqueue: Callable[[Request], None]) -> None:
        # Set-owned clones report back: any Restore event whose
        # controller owner is a RestoreSet re-enqueues the set, so clone
        # completions/failures close the readyReplicas gate without
        # waiting out the poll cadence.
        def on_restore_event(ev) -> None:
            for ref in ev.obj.metadata.owner_references:
                if ref.kind == "RestoreSet" and ref.controller:
                    enqueue(Request(ev.namespace, ref.name))

        # The TEMPLATE's lifecycle drives the set too: a Checkpoint
        # deleted or rolled back underneath a set must reach the
        # verify / fan-out promptly (Failed, loudly), not wait out the
        # poll cadence.
        def on_checkpoint_event(ev) -> None:
            for rs in cluster.list("RestoreSet", ev.namespace):
                if rs.spec.snapshot_ref == ev.name:
                    enqueue(Request(ev.namespace, rs.metadata.name))

        cluster.watch("Restore", on_restore_event)
        cluster.watch("Checkpoint", on_checkpoint_event)

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        rs = cluster.try_get("RestoreSet", req.name, req.namespace)
        if rs is None:
            # A deleted set's fan-out snapshot must go with it — a
            # lingering terminal file would be the "most recent set"
            # `gritscope watch --restoreset` latches onto.
            SERVE_READY_REPLICAS.set(0)
            status_dir = str(config.SERVE_STATUS_DIR.get())
            if status_dir:
                try:
                    os.unlink(os.path.join(
                        status_dir,
                        restoreset_status_filename(req.namespace, req.name)))
                except OSError:
                    pass
            return Result()
        phase = rs.status.phase or RestoreSetPhase.PENDING
        with trace.span(f"manager.restoreset.{phase.value}",
                        restoreset=f"{req.namespace}/{req.name}"):
            if phase == RestoreSetPhase.PENDING:
                return self._pending(cluster, rs)
            if phase == RestoreSetPhase.CLONING:
                return self._cloning(cluster, rs)
            return Result()  # Ready/Degraded/Failed are terminal

    def _set_phase(self, cluster: Cluster, rs: RestoreSet,
                   phase: RestoreSetPhase, reason: str,
                   message: str = "", **status_fields) -> None:
        def mutate(obj: RestoreSet) -> None:
            obj.status.phase = phase
            for k, v in status_fields.items():
                setattr(obj.status, k, v)
            update_condition(obj.status.conditions, phase.value, "True",
                             reason, message)

        cluster.patch("RestoreSet", rs.metadata.name, mutate,
                      rs.metadata.namespace)
        PHASE_TRANSITIONS.inc(kind="RestoreSet", phase=phase.value)
        # Keyed by the SNAPSHOT name: that is the uid every agent leg of
        # the fan-out derives from its work/stage dir basename.
        flight.emit("manager.phase", uid=rs.spec.snapshot_ref,
                    kind="RestoreSet", phase=phase.value, reason=reason)

    # -- Pending: template verify ---------------------------------------------

    def _pending(self, cluster: Cluster, rs: RestoreSet) -> Result:
        # Chaos seam: a raise here travels the workqueue error path —
        # the verify retries level-triggered, nothing is half-created.
        faults.fault_point("serve.verify")
        ns = rs.metadata.namespace
        ckpt = cluster.try_get("Checkpoint", rs.spec.snapshot_ref, ns)
        if ckpt is None:
            self._set_phase(
                cluster, rs, RestoreSetPhase.FAILED, "SnapshotNotFound",
                f"checkpoint {ns}/{rs.spec.snapshot_ref} deleted "
                "underneath the set")
            return Result()
        if ckpt.status.phase == CheckpointPhase.FAILED:
            self._set_phase(
                cluster, rs, RestoreSetPhase.FAILED, "SnapshotNotVerified",
                f"checkpoint {rs.spec.snapshot_ref} failed — no verified "
                "template to clone")
            return Result()
        if ckpt.status.phase not in VERIFIED_SNAPSHOT_PHASES:
            # Admission raced the checkpoint's own completion; poll.
            return Result(requeue_after=float(config.SERVE_POLL_S.get()))
        flight.emit("serve.fanout", uid=rs.spec.snapshot_ref,
                    restoreset=rs.metadata.name,
                    replicas=max(1, int(rs.spec.replicas)),
                    data_path=ckpt.status.data_path)
        self._set_phase(cluster, rs, RestoreSetPhase.CLONING,
                        "TemplateVerified",
                        f"snapshot {ckpt.status.data_path or ckpt.metadata.name}"
                        f" fans out to {max(1, int(rs.spec.replicas))} clones")
        return Result(requeue=True)

    # -- Cloning: fan-out + status.replicas[] fan-in ---------------------------

    def _ensure_clones(
            self, cluster: Cluster, rs: RestoreSet,
    ) -> tuple[dict[int, "Restore | None"], bool, str]:
        """Create missing clone Restores. Returns ``(clones, skipped,
        denied)``: ``clones`` is the per-ordinal Restore map this pass
        already fetched (``_fold_replicas`` consumes it — one GET per
        clone per tick, not two); ``skipped`` when an armed
        ``serve.clone`` fault deferred a creation (the clone retries
        next reconcile — siblings are never held back); ``denied``
        carries the admission message when the Restore webhook refused
        a clone — the template was deleted or rolled back UNDER the
        Cloning phase, which must land the set Failed, not error-loop
        the workqueue forever."""
        ns = rs.metadata.namespace
        clones: dict[int, Restore | None] = {}
        skipped = False
        for k in range(max(1, int(rs.spec.replicas))):
            name = clone_restore_name(rs.metadata.name, k)
            clones[k] = cluster.try_get("Restore", name, ns)
            if clones[k] is not None:
                continue
            try:
                # Per-clone chaos seam: the clone-commit boundary where
                # a fan-out leg enters the cluster.
                faults.fault_point("serve.clone")
            except faults.FaultInjected as exc:
                SERVE_CLONES.inc(outcome="skipped")
                flight.emit("serve.clone.abort", uid=rs.spec.snapshot_ref,
                            clone=name, reason=str(exc))
                skipped = True
                continue
            annotations = {
                RESTORESET_ANNOTATION: rs.metadata.name,
                CLONE_ORDINAL_ANNOTATION: str(k),
            }
            # Data-path/chaos/trace propagation, the member-CR idiom:
            # the fan-out must ride whatever transport and fault spec
            # the operator stamped on the set.
            for key in (MIGRATION_PATH_ANNOTATION, FAULT_POINTS_ANNOTATION,
                        trace.TRACEPARENT_ANNOTATION):
                val = rs.metadata.annotations.get(key)
                if val:
                    annotations[key] = val
            clone = Restore(
                metadata=ObjectMeta(
                    name=name, namespace=ns, annotations=annotations,
                    owner_references=[OwnerReference(
                        kind="RestoreSet", name=rs.metadata.name,
                        uid=rs.metadata.uid, controller=True)],
                ),
                spec=RestoreSpec(
                    checkpoint_name=rs.spec.snapshot_ref,
                    owner_ref=rs.spec.template.owner_ref,
                    selector=rs.spec.template.selector,
                ),
            )
            try:
                cluster.create(clone)
            except AlreadyExists:
                clones[k] = cluster.try_get("Restore", name, ns)
                continue
            except AdmissionDenied as exc:
                return clones, skipped, str(exc)
            clones[k] = clone
            flight.emit("serve.clone.start", uid=rs.spec.snapshot_ref,
                        clone=name, ordinal=k)
        return clones, skipped, ""

    def _fold_replicas(self, rs: RestoreSet,
                       clones: dict) -> tuple[list, int, int, int]:
        """(records, ready, failed, in_flight) — one record per ordinal,
        rebuilt every pass (level-triggered) from the clone map the
        same pass's ``_ensure_clones`` fetched."""
        prev = {r.get("restore"): r for r in rs.status.replicas
                if isinstance(r, dict)}
        records: list[dict] = []
        ready = failed = in_flight = 0
        for k in range(max(1, int(rs.spec.replicas))):
            name = clone_restore_name(rs.metadata.name, k)
            clone = clones.get(k)
            rec = {"ordinal": k, "restore": name, "targetPod": "",
                   "node": "", "state": REPLICA_PENDING, "reason": "",
                   "progress": {}}
            if clone is None:
                in_flight += 1
                records.append(rec)
                continue
            rec["targetPod"] = clone.status.target_pod
            rec["node"] = clone.status.node_name
            rec["progress"] = dict(clone.status.progress or {})
            phase = clone.status.phase
            was = (prev.get(name) or {}).get("state")
            if phase == RestorePhase.RESTORED:
                rec["state"] = REPLICA_READY
                ready += 1
                if was != REPLICA_READY:
                    SERVE_CLONES.inc(outcome="ready")
                    flight.emit("serve.clone.ready",
                                uid=rs.spec.snapshot_ref, clone=name,
                                ordinal=k, pod=clone.status.target_pod)
            elif phase == RestorePhase.FAILED \
                    and RETRY_AT_ANNOTATION not in clone.metadata.annotations:
                # Terminal: the clone's own bounded watchdog retries ran
                # out (a FAILED with retry-at pending is still its own
                # machinery's problem, not ours).
                rec["state"] = REPLICA_FAILED
                rec["reason"] = next(
                    (c.reason for c in reversed(clone.status.conditions)
                     if c.type == RestorePhase.FAILED.value), "Failed")
                failed += 1
                if was != REPLICA_FAILED:
                    SERVE_CLONES.inc(outcome="failed")
                    flight.emit("serve.clone.abort",
                                uid=rs.spec.snapshot_ref, clone=name,
                                ordinal=k, reason=rec["reason"])
            else:
                if phase in (RestorePhase.PENDING, RestorePhase.RESTORING,
                             RestorePhase.FAILED):
                    rec["state"] = REPLICA_RESTORING
                    if phase == RestorePhase.FAILED:
                        rec["reason"] = "retrying"
                in_flight += 1
            records.append(rec)
        return records, ready, failed, in_flight

    def _cloning(self, cluster: Cluster, rs: RestoreSet) -> Result:
        clones, skipped, denied = self._ensure_clones(cluster, rs)
        records, ready, failed, in_flight = self._fold_replicas(rs, clones)
        SERVE_READY_REPLICAS.set(ready)
        started = rs.status.started_at or time.time()
        progress = {
            "readyReplicas": ready,
            "replicas": {r["restore"]: r["progress"]
                         for r in records if r["progress"]},
        }

        # Mirror every status write onto the in-memory copy too: the
        # published snapshot file is built from it, so the controller
        # never re-GETs the object it just patched (which would also
        # raise on a concurrently-deleted set).
        def _local(phase: RestoreSetPhase | None = None,
                   finished: float = 0.0) -> None:
            if phase is not None:
                rs.status.phase = phase
            rs.status.replicas = records
            rs.status.ready_replicas = ready
            rs.status.progress = progress
            rs.status.started_at = rs.status.started_at or started
            if finished:
                rs.status.finished_at = finished

        if denied:
            # The snapshot was deleted/rolled back underneath the set
            # mid-fan-out: the Restore webhook now refuses new clones.
            # Already-created clones keep their own machinery; the SET
            # is terminally Failed — loudly, never an error loop.
            self._set_phase(
                cluster, rs, RestoreSetPhase.FAILED, "SnapshotNotVerified",
                f"clone admission refused: {denied}",
                replicas=records, ready_replicas=ready,
                progress=progress, started_at=started,
                finished_at=time.time())
            _local(RestoreSetPhase.FAILED, finished=time.time())
            self._publish_snapshot(rs)
            return Result()

        want = max(1, int(rs.spec.replicas))
        if in_flight == 0 and not skipped:
            finished = time.time()
            if ready == want:
                SERVE_FANOUT_SECONDS.set(max(0.0, finished - started))
                self._set_phase(
                    cluster, rs, RestoreSetPhase.READY, "AllReplicasReady",
                    f"{ready}/{want} clones serving",
                    replicas=records, ready_replicas=ready,
                    progress=progress, started_at=started,
                    finished_at=finished)
                _local(RestoreSetPhase.READY, finished=finished)
            else:
                bad = ", ".join(f"{r['restore']}: {r['reason']}"
                                for r in records
                                if r["state"] == REPLICA_FAILED)
                self._set_phase(
                    cluster, rs, RestoreSetPhase.DEGRADED, "CloneFailures",
                    f"{ready}/{want} clones serving; failed: {bad}",
                    replicas=records, ready_replicas=ready,
                    progress=progress, started_at=started,
                    finished_at=finished)
                _local(RestoreSetPhase.DEGRADED, finished=finished)
            self._publish_snapshot(rs)
            return Result()

        # Patch only on change: a status write that always differs would
        # advance the resource version every pass and self-wake this
        # set's own watch forever.
        if (records != rs.status.replicas
                or ready != rs.status.ready_replicas
                or progress != rs.status.progress
                or not rs.status.started_at):
            def mutate(obj: RestoreSet) -> None:
                obj.status.replicas = records
                obj.status.ready_replicas = ready
                obj.status.progress = progress
                if not obj.status.started_at:
                    obj.status.started_at = started

            cluster.patch("RestoreSet", rs.metadata.name, mutate,
                          rs.metadata.namespace)
        _local()
        self._publish_snapshot(rs)
        return Result(requeue_after=float(config.SERVE_POLL_S.get()))

    # -- fan-out snapshot file (gritscope watch --restoreset) ------------------

    def _publish_snapshot(self, rs: RestoreSet) -> None:
        """Atomically publish the fan-out view (the `gritscope watch
        --restoreset` feed) in GRIT_SERVE_STATUS_DIR. Same contract as
        the fleet snapshot: tmp + rename, torn readers skip the tick."""
        status_dir = str(config.SERVE_STATUS_DIR.get())
        if not status_dir:
            return
        snap = {
            "kind": "restoreset",
            "namespace": rs.metadata.namespace,
            "name": rs.metadata.name,
            "snapshotRef": rs.spec.snapshot_ref,
            "phase": rs.status.phase.value if rs.status.phase else "",
            "specReplicas": max(1, int(rs.spec.replicas)),
            "readyReplicas": rs.status.ready_replicas,
            "replicas": rs.status.replicas,
            "updatedAt": time.time(),
        }
        try:
            os.makedirs(status_dir, exist_ok=True)
            path = os.path.join(status_dir, restoreset_status_filename(
                rs.metadata.namespace, rs.metadata.name))
            atomic_write_json(path, snap)
        except OSError:
            pass  # observability must never fail the reconcile
