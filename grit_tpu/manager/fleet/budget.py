"""Fleet bandwidth/concurrency budgets: token buckets + admission math.

The scheduler's control signals already exist — ``status.progress``
rateBps/bytesShipped (PR 8) per member, per-migration byte shaping
(``GRIT_MIRROR_MAX_INFLIGHT_MB``) as the actuator — this module adds
the fleet-level policy between them:

- :class:`TokenBucket` — classic refill/ceiling bucket with an explicit
  **borrow** bound: tokens accrue at the budget rate up to a burst
  ceiling (``GRIT_FLEET_BURST_S`` worth — an idle link must not bank
  unlimited credit and then blow the instantaneous budget when the wave
  lands), draws beyond the balance are refused unless the caller
  borrows, and borrowing is bounded (the deficit is repaid by future
  refill before the next draw clears). Latency-critical admissions may
  borrow; batch ones never do.
- :class:`FleetBudget` — the per-plan composite: a concurrency ceiling,
  one fleet-wide bucket, and one bucket per ``src->dst`` link, rebuilt
  cheap (buckets are lazily created per link) and consulted at every
  admission. Observed member bytes (``status.progress`` deltas) are
  charged to the buckets each reconcile, so a wave that ships faster
  than its budget stops admitting until the buckets recover.

Shaping: an admitted member's link share is the link budget split
evenly across that link's active members; the share is actuated as
``GRIT_MIRROR_MAX_INFLIGHT_MB = share x GRIT_FLEET_SHAPE_WINDOW_S`` —
bounding in-flight bytes bounds the sustained rate to roughly
share x window / window without starving the dump mirror.

Everything takes an explicit ``now`` so the tier-1 suite drives the
refill/borrow/ceiling math as pure functions (ISSUE satellite).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from grit_tpu.api import config


class TokenBucket:
    """Bytes-denominated token bucket. ``rate_bps`` <= 0 = unlimited
    (every draw succeeds, balance pinned at 0)."""

    def __init__(self, rate_bps: float, burst_s: float,
                 borrow_s: float = 0.0, *, now: float = 0.0) -> None:
        self.rate_bps = float(rate_bps)
        self.capacity = max(0.0, self.rate_bps * float(burst_s))
        #: How deep a *borrowing* draw may push the balance negative —
        #: the preemption credit a latency-critical admission spends.
        self.borrow_floor = -max(0.0, self.rate_bps * float(borrow_s))
        self.tokens = self.capacity
        self._last = float(now)

    @property
    def unlimited(self) -> bool:
        return self.rate_bps <= 0

    def refill(self, now: float) -> float:
        """Accrue tokens for the elapsed wall, capped at the burst
        ceiling; returns the new balance. Time moving backwards (clock
        step) accrues nothing rather than draining."""
        if self.unlimited:
            self._last = now
            return 0.0
        elapsed = max(0.0, now - self._last)
        self._last = now
        self.tokens = min(self.capacity, self.tokens + elapsed * self.rate_bps)
        return self.tokens

    def balance(self, now: float) -> float:
        return self.refill(now)

    def try_take(self, nbytes: float, now: float, *,
                 borrow: bool = False) -> bool:
        """Draw ``nbytes``; refused (False, balance untouched) when the
        draw would push past zero — or past the borrow floor when
        ``borrow``. A refused draw costs nothing: the caller re-asks
        after refill."""
        if self.unlimited:
            return True
        self.refill(now)
        floor = self.borrow_floor if borrow else 0.0
        if self.tokens - nbytes < floor:
            return False
        self.tokens -= nbytes
        return True

    def charge(self, nbytes: float, now: float) -> None:
        """Unconditionally charge observed bytes (they already moved on
        the wire — the budget can only respond by pausing admissions
        and tightening shaping until the balance recovers). The balance
        may go below the borrow floor here; ``try_take`` refusing until
        refill catches up is exactly the feedback loop."""
        if self.unlimited or nbytes <= 0:
            return
        self.refill(now)
        self.tokens -= nbytes

    def refund(self, nbytes: float, now: float) -> None:
        """Return tokens a refused composite admission drew (all-or-
        nothing across buckets), capped at the burst ceiling."""
        if self.unlimited or nbytes <= 0:
            return
        self.refill(now)
        self.tokens = min(self.capacity, self.tokens + nbytes)


@dataclass
class LinkState:
    bucket: TokenBucket
    #: bytesShipped watermark per member checkpoint name, for charging
    #: only the delta each reconcile.
    last_bytes: dict[str, int] = field(default_factory=dict)


class FleetBudget:
    """One plan's budget state. Held in controller memory per plan;
    rebuilt full on manager restart (the safe direction — a restarted
    manager briefly over-admits nothing: concurrency is recomputed from
    cluster state, and the buckets start at their burst ceiling)."""

    def __init__(self, max_concurrent: int, fleet_bps: float,
                 link_bps: float, *, burst_s: float | None = None,
                 borrow_s: float | None = None,
                 shape_window_s: float | None = None,
                 now: float = 0.0) -> None:
        self.max_concurrent = max(1, int(max_concurrent))
        self.fleet_bps = float(fleet_bps)
        self.link_bps = float(link_bps)
        self.burst_s = (float(config.FLEET_BURST_S.get())
                        if burst_s is None else float(burst_s))
        # Borrow bound: one burst window — the latency-critical credit.
        self.borrow_s = self.burst_s if borrow_s is None else float(borrow_s)
        self.shape_window_s = (float(config.FLEET_SHAPE_WINDOW_S.get())
                               if shape_window_s is None
                               else float(shape_window_s))
        self.fleet_bucket = TokenBucket(self.fleet_bps, self.burst_s,
                                        self.borrow_s, now=now)
        self.links: dict[str, LinkState] = {}

    @classmethod
    def for_plan(cls, plan, *, now: float = 0.0) -> "FleetBudget":
        """Effective budget: the plan's declared numbers, falling back
        to the GRIT_FLEET_* defaults field by field."""
        b = plan.spec.budget
        max_concurrent = b.max_concurrent if b.max_concurrent > 0 else \
            int(config.FLEET_MAX_CONCURRENT.get())
        fleet_bps = b.fleet_bandwidth_bps if b.fleet_bandwidth_bps > 0 \
            else float(config.FLEET_BUDGET_MBPS.get()) * 1e6
        link_bps = b.link_bandwidth_bps if b.link_bandwidth_bps > 0 \
            else float(config.FLEET_LINK_BUDGET_MBPS.get()) * 1e6
        return cls(max_concurrent, fleet_bps, link_bps, now=now)

    def link(self, key: str, *, now: float) -> LinkState:
        state = self.links.get(key)
        if state is None:
            state = LinkState(bucket=TokenBucket(
                self.link_bps, self.burst_s, self.borrow_s, now=now))
            self.links[key] = state
        return state

    # -- accounting (observed bytes -> bucket charges) -----------------------

    def charge_observed(self, key: str, member: str, bytes_shipped: int,
                        *, now: float) -> int:
        """Charge the member's shipped-bytes DELTA since the last
        reconcile to its link bucket and the fleet bucket; returns the
        delta. A shrinking watermark (fresh CR after a plan retry)
        resets without charging."""
        state = self.link(key, now=now)
        last = state.last_bytes.get(member, 0)
        delta = bytes_shipped - last
        state.last_bytes[member] = bytes_shipped
        if delta <= 0:
            return 0
        state.bucket.charge(delta, now)
        self.fleet_bucket.charge(delta, now)
        return delta

    def forget_member(self, member: str) -> None:
        """Drop a member's byte watermark everywhere (its CR is being
        retried under a fresh zeroed progress snapshot)."""
        for state in self.links.values():
            state.last_bytes.pop(member, None)

    # -- admission -----------------------------------------------------------

    def admission_cost(self) -> float:
        """Tokens one admission draws up front: the shaping window's
        worth of the member's link share — the burst the new member may
        put on the wire before the next reconcile re-observes it."""
        if self.link_bps <= 0:
            return 0.0
        return self.link_bps * min(self.shape_window_s, self.burst_s)

    def try_admit(self, key: str, active: int, *, now: float,
                  latency_critical: bool = False) -> bool:
        """One admission decision: concurrency ceiling, then the link
        bucket, then the fleet bucket. Latency-critical members may
        borrow (bounded) from both buckets — the fast-window promise;
        batch members wait for a clean balance. A refused draw leaves
        every bucket untouched."""
        if active >= self.max_concurrent:
            return False
        cost = self.admission_cost()
        state = self.link(key, now=now)
        if not state.bucket.try_take(cost, now, borrow=latency_critical):
            return False
        if not self.fleet_bucket.try_take(cost, now,
                                          borrow=latency_critical):
            # Repay the link draw: admission is all-or-nothing.
            state.bucket.refund(cost, now)
            return False
        return True

    # -- shaping -------------------------------------------------------------

    def share_bps(self, active_on_link: int) -> float:
        """A member's even split of its link budget; 0 = unshaped."""
        if self.link_bps <= 0:
            return 0.0
        return self.link_bps / max(1, active_on_link)

    def shaping_mb(self, share_bps: float) -> int:
        """Actuate a rate share as an in-flight byte bound
        (``GRIT_MIRROR_MAX_INFLIGHT_MB``); 0 = leave the agent default."""
        if share_bps <= 0:
            return 0
        return max(1, int(share_bps * self.shape_window_s / 1e6))

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The STABLE budget half of the plan's ``status.budget``
        record: declared ceilings and link keys only. Deliberately no
        live token balances — they change with wall time on every
        read, and a status patch that always differs would wake the
        plan's own watch forever (reconcile → patch → MODIFIED →
        reconcile). The balances ride :meth:`tokens_snapshot` into the
        fleet snapshot FILE instead (file writes bump no
        resourceVersion)."""
        return {
            "maxConcurrent": self.max_concurrent,
            "fleetBudgetBps": self.fleet_bps,
            "linkBudgetBps": self.link_bps,
            "links": {key: {"budgetBps": self.link_bps}
                      for key in sorted(self.links)},
        }

    def tokens_snapshot(self, *, now: float) -> dict:
        """Live bucket balances for the fleet-view file."""
        return {
            "fleetTokens": (round(self.fleet_bucket.balance(now), 1)
                            if not self.fleet_bucket.unlimited else None),
            "linkTokens": {
                key: (round(state.bucket.balance(now), 1)
                      if not state.bucket.unlimited else None)
                for key, state in sorted(self.links.items())},
        }
