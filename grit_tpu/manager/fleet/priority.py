"""Priority classes: annotation-declared admission ordering.

Latency-critical pods (a serving replica mid-drain) migrate in the fast
window; batch jobs queue behind them. Preemption is of QUEUED slots
only — a latency-critical arrival goes ahead of every queued batch
member, but an in-flight migration is never aborted for priority
(half-migrated state is strictly worse than a late migration; the abort
machine exists for failures, not scheduling).

Pure functions over the plan's member records so the ordering matrix is
tier-1-testable without a cluster.
"""

from __future__ import annotations

import logging

from grit_tpu.api.constants import MIGRATION_PRIORITY_ANNOTATION
from grit_tpu.api.types import (
    PRIORITY_BATCH,
    PRIORITY_CLASSES,
    PRIORITY_LATENCY_CRITICAL,
)

log = logging.getLogger(__name__)

_RANK = {PRIORITY_LATENCY_CRITICAL: 0, PRIORITY_BATCH: 1}


def pod_priority(pod) -> str:
    """The pod's declared class; unknown values degrade to batch with a
    loud warning (the webhook denies unknown classes at plan admission,
    so this only fires for annotations edited after the fact)."""
    raw = pod.metadata.annotations.get(MIGRATION_PRIORITY_ANNOTATION, "")
    if not raw:
        return PRIORITY_BATCH
    if raw not in PRIORITY_CLASSES:
        log.warning("pod %s/%s declares unknown migration priority %r; "
                    "treating as %s", pod.metadata.namespace,
                    pod.metadata.name, raw, PRIORITY_BATCH)
        return PRIORITY_BATCH
    return raw


def priority_rank(priority: str) -> int:
    return _RANK.get(priority, _RANK[PRIORITY_BATCH])


def order_queue(members: list[dict]) -> list[dict]:
    """Admission order of queued member records ({"priority", ...}):
    latency-critical before batch, stable within a class (spec order is
    arrival order). The preemption METRIC is deliberately not derived
    from this ordering — it counts slots actually taken at admission
    (plan_controller), because a standing queue re-ordered every poll
    pass is not repeated preemption."""
    indexed = list(enumerate(members))
    ordered = sorted(indexed,
                     key=lambda kv: (priority_rank(
                         kv[1].get("priority", PRIORITY_BATCH)), kv[0]))
    return [m for _, m in ordered]
