"""MigrationPlan controller: one plan → a rolling wave of Checkpoints.

The reconcile is level-triggered and rebuilds everything observable from
cluster state (member records in ``status.pods[]``, used capacity from
the records' placements, concurrency from the live member CRs); only the
token buckets live in controller memory, and a manager restart simply
refills them (the safe direction — see :mod:`budget`).

Phase machine:

- **Planning**: bind every member's identity NOW (pod UID, source node,
  priority class, HBM demand) — auto-migration deletes the source pod
  at Submitting, so nothing may need the pod object later.
- **Migrating**: the wave loop. Fold member CR phases into the records;
  charge observed progress bytes to the budget buckets; resolve failed
  members (the member CR's own watchdog/abort machinery already ran —
  by the time a member reads FAILED its source was resumed; the plan
  either retries it with a fresh CR, bounded by maxRetriesPerPod, or
  records it); then admit queued members in priority order — placement
  by the bin-packer over the plan-declared capacities, admission by the
  token buckets — and publish status + the fleet snapshot file.
- **Succeeded / PartiallyFailed**: terminal verdict with per-pod
  reasons; ``status.makespan_seconds`` spans first admission → verdict.

A failed member never stalls the rest of the wave: its slot frees the
moment its CR goes terminal, the next queued member is admitted on the
same pass, and the failed pod's reservation on its destination is
released (its pod resumed on the SOURCE — the abort machine's
invariant is what makes fleet rollback safe).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections.abc import Callable

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.api.constants import (
    DESTINATION_NODE_ANNOTATION,
    FAULT_POINTS_ANNOTATION,
    HBM_DEMAND_ANNOTATION,
    MAX_INFLIGHT_MB_ANNOTATION,
    MIGRATION_PATH_ANNOTATION,
    TPU_TOPOLOGY_ANNOTATION,
)
from grit_tpu.api.types import (
    Checkpoint,
    CheckpointPhase,
    CheckpointSpec,
    MigrationPlan,
    MigrationPlanPhase,
    PRIORITY_BATCH,
    PRIORITY_CLASSES,
    PRIORITY_LATENCY_CRITICAL,
)
from grit_tpu.kube.cluster import (
    AdmissionDenied,
    AlreadyExists,
    Cluster,
    NotFound,
)
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import ObjectMeta, OwnerReference, now
from grit_tpu.manager.fleet.binpack import Candidate, choose_destination
from grit_tpu.manager.fleet.budget import FleetBudget
from grit_tpu.manager.fleet.priority import (
    order_queue,
    pod_priority,
    priority_rank,
)
from grit_tpu.metadata import atomic_write_json, fleet_status_filename
from grit_tpu.obs import flight
from grit_tpu.obs.metrics import (
    FLEET_BUDGET_UTILIZATION,
    FLEET_CONCURRENT,
    FLEET_MAKESPAN_SECONDS,
    FLEET_MEMBERS,
    FLEET_PLACEMENTS,
    FLEET_PLANS,
    FLEET_QUEUE_DEPTH,
    FLEET_QUEUE_PREEMPTIONS,
    FLEET_RATE_BPS,
    PHASE_TRANSITIONS,
)
from grit_tpu.manager.util import update_condition

log = logging.getLogger(__name__)

# Member states in status.pods[] — a closed vocabulary.
QUEUED = "Queued"
MIGRATING = "Migrating"
SUCCEEDED = "Succeeded"
RETRYING = "Retrying"
FAILED = "Failed"

#: Member CR phases that count as terminal success for the plan: the
#: data is durable and the restore leg is owned by the ordinary
#: machinery (Submitting/Submitted for auto-migration members).
_MEMBER_SUCCESS_PHASES = (CheckpointPhase.SUBMITTED,)

_PLACEMENT_OUTCOME = {
    "Placed": "placed",
    "NoCapacity": "no_capacity",
    "TopologyMismatch": "topology_mismatch",
    "DestinationRejected": "destination_rejected",
}


def plan_member_checkpoint_name(plan_name: str, pod_name: str) -> str:
    """The plan-owned member CR's name. Stable across plan-level
    retries (the failed CR is deleted first), so the agent-Job name
    mapping and the drain-path TTL idioms keep working unchanged."""
    return f"{plan_name}-{pod_name}"


def member_demand_gb(pod) -> float:
    """The pod's HBM footprint for capacity accounting: the
    grit.dev/hbm-gb annotation wins; else google.com/tpu chip count x
    GRIT_FLEET_HBM_PER_CHIP_GB; else 0 (fits anywhere — capacity not
    modeled for this pod)."""
    raw = pod.metadata.annotations.get(HBM_DEMAND_ANNOTATION, "")
    if raw:
        try:
            return max(0.0, float(raw))
        except ValueError:
            log.warning("pod %s/%s: malformed %s=%r ignored",
                        pod.metadata.namespace, pod.metadata.name,
                        HBM_DEMAND_ANNOTATION, raw)
    chips = 0
    for c in pod.spec.containers:
        for resources in (c.resources.limits, c.resources.requests):
            val = resources.get("google.com/tpu")
            if val:
                try:
                    chips = max(chips, int(val))
                except (TypeError, ValueError):
                    pass
    if chips:
        return chips * float(config.FLEET_HBM_PER_CHIP_GB.get())
    return 0.0


class MigrationPlanController:
    kind = "MigrationPlan"

    def __init__(self) -> None:
        # (ns, name) -> FleetBudget: token buckets are the only
        # controller-memory state (deliberately — see module doc).
        self._budgets: dict[tuple[str, str], FleetBudget] = {}
        self._lock = threading.Lock()

    # -- watch wiring ---------------------------------------------------------

    def register(self, cluster: Cluster,
                 enqueue: Callable[[Request], None]) -> None:
        # Plan-owned member CRs report back: any Checkpoint event whose
        # controller owner is a MigrationPlan re-enqueues the plan, so
        # member completions/failures advance the wave without waiting
        # out the poll cadence.
        def on_checkpoint_event(ev) -> None:
            for ref in ev.obj.metadata.owner_references:
                if ref.kind == "MigrationPlan" and ref.controller:
                    enqueue(Request(ev.namespace, ref.name))

        cluster.watch("Checkpoint", on_checkpoint_event)

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        plan = cluster.try_get("MigrationPlan", req.name, req.namespace)
        if plan is None:
            with self._lock:
                self._budgets.pop((req.namespace, req.name), None)
            # A deleted plan's fleet-view snapshot must go with it: a
            # lingering terminal file would be the "most recent plan"
            # `gritscope watch --fleet` latches onto before the NEXT
            # plan's first wave publishes.
            status_dir = str(config.FLEET_STATUS_DIR.get())
            if status_dir:
                try:
                    os.unlink(os.path.join(status_dir, fleet_status_filename(
                        req.namespace, req.name)))
                except OSError:
                    pass
            return Result()
        phase = plan.status.phase or MigrationPlanPhase.PLANNING
        if phase == MigrationPlanPhase.PLANNING:
            return self._planning(cluster, plan)
        if phase == MigrationPlanPhase.MIGRATING:
            return self._migrating(cluster, plan)
        return Result()  # terminal verdicts are terminal

    def _set_phase(self, cluster: Cluster, plan: MigrationPlan,
                   phase: MigrationPlanPhase, reason: str,
                   message: str = "", **status_fields) -> None:
        def mutate(obj: MigrationPlan) -> None:
            obj.status.phase = phase
            for k, v in status_fields.items():
                setattr(obj.status, k, v)
            update_condition(obj.status.conditions, phase.value, "True",
                             reason, message)

        cluster.patch("MigrationPlan", plan.metadata.name, mutate,
                      plan.metadata.namespace)
        PHASE_TRANSITIONS.inc(kind="MigrationPlan", phase=phase.value)
        flight.emit("fleet.plan", uid=plan.metadata.name,
                    phase=phase.value, reason=reason)

    # -- Planning: bind member identity while the pods still exist ------------

    def _planning(self, cluster: Cluster, plan: MigrationPlan) -> Result:
        ns = plan.metadata.namespace
        records: list[dict] = []
        for member in plan.spec.members:
            pod = cluster.try_get("Pod", member.pod_name, ns)
            rec = {
                "pod": member.pod_name,
                "podUid": "",
                "sourceNode": "",
                "priority": PRIORITY_BATCH,
                "demandGb": 0.0,
                "topology": "",
                "state": QUEUED,
                "checkpoint": "",
                "destination": "",
                "attempts": 0,
                "reason": "",
            }
            if pod is None or pod.status.phase != "Running" \
                    or not pod.spec.node_name:
                # Webhook-gated at CREATE; a pod gone by the first
                # reconcile is a terminal member failure, never a plan
                # failure — the rest of the wave proceeds.
                rec.update(state=FAILED, reason="PodNotFound")
                FLEET_MEMBERS.inc(outcome="failed")
            else:
                rec.update(
                    podUid=pod.metadata.uid,
                    sourceNode=pod.spec.node_name,
                    priority=pod_priority(pod),
                    demandGb=round(member_demand_gb(pod), 3),
                    topology=pod.metadata.annotations.get(
                        TPU_TOPOLOGY_ANNOTATION, ""),
                )
            records.append(rec)
        self._set_phase(cluster, plan, MigrationPlanPhase.MIGRATING,
                        "PlanExpanded",
                        f"{len(records)} member pod(s) resolved",
                        pods=records)
        return Result(requeue=True)

    # -- Migrating: the wave loop ---------------------------------------------

    def _budget(self, plan: MigrationPlan) -> FleetBudget:
        key = (plan.metadata.namespace, plan.metadata.name)
        with self._lock:
            b = self._budgets.get(key)
            if b is None:
                b = FleetBudget.for_plan(plan, now=now())
                self._budgets[key] = b
            return b

    @staticmethod
    def _link_key(rec: dict) -> str:
        return f"{rec.get('sourceNode', '')}->{rec.get('destination', '')}"

    @staticmethod
    def _member_failure_reason(ckpt: Checkpoint) -> str:
        failed = [c for c in ckpt.status.conditions
                  if c.type == CheckpointPhase.FAILED.value
                  and c.status == "True"]
        if failed:
            last = failed[-1]
            return f"{last.reason}: {last.message}"[:300] if last.message \
                else last.reason
        return "Failed"

    def _max_retries(self, plan: MigrationPlan) -> int:
        if plan.spec.max_retries_per_pod >= 0:
            return plan.spec.max_retries_per_pod
        return max(0, int(config.FLEET_MAX_RETRIES.get()))

    def _migrating(self, cluster: Cluster, plan: MigrationPlan) -> Result:
        # Chaos seam: an armed fleet.wave fault exercises the workqueue
        # error path (RECONCILE_ERRORS + requeue with backoff) — the
        # wave resumes from cluster state on the retry.
        faults.fault_point("fleet.wave")
        ns, name = plan.metadata.namespace, plan.metadata.name
        budget = self._budget(plan)
        t = now()
        records = [dict(r) for r in plan.status.pods]
        max_retries = self._max_retries(plan)

        # 1. Fold member CR state into the records (the folded progress
        # rides each record so the fleet snapshot — and `gritscope
        # watch --plan` — carries every member's live line).
        for rec in records:
            if rec["state"] in (SUCCEEDED, FAILED):
                continue
            if rec["state"] in (QUEUED, RETRYING) and not rec["checkpoint"]:
                continue
            ckpt = cluster.try_get("Checkpoint", rec["checkpoint"], ns)
            if ckpt is None:
                # In-flight member CR vanished (operator delete, TTL of
                # a same-named predecessor): the pod may have been
                # resumed or never quiesced — either way the safe state
                # to continue FROM is the source, so this rides the
                # retry bookkeeping like any terminal failure.
                self._resolve_member_failure(
                    plan, rec, "CheckpointLost", budget, max_retries)
                continue
            phase = ckpt.status.phase
            if ckpt.status.progress:
                rec["progress"] = ckpt.status.progress
                # Charge the shipped-bytes delta BEFORE the phase
                # branches: a member completing within one lease period
                # still moved its tail bytes on the wire, and skipping
                # terminal folds would leave the buckets crediting a
                # wave that sustainedly exceeded its declared budget.
                shipped = int(
                    ckpt.status.progress.get("bytesShipped") or 0)
                budget.charge_observed(self._link_key(rec),
                                       rec["checkpoint"], shipped, now=t)
            if phase in _MEMBER_SUCCESS_PHASES:
                if rec["state"] != SUCCEEDED:
                    rec.update(state=SUCCEEDED, reason="")
                    FLEET_MEMBERS.inc(outcome="succeeded")
            elif phase == CheckpointPhase.FAILED:
                # Terminal only once the CR parked FAILED with its
                # abort resolved or no watchdog retry pending; a CR
                # whose own bounded agent retry is scheduled
                # (grit.dev/retry-at) is still migrating from the
                # plan's viewpoint.
                if self._member_cr_still_retrying(ckpt):
                    rec.update(state=MIGRATING, reason="RetryScheduled")
                else:
                    cause = self._member_failure_reason(ckpt)
                    self._delete_member_cr(cluster, ns, rec["checkpoint"])
                    self._resolve_member_failure(
                        plan, rec, cause, budget, max_retries)
            else:
                rec["state"] = MIGRATING

        # 2. Admission: queued members in priority order, bin-packed
        # onto the declared destinations, metered by the buckets.
        active = [r for r in records if r["state"] == MIGRATING]
        used_gb: dict[str, float] = {}
        for rec in records:
            if rec["state"] in (MIGRATING, SUCCEEDED) and rec["destination"]:
                used_gb[rec["destination"]] = (
                    used_gb.get(rec["destination"], 0.0)
                    + float(rec.get("demandGb") or 0.0))
        rejected = self._rejected_destinations(cluster, plan)
        candidates = [Candidate(node_name=d.node_name,
                                capacity_gb=d.capacity_gb,
                                topology=d.topology)
                      for d in plan.spec.destinations]
        queue = [r for r in records if r["state"] in (QUEUED, RETRYING)]
        ordered = order_queue(queue)
        admitted = 0
        preempted = 0
        for rec in ordered:
            if len(active) >= budget.max_concurrent:
                rec.setdefault("reason", "")
                rec["reason"] = rec["reason"] or "ConcurrencyCeiling"
                continue
            placement = choose_destination(
                float(rec.get("demandGb") or 0.0),
                str(rec.get("topology") or ""),
                candidates, used_gb, rejected)
            outcome = _PLACEMENT_OUTCOME.get(placement.reason,
                                             "no_capacity")
            FLEET_PLACEMENTS.inc(outcome=outcome)
            if not placement.placed:
                rec["reason"] = placement.reason
                flight.emit("fleet.place", uid=name, pod=rec["pod"],
                            placed=False, reason=placement.reason)
                continue  # queued, never failed — a later member may fit
            link = f"{rec.get('sourceNode', '')}->{placement.node_name}"
            latency_critical = (
                priority_rank(rec.get("priority", PRIORITY_BATCH)) == 0)
            try:
                faults.fault_point("fleet.budget")
                ok = budget.try_admit(link, len(active), now=t,
                                      latency_critical=latency_critical)
            except faults.FaultInjected:
                ok = False
            if not ok:
                rec["reason"] = "BudgetExhausted"
                continue  # a member on another link may still admit
            if not self._create_member_cr(cluster, plan, rec,
                                          placement.node_name, budget):
                continue
            if latency_critical:
                # A preemption is a slot actually TAKEN ahead of an
                # earlier-arrived member still queued at this instant —
                # counted once, at admission (a standing queue re-ordered
                # every poll pass is not repeated preemption).
                arrival = {id(r): i for i, r in enumerate(records)}
                idx = arrival.get(id(rec), len(records))
                preempted += sum(
                    1 for i, other in enumerate(records)
                    if i < idx and other is not rec
                    and other["state"] in (QUEUED, RETRYING)
                    and priority_rank(other.get(
                        "priority", PRIORITY_BATCH)) > 0)
            rec.update(state=MIGRATING, destination=placement.node_name,
                       reason="")
            used_gb[placement.node_name] = (
                used_gb.get(placement.node_name, 0.0)
                + float(rec.get("demandGb") or 0.0))
            active.append(rec)
            admitted += 1
            flight.emit("fleet.place", uid=name, pod=rec["pod"],
                        placed=True, destination=placement.node_name)

        # 3. Status + gauges + the fleet snapshot file.
        if preempted:
            FLEET_QUEUE_PREEMPTIONS.inc(preempted)
        wave = int(plan.status.budget.get("wave", 0)) + (1 if admitted else 0)
        if admitted:
            flight.emit("fleet.wave", uid=name, wave=wave,
                        admitted=admitted, active=len(active))
        started = plan.status.started_at
        if admitted and not started:
            started = t
        fleet_rate = 0.0
        for rec in records:
            if rec["state"] != MIGRATING:
                continue
            snap = rec.get("progress") or {}
            try:
                fleet_rate += float(snap.get("rateBps") or 0.0)
            except (TypeError, ValueError):
                pass
        budget_status = budget.snapshot()
        budget_status.update(
            wave=wave,
            concurrent=len(active),
            queued=sum(1 for r in records
                       if r["state"] in (QUEUED, RETRYING)),
            fleetRateBps=round(fleet_rate, 1),
        )
        self._export_gauges(records, budget, fleet_rate, len(active))
        self._update_status(cluster, plan, records, budget_status,
                            started)
        plan.status.pods = records
        plan.status.budget = budget_status
        plan.status.started_at = started

        # 4. Verdict when every member is terminal.
        if all(r["state"] in (SUCCEEDED, FAILED) for r in records):
            return self._finish(cluster, plan, records, budget, t)
        self._publish_snapshot(plan, budget=budget, now_t=t)
        return Result(requeue_after=float(config.FLEET_POLL_S.get()))

    # -- member failure resolution (the rollback half) ------------------------

    @staticmethod
    def _member_cr_still_retrying(ckpt: Checkpoint) -> bool:
        """A FAILED member CR with a watchdog-scheduled agent retry
        pending (grit.dev/retry-at stamped — the _failed handler
        consumes it when the retry runs) is still migrating. An
        ABORTED CR is terminal by design (the source was resumed), and
        a FAILED CR with no retry scheduled — a non-self-healing
        failure like PodNotFound — must resolve at the PLAN level
        (fresh CR or recorded failure) rather than stall the wave
        waiting for an operator."""
        for c in ckpt.status.conditions:
            if c.type == "Aborting" and c.status == "True":
                return False  # aborted migrations are terminal by design
        from grit_tpu.api.constants import (  # noqa: PLC0415
            RETRY_AT_ANNOTATION,
        )

        return RETRY_AT_ANNOTATION in ckpt.metadata.annotations

    def _resolve_member_failure(self, plan: MigrationPlan, rec: dict,
                                cause: str, budget: FleetBudget,
                                max_retries: int) -> None:
        """A member's migration terminally failed — its abort already
        resumed the source (the member CR's machinery), so the pod is
        safe where it was. Retry with a fresh CR while attempts remain;
        record the pod otherwise. Either way the rest of the wave keeps
        rolling: the slot and the destination reservation free here."""
        budget.forget_member(rec["checkpoint"])
        attempts = int(rec.get("attempts") or 0)
        rec.update(checkpoint="", destination="")
        if attempts < max_retries:
            rec.update(state=RETRYING, attempts=attempts + 1, reason=cause)
            FLEET_MEMBERS.inc(outcome="retried")
            flight.emit("fleet.abort", uid=plan.metadata.name,
                        pod=rec["pod"], resolution="retry",
                        attempt=attempts + 1, cause=cause)
        else:
            rec.update(state=FAILED, reason=cause)
            FLEET_MEMBERS.inc(outcome="failed")
            flight.emit("fleet.abort", uid=plan.metadata.name,
                        pod=rec["pod"], resolution="failed", cause=cause)

    @staticmethod
    def _delete_member_cr(cluster: Cluster, ns: str, name: str) -> None:
        """GC a terminally failed member CR so a plan retry can reuse
        the name (the failure trail lives on in status.pods[].reason
        and the flight log)."""
        from grit_tpu.manager.util import agent_job_name  # noqa: PLC0415

        cluster.try_delete("Job", agent_job_name(name), ns)
        try:
            cluster.delete("Checkpoint", name, ns)
        except NotFound:
            pass

    # -- admission helpers ----------------------------------------------------

    def _rejected_destinations(self, cluster: Cluster,
                               plan: MigrationPlan) -> set[str]:
        """Destinations unusable THIS pass: node gone, unready, or
        cordoned (draining a pool onto a node being drained would
        re-migrate the pod immediately) — plus any armed fleet.place
        fault (the chaos lane's destination-rejects-placement seam)."""
        rejected: set[str] = set()
        for dest in plan.spec.destinations:
            try:
                faults.fault_point("fleet.place")
            except faults.FaultInjected:
                rejected.add(dest.node_name)
                continue
            node = cluster.try_get("Node", dest.node_name, "")
            if node is None or not node.status.ready() \
                    or node.spec.unschedulable:
                rejected.add(dest.node_name)
        return rejected

    def _member_claim(self, plan: MigrationPlan, pod_name: str):
        for member in plan.spec.members:
            if member.pod_name == pod_name and member.volume_claim:
                return member.volume_claim
        return plan.spec.volume_claim

    def _create_member_cr(self, cluster: Cluster, plan: MigrationPlan,
                          rec: dict, destination: str,
                          budget: FleetBudget) -> bool:
        ns, plan_name = plan.metadata.namespace, plan.metadata.name
        cr_name = plan_member_checkpoint_name(plan_name, rec["pod"])
        # Conservative static split: stamped shares sum to at most the
        # link budget even when every concurrent member lands on one
        # link (shares are fixed at admission — a running agent Job's
        # env cannot be re-stamped; the token bucket meters the
        # observed bytes adaptively on top).
        share = budget.share_bps(budget.max_concurrent)
        meta = ObjectMeta(name=cr_name, namespace=ns)
        meta.annotations[DESTINATION_NODE_ANNOTATION] = destination
        shaping = budget.shaping_mb(share)
        if shaping:
            meta.annotations[MAX_INFLIGHT_MB_ANNOTATION] = str(shaping)
        for key in (MIGRATION_PATH_ANNOTATION, FAULT_POINTS_ANNOTATION):
            val = plan.metadata.annotations.get(key, "")
            if val:
                meta.annotations[key] = val
        meta.owner_references.append(OwnerReference(
            kind="MigrationPlan", name=plan_name,
            uid=plan.metadata.uid, controller=True))
        ck = Checkpoint(
            metadata=meta,
            spec=CheckpointSpec(
                pod_name=rec["pod"],
                volume_claim=self._member_claim(plan, rec["pod"]),
                auto_migration=True,
                pre_copy=plan.spec.pre_copy,
                ttl_seconds_after_finished=(
                    plan.spec.ttl_seconds_after_finished),
            ),
        )
        rec["checkpoint"] = cr_name
        try:
            cluster.create(ck)
        except AlreadyExists:
            # Raced ourselves across workers — adopt it; unless the
            # same-named CR belongs to a PREVIOUS pod generation
            # (StatefulSet names recur), whose terminal phase would
            # read as this member already migrated: GC and recreate.
            existing = cluster.try_get("Checkpoint", cr_name, ns)
            if existing is not None and (
                    existing.spec.pod_name != rec["pod"]
                    or (existing.status.pod_uid and rec.get("podUid")
                        and existing.status.pod_uid != rec["podUid"])):
                self._delete_member_cr(cluster, ns, cr_name)
                rec["checkpoint"] = ""
                return False
            return True
        except AdmissionDenied as exc:
            # The pod raced away (deleted, rescheduled, node unready)
            # between planning and admission: a terminal member failure
            # subject to the plan's bounded retry, never a wave stall.
            log.warning("fleet: member checkpoint %s/%s denied: %s",
                        ns, cr_name, exc)
            self._resolve_member_failure(
                plan, rec, f"AdmissionDenied: {exc}"[:300], budget,
                self._max_retries(plan))
            return False
        log.info("fleet: plan %s/%s admitted pod %s -> %s (ckpt %s)",
                 ns, plan_name, rec["pod"], destination, cr_name)
        return True

    # -- status / verdict / publication ---------------------------------------

    def _update_status(self, cluster: Cluster, plan: MigrationPlan,
                       records: list[dict], budget_status: dict,
                       started: float) -> None:
        if plan.status.pods == records \
                and plan.status.budget == budget_status \
                and plan.status.started_at == started:
            return

        def mutate(obj: MigrationPlan) -> None:
            obj.status.pods = records
            obj.status.budget = budget_status
            obj.status.started_at = started

        cluster.patch("MigrationPlan", plan.metadata.name, mutate,
                      plan.metadata.namespace)

    def _export_gauges(self, records: list[dict], budget: FleetBudget,
                       fleet_rate: float, active: int) -> None:
        FLEET_CONCURRENT.set(active)
        FLEET_RATE_BPS.set(round(fleet_rate, 1))
        for cls in PRIORITY_CLASSES:
            FLEET_QUEUE_DEPTH.set(
                sum(1 for r in records
                    if r["state"] in (QUEUED, RETRYING)
                    and r.get("priority", PRIORITY_BATCH) == cls),
                priority=cls)
        FLEET_BUDGET_UTILIZATION.set(
            round(active / budget.max_concurrent, 3),
            dimension="concurrency")
        FLEET_BUDGET_UTILIZATION.set(
            round(fleet_rate / budget.fleet_bps, 3)
            if budget.fleet_bps > 0 else 0.0,
            dimension="bandwidth")

    def _finish(self, cluster: Cluster, plan: MigrationPlan,
                records: list[dict], budget: FleetBudget,
                t: float) -> Result:
        failed = [r for r in records if r["state"] == FAILED]
        verdict = (MigrationPlanPhase.PARTIALLY_FAILED if failed
                   else MigrationPlanPhase.SUCCEEDED)
        started = plan.status.started_at or t
        makespan = round(max(0.0, t - started), 3)
        reasons = "; ".join(f"{r['pod']}: {r['reason']}"
                            for r in failed)[:500]
        self._set_phase(
            cluster, plan, verdict,
            "AllMembersTerminal",
            (f"{len(records) - len(failed)}/{len(records)} migrated"
             + (f" — failed: {reasons}" if reasons else "")),
            finished_at=t, makespan_seconds=makespan)
        FLEET_PLANS.inc(verdict=verdict.value)
        FLEET_MAKESPAN_SECONDS.set(makespan)
        FLEET_CONCURRENT.set(0)
        for cls in PRIORITY_CLASSES:
            FLEET_QUEUE_DEPTH.set(0, priority=cls)
        plan.status.phase = verdict
        plan.status.finished_at = t
        plan.status.makespan_seconds = makespan
        self._publish_snapshot(plan, budget=budget, now_t=t)
        with self._lock:
            self._budgets.pop(
                (plan.metadata.namespace, plan.metadata.name), None)
        log.info("fleet: plan %s/%s finished %s (makespan %.1fs)",
                 plan.metadata.namespace, plan.metadata.name,
                 verdict.value, makespan)
        return Result()

    def _publish_snapshot(self, plan: MigrationPlan,
                          budget: FleetBudget | None = None,
                          now_t: float | None = None) -> None:
        """Atomically replace the plan's fleet-view snapshot (the
        `gritscope watch --plan` feed) in GRIT_FLEET_STATUS_DIR. Same
        contract as the progress snapshot: throttle-free (reconciles
        are already paced), never raises — observability must not take
        down the control plane. Live token balances ride only HERE
        (file writes bump no resourceVersion — see budget.snapshot)."""
        status_dir = str(config.FLEET_STATUS_DIR.get())
        if not status_dir:
            return
        budget_rec = dict(plan.status.budget)
        if budget is not None:
            budget_rec.update(budget.tokens_snapshot(
                now=now_t if now_t is not None else now()))
        rec = {
            "plan": plan.metadata.name,
            "namespace": plan.metadata.namespace,
            "phase": (plan.status.phase.value
                      if plan.status.phase is not None else ""),
            "pods": plan.status.pods,
            "budget": budget_rec,
            "startedAt": plan.status.started_at,
            "finishedAt": plan.status.finished_at,
            "makespanSeconds": plan.status.makespan_seconds,
            "updatedAt": round(now(), 3),
        }
        path = os.path.join(status_dir, fleet_status_filename(
            plan.metadata.namespace, plan.metadata.name))
        try:
            os.makedirs(status_dir, exist_ok=True)
            atomic_write_json(path, rec)
        except OSError as exc:
            log.warning("fleet snapshot %s unwritable: %s", path, exc)
