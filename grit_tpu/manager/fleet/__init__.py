"""Fleet migration scheduler (ROADMAP item 3): MigrationPlan expansion.

Everything before this package migrates ONE pod per operator action;
production means draining a whole node pool under live traffic — many
concurrent migrations competing for links, destinations, and blackout
windows. The run-time CRIU migration literature (PAPERS.md) treats
*which pod moves where and when* as the hard half of live migration,
and the DMTCP-at-NERSC experience shows fleet-scale checkpointing lives
or dies on scheduling and I/O budgeting, not the per-process dump.

Three pure, independently-testable cores plus the controller that
drives them:

- :mod:`binpack` — the topology/HBM-aware destination chooser (best
  fit over plan-declared capacity; no fit queues, never fails);
- :mod:`budget` — the fleet token bucket (refill/borrow/ceiling math)
  enforcing global migration concurrency and per-link bandwidth
  budgets, actuated per member through byte shaping
  (``GRIT_MIRROR_MAX_INFLIGHT_MB``);
- :mod:`priority` — annotation-declared priority classes ordering the
  admission queue (latency-critical preempts QUEUED slots on arrival;
  in-flight migrations are never preempted);
- :mod:`plan_controller` — the MigrationPlan reconciler expanding the
  plan into a rolling wave of ordinary Checkpoint CRs, folding member
  outcomes into ``status.pods[]``, riding the existing abort machine
  for failed members (bounded plan-level retry), and publishing the
  ``.grit-fleet-*.json`` snapshot ``gritscope watch --plan`` renders.
"""

from grit_tpu.manager.fleet.binpack import (  # noqa: F401
    Candidate,
    Placement,
    choose_destination,
)
from grit_tpu.manager.fleet.budget import (  # noqa: F401
    FleetBudget,
    TokenBucket,
)
from grit_tpu.manager.fleet.plan_controller import (  # noqa: F401
    MigrationPlanController,
    plan_member_checkpoint_name,
)
from grit_tpu.manager.fleet.priority import (  # noqa: F401
    order_queue,
    pod_priority,
)
