"""Topology/HBM-aware bin-packing destination chooser.

Pure functions over plan-declared capacity: the controller rebuilds the
used-capacity map from ``status.pods[]`` every reconcile (level
triggered, manager-restart safe) and asks for one placement at a time.

Semantics, in the order they bite:

- a destination the controller marked **rejected** this pass (unready
  node, armed ``fleet.place`` fault) is skipped;
- **topology**: when both the member and the destination declare one
  (``grit.dev/tpu-topology`` pod annotation vs the destination's
  ``topology`` field) they must match — restoring a 2x2-sharded
  snapshot onto a 2x4 host is exactly the chip-compat constraint the
  restore side enforces, surfaced at planning time instead of at place
  time;
- **capacity**: the summed HBM demand of members already placed on the
  destination plus this member's must stay within ``capacity_gb``
  (0 = unbounded — capacity not modeled for that node);
- among the destinations that fit, **best fit** wins: the one left with
  the least remaining capacity, so big members retain the big holes
  (classic best-fit-decreasing when the controller feeds the queue in
  priority order). Unbounded destinations are chosen only when no
  bounded one fits — declared capacity is information the packer must
  not waste. Ties break by node name for determinism.

No fit is a **Placement(node_name="")** with the reason — the member
stays Queued; capacity exhaustion must never fail a pod (ISSUE
satellite: "no-fit → queued not failed").
"""

from __future__ import annotations

from dataclasses import dataclass

#: Placement outcome reasons — a closed vocabulary (the placements
#: metric labels by it and status.pods[].reason carries it).
PLACED = "Placed"
NO_FIT = "NoCapacity"
TOPOLOGY_MISMATCH = "TopologyMismatch"
REJECTED = "DestinationRejected"


@dataclass(frozen=True)
class Candidate:
    """One plan-declared destination, as the packer sees it."""

    node_name: str
    capacity_gb: float = 0.0  # 0 = unbounded
    topology: str = ""


@dataclass(frozen=True)
class Placement:
    """One placement decision. ``node_name`` empty = not placed;
    ``reason`` then says why (the member stays Queued either way)."""

    node_name: str
    reason: str

    @property
    def placed(self) -> bool:
        return bool(self.node_name)


def remaining_gb(candidate: Candidate, used_gb: float) -> float:
    """Capacity left on ``candidate`` after ``used_gb`` is committed;
    ``float("inf")`` for unbounded candidates."""
    if candidate.capacity_gb <= 0:
        return float("inf")
    return candidate.capacity_gb - used_gb


def choose_destination(
    demand_gb: float,
    topology: str,
    candidates: list[Candidate],
    used_gb: dict[str, float],
    rejected: frozenset[str] | set[str] = frozenset(),
) -> Placement:
    """Best-fit placement of one member.

    ``used_gb`` maps node name -> GB already committed there (members
    Migrating or Succeeded — an aborted member's pod went back to its
    source, so its reservation is NOT in the map). Returns the tightest
    fitting candidate, preferring bounded capacity over unbounded."""
    fits: list[tuple[float, str]] = []
    saw_topology_mismatch = False
    saw_rejected = False
    for cand in candidates:
        if cand.node_name in rejected:
            saw_rejected = True
            continue
        if topology and cand.topology and topology != cand.topology:
            saw_topology_mismatch = True
            continue
        left = remaining_gb(cand, used_gb.get(cand.node_name, 0.0))
        if left < demand_gb:
            continue
        fits.append((left - demand_gb, cand.node_name))
    if fits:
        # Tightest remaining capacity first; inf (unbounded) naturally
        # sorts last, so declared capacity is consumed before the
        # packer falls back to nodes it knows nothing about.
        fits.sort()
        return Placement(node_name=fits[0][1], reason=PLACED)
    if saw_topology_mismatch and not any(
            c.node_name not in rejected and not (
                topology and c.topology and topology != c.topology)
            for c in candidates):
        return Placement(node_name="", reason=TOPOLOGY_MISMATCH)
    if saw_rejected and all(c.node_name in rejected for c in candidates):
        return Placement(node_name="", reason=REJECTED)
    return Placement(node_name="", reason=NO_FIT)
