"""Lease-based leader election for the manager.

Parity: reference ``cmd/grit-manager/app/manager.go`` enables
controller-runtime leader election with a coordination/v1 Lease
(LeaderElectionResourceLock "leases", namespace ``kaito-workspace``); this
is the client-go leaderelection loop distilled: acquire-or-renew a Lease by
optimistic-concurrency writes, step down by letting it expire.

Works against any apiserver speaking the generic REST the
:class:`grit_tpu.kube.client.KubeApi` transport uses (the test suite runs
it against the in-process fake)."""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable

from grit_tpu.kube.client import KubeApi
from grit_tpu.kube.cluster import Conflict, NotFound

LEASE_PATH = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"


def _now_micro() -> str:
    # Real microsecond precision: observers key expiry off renewTime *changes*,
    # so a whole-second stamp would make sub-second renewals look stalled.
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ"
    )


class LeaderElector:
    """Acquire/renew loop for one Lease.

    on_started_leading fires (in the elector thread) when the lease is won;
    on_stopped_leading fires if a renewal fails hard (another holder took
    over) — the caller should stop its controllers then.
    """

    def __init__(
        self,
        api: KubeApi,
        *,
        lease_name: str = "grit-manager",
        namespace: str = "grit-system",
        identity: str | None = None,
        lease_duration: float = 15.0,
        renew_interval: float = 5.0,
        renew_deadline: float | None = None,
        on_started_leading: Callable[[], None] = lambda: None,
        on_stopped_leading: Callable[[], None] = lambda: None,
    ) -> None:
        self.api = api
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"grit-manager-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        # Self-deposition deadline on transient API errors. Strictly less
        # than lease_duration (client-go RenewDeadline) so a partitioned
        # leader steps down BEFORE an observer may legitimately seize the
        # lease — otherwise both report leadership for up to a retry tick.
        self.renew_deadline = (
            renew_deadline if renew_deadline is not None
            else lease_duration * 2.0 / 3.0
        )
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._leading = threading.Event()
        self._thread: threading.Thread | None = None
        # (holder, renewTime) last seen + local monotonic time when first
        # observed — expiry is judged against OUR clock from that observation
        # (client-go leaderelection semantics; advisor r2: trusting the
        # holder's renewTime makes clock skew > leaseDuration split-brain).
        self._observed: tuple[tuple[str, str], float] | None = None

    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(
            target=self._run, name="grit-leader-elector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # Best-effort release so a successor acquires immediately.
        if self._leading.is_set():
            self._leading.clear()
            try:
                lease = self._get()
                if lease and self._holder(lease) == self.identity:
                    spec = lease.setdefault("spec", {})
                    spec["holderIdentity"] = ""
                    self._put(lease)
            except (NotFound, Conflict, Exception):  # noqa: BLE001
                pass

    def wait_for_leadership(self, timeout: float | None = None) -> bool:
        return self._leading.wait(timeout)

    # -- internals ---------------------------------------------------------------

    def _path(self, name: str | None = None) -> str:
        base = LEASE_PATH.format(ns=self.namespace)
        return f"{base}/{name}" if name else base

    def _get(self) -> dict | None:
        try:
            return self.api.request("GET", self._path(self.lease_name))
        except NotFound:
            return None

    def _put(self, lease: dict) -> dict:
        return self.api.request("PUT", self._path(self.lease_name), body=lease)

    @staticmethod
    def _holder(lease: dict) -> str:
        return (lease.get("spec") or {}).get("holderIdentity") or ""

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec") or {}
        # Fall back to our own duration when the holder published none (or a
        # sub-second one rounded to zero at test scale).
        duration = spec.get("leaseDurationSeconds") or self.lease_duration
        key = (self._holder(lease), spec.get("renewTime") or "")
        now = time.monotonic()
        if self._observed is None or self._observed[0] != key:
            # Holder or renewTime changed since we last looked: the lease is
            # live as of now; start the expiry clock locally.
            self._observed = (key, now)
            return False
        return now - self._observed[1] > duration

    def _try_acquire_or_renew(self) -> bool:
        lease = self._get()
        if lease is None:
            body = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.lease_name, "namespace": self.namespace},
                "spec": self._spec(acquire=True, transitions=0),
            }
            try:
                self.api.request("POST", self._path(), body=body)
                return True
            except Exception:  # noqa: BLE001 - lost the creation race
                return False
        holder = self._holder(lease)
        if holder == self.identity:
            lease["spec"].update(self._spec(acquire=False,
                                            transitions=lease["spec"].get("leaseTransitions", 0)))
            self._scrub_duration(lease)
            try:
                self._put(lease)
                return True
            except (Conflict, NotFound):
                return False
        if holder and not self._expired(lease):
            return False
        # free or expired: take it over
        transitions = (lease.get("spec") or {}).get("leaseTransitions", 0) + 1
        lease["spec"] = {**(lease.get("spec") or {}),
                         **self._spec(acquire=True, transitions=transitions)}
        self._scrub_duration(lease)
        try:
            self._put(lease)
            return True
        except (Conflict, NotFound):
            return False

    def _scrub_duration(self, lease: dict) -> None:
        """When our _spec omits leaseDurationSeconds (sub-second test scale),
        drop any stale value merged in from the previous holder — observers
        judge expiry by it."""
        if int(self.lease_duration) <= 0:
            lease["spec"].pop("leaseDurationSeconds", None)

    def _spec(self, *, acquire: bool, transitions: int) -> dict:
        spec = {
            "holderIdentity": self.identity,
            "renewTime": _now_micro(),
            "leaseTransitions": transitions,
        }
        if int(self.lease_duration) > 0:  # sub-second (test scale): omit
            spec["leaseDurationSeconds"] = int(self.lease_duration)
        if acquire:
            spec["acquireTime"] = _now_micro()
        return spec

    def _run(self) -> None:
        last_ok = time.monotonic()
        while not self._stop.is_set():
            indeterminate = False
            try:
                ok = self._try_acquire_or_renew()
            except Exception:  # noqa: BLE001 - transient API failure
                ok = False
                indeterminate = True
            now = time.monotonic()
            if ok:
                last_ok = now
                if not self._leading.is_set():
                    self._leading.set()
                    self.on_started_leading()
            elif self._leading.is_set() and (
                # Definitive loss (another holder / lease gone) drops
                # leadership immediately; a transient API error only does so
                # once renewal has failed for the renew deadline — client-go
                # retries inside RenewDeadline rather than treating one
                # apiserver blip as deposition.
                not indeterminate or now - last_ok > self.renew_deadline
            ):
                self._leading.clear()
                self.on_stopped_leading()
            self._stop.wait(
                self.renew_interval if ok else min(self.renew_interval, 2.0)
            )
