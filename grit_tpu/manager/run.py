"""Deployable manager assembly — the analogue of reference ``Run()``.

Parity: reference ``cmd/grit-manager/app/manager.go:75-189`` assembles the
apiserver client, leader election, the TLS webhook server (cert re-read from
the webhook Secret on handshake), metrics/healthz, and the controller set
into one process. :class:`ManagerRuntime` is that assembly for this
framework: every ingredient already exists (`KubeCluster`, `WebhookServer`,
`LeaderElector`, `SecretController`, `build_manager`) — this class wires
them in the reference's order:

1. ensure the webhook cert Secret exists (every replica; create is
   idempotent) so TLS serving can start before leadership is decided —
   the webhook Service load-balances across *all* replicas, leader or not;
2. start the AdmissionReview HTTPS server;
3. start controllers immediately, or gate them on winning the Lease when
   leader election is enabled. Losing leadership is fatal (controller-runtime
   semantics: the process exits and its replacement re-elects).
"""

from __future__ import annotations

import socket
import threading
import uuid

from grit_tpu.kube.controller import ControllerManager, Request
from grit_tpu.manager.leader import LeaderElector
from grit_tpu.manager.manager import build_manager
from grit_tpu.manager.secret_controller import (
    HAVE_CRYPTOGRAPHY,
    SecretController,
    WEBHOOK_SECRET_NAME,
    WEBHOOK_SECRET_NAMESPACE,
)
from grit_tpu.manager.webhook_server import WebhookServer


class ManagerRuntime:
    """One deployable grit-manager replica over a real-apiserver adapter.

    ``cluster`` is a :class:`grit_tpu.kube.client.KubeCluster` (or anything
    exposing the same surface incl. ``.api``). For the in-memory cluster use
    :func:`grit_tpu.manager.manager.build_manager` directly — admission runs
    locally there and no TLS/lease machinery applies.
    """

    def __init__(
        self,
        cluster,
        *,
        webhook_port: int = 10350,
        webhook_tls: bool = True,
        enable_leader_election: bool = False,
        lease_namespace: str = WEBHOOK_SECRET_NAMESPACE,
        identity: str | None = None,
        lease_duration: float = 15.0,
        renew_interval: float = 5.0,
        workers_per_controller: int = 2,
    ) -> None:
        self.cluster = cluster
        self.webhook_port = webhook_port
        self.webhook_tls = webhook_tls
        self.enable_leader_election = enable_leader_election
        self.identity = identity or f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        self.workers_per_controller = workers_per_controller
        self.lost_leadership = threading.Event()
        self.webhooks: WebhookServer | None = None
        self.elector: LeaderElector | None = None
        self.manager: ControllerManager = build_manager(cluster)
        self._controllers_started = threading.Event()
        if enable_leader_election:
            self.elector = LeaderElector(
                cluster.api,
                namespace=lease_namespace,
                identity=self.identity,
                lease_duration=lease_duration,
                renew_interval=renew_interval,
                on_started_leading=self._start_controllers,
                on_stopped_leading=self._on_lost_leadership,
            )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ManagerRuntime":
        # Every replica ensures the webhook PKI exists before serving TLS;
        # the SecretController inside the manager keeps rotating it once this
        # replica leads (reference: knative-style ensure-at-startup + the
        # 85%-renewal loop, secret_controller.go:137-184).
        SecretController().reconcile(
            self.cluster,
            Request(WEBHOOK_SECRET_NAMESPACE, WEBHOOK_SECRET_NAME),
        )
        if self.webhook_tls and not HAVE_CRYPTOGRAPHY:
            # Never silently downgrade admission to plaintext: without the
            # PKI dep the TLS webhook server simply does not come up, and
            # the rest of the manager (controllers, leases, metrics) runs.
            import logging  # noqa: PLC0415

            logging.getLogger(__name__).warning(
                "webhook server disabled: TLS requested but the optional "
                "'cryptography' package is not installed (no webhook PKI)")
            self.webhooks = None
        else:
            self.webhooks = WebhookServer(
                self.cluster, port=self.webhook_port, tls=self.webhook_tls
            )
        if self.elector is not None:
            self.elector.start()
        else:
            self._start_controllers()
        # Periodic observability sampler: re-derives the heartbeat-age
        # gauge from the last observed beat (a scrape between watchdog
        # polls must not read a stale age) plus the default refreshers.
        from grit_tpu.manager import watchdog  # noqa: PLC0415
        from grit_tpu.obs import sampler as obs_sampler  # noqa: PLC0415

        sampler = obs_sampler.default_sampler()
        sampler.register("heartbeat-age", watchdog.sample_heartbeat_age)
        sampler.start()
        return self

    def _start_controllers(self) -> None:
        if not self._controllers_started.is_set():
            self._controllers_started.set()
            self.manager.start(self.workers_per_controller)

    def _on_lost_leadership(self) -> None:
        # Fatal by design: a replica that lost its lease must not keep
        # reconciling next to the new leader. The entrypoint exits on this
        # event; the Deployment restarts the pod which re-elects.
        self.manager.stop()
        self.lost_leadership.set()

    @property
    def is_leader(self) -> bool:
        if self.elector is None:
            return self._controllers_started.is_set()
        return self.elector.is_leader

    def wait_for_leadership(self, timeout: float | None = None) -> bool:
        if self.elector is None:
            return True
        return self.elector.wait_for_leadership(timeout)

    def stop(self) -> None:
        if self.elector is not None:
            self.elector.stop()  # releases the Lease for fast failover
        self.manager.stop()
        if self.webhooks is not None:
            self.webhooks.shutdown()
        if hasattr(self.cluster, "stop_watches"):
            self.cluster.stop_watches()
        from grit_tpu.obs import sampler as obs_sampler  # noqa: PLC0415

        obs_sampler.stop()
