"""Manager assembly: controllers + webhooks over one cluster handle.

Parity: reference registries ``pkg/gritmanager/controllers/controllers.go``
/ ``pkg/gritmanager/webhooks/webhooks.go``. This wires the controller and
webhook set over one cluster handle; the full deployable process — TLS
webhook serving and Lease leader election on top of this — is
:class:`grit_tpu.manager.run.ManagerRuntime` (reference
``cmd/grit-manager/app/manager.go:75-189``).
"""

from __future__ import annotations

from grit_tpu.kube.cluster import Cluster
from grit_tpu.kube.controller import ControllerManager
from grit_tpu.manager.agentmanager import AgentManager
from grit_tpu.manager.checkpoint_controller import CheckpointController
from grit_tpu.manager.drain_controller import DrainController
from grit_tpu.manager.fleet import MigrationPlanController
from grit_tpu.manager.preemption_watcher import PreemptionWatcher
from grit_tpu.manager.restore_controller import RestoreController
from grit_tpu.manager.restoreset_controller import RestoreSetController
from grit_tpu.manager.secret_controller import SecretController
from grit_tpu.manager.webhooks import register_webhooks


def build_manager(cluster: Cluster, *, with_cert_controller: bool = True) -> ControllerManager:
    """Build the full grit-manager control plane against ``cluster``."""

    agent_manager = AgentManager(cluster)
    register_webhooks(cluster, agent_manager)
    mgr = ControllerManager(cluster)
    if with_cert_controller:
        mgr.add_controller(SecretController())
    mgr.add_controller(CheckpointController(agent_manager))
    mgr.add_controller(RestoreController(agent_manager))
    mgr.add_controller(DrainController())
    mgr.add_controller(PreemptionWatcher())
    mgr.add_controller(MigrationPlanController())
    mgr.add_controller(RestoreSetController())
    return mgr
