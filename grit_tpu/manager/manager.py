"""Manager assembly: controllers + webhooks over one cluster handle.

Parity: reference ``cmd/grit-manager/app/manager.go:75-189`` (Run) and the
registries ``pkg/gritmanager/controllers/controllers.go`` /
``pkg/gritmanager/webhooks/webhooks.go``. TLS serving and leader election are
deployment concerns handled by the real-cluster adapter (see deploy/); the
in-process manager wires the same controller/webhook set.
"""

from __future__ import annotations

from grit_tpu.kube.cluster import Cluster
from grit_tpu.kube.controller import ControllerManager
from grit_tpu.manager.agentmanager import AgentManager
from grit_tpu.manager.checkpoint_controller import CheckpointController
from grit_tpu.manager.restore_controller import RestoreController
from grit_tpu.manager.secret_controller import SecretController
from grit_tpu.manager.webhooks import register_webhooks


def build_manager(cluster: Cluster, *, with_cert_controller: bool = True) -> ControllerManager:
    """Build the full grit-manager control plane against ``cluster``."""

    agent_manager = AgentManager(cluster)
    register_webhooks(cluster, agent_manager)
    mgr = ControllerManager(cluster)
    if with_cert_controller:
        mgr.add_controller(SecretController())
    mgr.add_controller(CheckpointController(agent_manager))
    mgr.add_controller(RestoreController(agent_manager))
    return mgr
