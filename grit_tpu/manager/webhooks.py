"""Admission webhooks: pod mutating, checkpoint validating, restore
mutating+validating.

Parity: reference ``pkg/gritmanager/webhooks/{pod,checkpoint,restore}``.
"""

from __future__ import annotations

from grit_tpu.api.constants import (
    CHECKPOINT_DATA_PATH_ANNOTATION,
    COMPILE_CACHE_DEFAULT_DIR,
    COMPILE_CACHE_ENV,
    MIGRATION_PRIORITY_ANNOTATION,
    POD_SELECTED_ANNOTATION,
    POD_SPEC_HASH_ANNOTATION,
    RESTORE_NAME_ANNOTATION,
)
from grit_tpu.api.types import (
    Checkpoint,
    CheckpointPhase,
    MigrationPlan,
    PRIORITY_CLASSES,
    Restore,
    RestorePhase,
    RestoreSet,
    VERIFIED_SNAPSHOT_PHASES,
)
from grit_tpu.kube.cluster import AdmissionDenied, Cluster, Conflict, NotFound
from grit_tpu.kube.objects import EnvVar, Pod
from grit_tpu.manager.agentmanager import AgentManager
from grit_tpu.manager.util import compute_pod_spec_hash


class PodRestoreWebhook:
    """Mutating webhook on pod CREATE — the restore rendezvous.

    On every pod CREATE (failurePolicy=ignore → registered fail-open,
    reference pod_restore_default.go:119):

    1. find candidate Restores in the pod's namespace: phase unset/Created and
       not yet pod-selected (pod_restore_default.go:54-63);
    2. match by controller ownerRef UID equality (or label selector for
       standalone pods) AND pod-spec FNV hash equality with the hash the
       restore webhook copied from the Checkpoint (:70-91);
    3. atomically claim the Restore by patching
       ``grit.dev/pod-selected=true`` (:101-106) — the patch is the
       concurrency gate: two replicate pods racing will conflict on
       resourceVersion and only one claims;
    4. annotate the pod with ``grit.dev/checkpoint=<hostPath>/<ns>/<ckpt>``
       and ``grit.dev/restore-name`` (:108-114). This annotation is the only
       signal the node runtime sees.
    """

    def __init__(self, agent_manager: AgentManager) -> None:
        self.agent_manager = agent_manager

    def __call__(self, cluster: Cluster, pod: Pod) -> None:
        restores = [
            r for r in cluster.list("Restore", pod.metadata.namespace)
            if r.status.phase in (None, RestorePhase.CREATED)
            and r.metadata.annotations.get(POD_SELECTED_ANNOTATION) != "true"
        ]
        if not restores:
            return
        pod_hash = compute_pod_spec_hash(pod.spec)
        ctrl_ref = pod.metadata.controller_ref()

        for restore in restores:
            if restore.spec.owner_ref is not None and restore.spec.owner_ref.uid:
                if ctrl_ref is None or ctrl_ref.uid != restore.spec.owner_ref.uid:
                    continue
            elif restore.spec.selector is not None:
                if not restore.spec.selector.matches(pod.metadata.labels):
                    continue
            else:
                continue
            expected_hash = restore.metadata.annotations.get(POD_SPEC_HASH_ANNOTATION, "")
            if expected_hash and expected_hash != pod_hash:
                continue

            # Atomic claim: conditional patch fails (Conflict) if another pod
            # admission claimed it concurrently.
            try:
                def claim(r: Restore) -> None:
                    if r.metadata.annotations.get(POD_SELECTED_ANNOTATION) == "true":
                        raise Conflict("already claimed")
                    r.metadata.annotations[POD_SELECTED_ANNOTATION] = "true"

                cluster.patch(
                    "Restore", restore.metadata.name, claim, restore.metadata.namespace,
                )
            except (Conflict, NotFound):
                continue

            ckpt_path = self.agent_manager.host_work_path(
                restore.metadata.namespace, restore.spec.checkpoint_name
            )
            pod.metadata.annotations[CHECKPOINT_DATA_PATH_ANNOTATION] = ckpt_path
            pod.metadata.annotations[RESTORE_NAME_ANNOTATION] = restore.metadata.name
            # The replacement pod joins the migration's trace: the
            # grit.dev/* annotation passthrough carries this into the OCI
            # spec, where the shim picks it up (obs/trace.py contract).
            from grit_tpu.obs import trace  # noqa: PLC0415

            tp = restore.metadata.annotations.get(
                trace.TRACEPARENT_ANNOTATION, "")
            if tp:
                pod.metadata.annotations[trace.TRACEPARENT_ANNOTATION] = tp
            # Make the snapshot's compile-cache carry work out of the box:
            # the restored workload seeds this dir from the checkpoint
            # (restore_snapshot → hook.py). Operator-set values win.
            for container in pod.spec.containers:
                if not any(e.name == COMPILE_CACHE_ENV
                           for e in container.env):
                    container.env.append(EnvVar(
                        name=COMPILE_CACHE_ENV,
                        value=COMPILE_CACHE_DEFAULT_DIR,
                    ))
            return


class CheckpointValidatingWebhook:
    """CREATE-time validation (reference checkpoint_webhook.go:34-76):
    target pod exists, is Running and scheduled; its node is Ready; the
    spec'd PVC is Bound."""

    def __call__(self, cluster: Cluster, ckpt: Checkpoint) -> None:
        ns = ckpt.metadata.namespace
        # Gang slice CRs (spec.sliceHosts > 1): pod_name is the per-host
        # PREFIX — every host's pod ("<prefix>-<k>", the JobSet
        # convention) must pass the same gates, or the gang is doomed
        # at admission time rather than mid-quiesce.
        pod_names = ([f"{ckpt.spec.pod_name}-{k}"
                      for k in range(ckpt.spec.slice_hosts)]
                     if (ckpt.spec.slice_hosts or 0) > 1
                     else [ckpt.spec.pod_name])
        for pod_name in pod_names:
            pod = cluster.try_get("Pod", pod_name, ns)
            if pod is None:
                raise AdmissionDenied(f"pod {ns}/{pod_name} not found")
            if pod.status.phase != "Running" or not pod.spec.node_name:
                raise AdmissionDenied(
                    f"pod {ns}/{pod_name} is not running/scheduled "
                    f"(phase={pod.status.phase})"
                )
            node = cluster.try_get("Node", pod.spec.node_name, "")
            if node is None or not node.status.ready():
                raise AdmissionDenied(
                    f"node {pod.spec.node_name} is not ready")
        if ckpt.spec.volume_claim is not None:
            pvc = cluster.try_get(
                "PersistentVolumeClaim", ckpt.spec.volume_claim.claim_name, ns
            )
            if pvc is None or pvc.status.phase != "Bound":
                raise AdmissionDenied(
                    f"pvc {ns}/{ckpt.spec.volume_claim.claim_name} is not bound"
                )


class RestoreMutatingWebhook:
    """Copies ``Checkpoint.status.podSpecHash`` onto the Restore as the
    ``grit.dev/pod-spec-hash`` annotation (reference restore_webhook.go:33-51)
    so the pod webhook can match without a Checkpoint lookup."""

    def __call__(self, cluster: Cluster, restore: Restore) -> None:
        ckpt = cluster.try_get(
            "Checkpoint", restore.spec.checkpoint_name, restore.metadata.namespace
        )
        if ckpt is not None and ckpt.status.pod_spec_hash:
            restore.metadata.annotations[POD_SPEC_HASH_ANNOTATION] = ckpt.status.pod_spec_hash


class RestoreValidatingWebhook:
    """The referenced Checkpoint must exist and be phase
    Checkpointed/Submitting/Submitted (reference restore_webhook.go:53-77)."""

    _OK = VERIFIED_SNAPSHOT_PHASES

    def __call__(self, cluster: Cluster, restore: Restore) -> None:
        if not restore.spec.checkpoint_name:
            raise AdmissionDenied("spec.checkpointName is required")
        if restore.spec.owner_ref is None and restore.spec.selector is None:
            raise AdmissionDenied("one of spec.ownerRef / spec.selector is required")
        ckpt = cluster.try_get(
            "Checkpoint", restore.spec.checkpoint_name, restore.metadata.namespace
        )
        if ckpt is None:
            raise AdmissionDenied(
                f"checkpoint {restore.metadata.namespace}/{restore.spec.checkpoint_name} "
                "not found"
            )
        if ckpt.status.phase not in self._OK:
            raise AdmissionDenied(
                f"checkpoint {ckpt.metadata.name} is not checkpointed "
                f"(phase={ckpt.status.phase})"
            )


class MigrationPlanValidatingWebhook:
    """CREATE-time validation of a fleet MigrationPlan: a plan doomed
    at admission time (missing pods, no claim, no usable destination,
    nonsense budgets) must be refused loudly NOW, not discovered
    member-by-member mid-wave. Per-member liveness is still re-checked
    level-triggered at admission — this gate bounds operator error,
    not cluster drift."""

    def __call__(self, cluster: Cluster, plan: MigrationPlan) -> None:
        ns = plan.metadata.namespace
        if not plan.spec.members:
            raise AdmissionDenied("spec.members must name at least one pod")
        seen: set[str] = set()
        for member in plan.spec.members:
            if not member.pod_name:
                raise AdmissionDenied("spec.members[].podName is required")
            if member.pod_name in seen:
                raise AdmissionDenied(
                    f"pod {member.pod_name} listed twice in spec.members")
            seen.add(member.pod_name)
            pod = cluster.try_get("Pod", member.pod_name, ns)
            if pod is None:
                raise AdmissionDenied(f"pod {ns}/{member.pod_name} not found")
            if pod.status.phase != "Running" or not pod.spec.node_name:
                raise AdmissionDenied(
                    f"pod {ns}/{member.pod_name} is not running/scheduled "
                    f"(phase={pod.status.phase})")
            prio = pod.metadata.annotations.get(
                MIGRATION_PRIORITY_ANNOTATION, "")
            if prio and prio not in PRIORITY_CLASSES:
                raise AdmissionDenied(
                    f"pod {ns}/{member.pod_name} declares unknown "
                    f"migration priority {prio!r} (one of "
                    f"{', '.join(PRIORITY_CLASSES)})")
            claim = member.volume_claim or plan.spec.volume_claim
            if claim is None:
                raise AdmissionDenied(
                    f"pod {member.pod_name} has no volume claim (member "
                    "override or spec.volumeClaim)")
            pvc = cluster.try_get("PersistentVolumeClaim",
                                  claim.claim_name, ns)
            if pvc is None or pvc.status.phase != "Bound":
                raise AdmissionDenied(
                    f"pvc {ns}/{claim.claim_name} is not bound")
        if not plan.spec.destinations:
            raise AdmissionDenied(
                "spec.destinations must name at least one candidate node")
        dest_seen: set[str] = set()
        for dest in plan.spec.destinations:
            if not dest.node_name:
                raise AdmissionDenied(
                    "spec.destinations[].nodeName is required")
            if dest.node_name in dest_seen:
                raise AdmissionDenied(
                    f"destination {dest.node_name} listed twice")
            dest_seen.add(dest.node_name)
            if dest.capacity_gb < 0:
                raise AdmissionDenied(
                    f"destination {dest.node_name}: capacityGb must be "
                    ">= 0 (0 = unbounded)")
            node = cluster.try_get("Node", dest.node_name, "")
            if node is None:
                raise AdmissionDenied(
                    f"destination node {dest.node_name} not found")
        budget = plan.spec.budget
        if budget.link_bandwidth_bps < 0 or budget.fleet_bandwidth_bps < 0:
            raise AdmissionDenied(
                "spec.budget bandwidth fields must be >= 0 "
                "(0 = use the GRIT_FLEET_* default)")


class RestoreSetValidatingWebhook:
    """CREATE-time validation of a serving RestoreSet: a fan-out doomed
    at admission (missing/unverified snapshot, no clone targeting, a
    replica count that would stampede the source PVC) must be refused
    loudly NOW, not discovered clone-by-clone. The snapshot phase is
    still re-checked level-triggered by the controller — this gate
    bounds operator error, not cluster drift."""

    _OK = VERIFIED_SNAPSHOT_PHASES

    def __call__(self, cluster: Cluster, rs: RestoreSet) -> None:
        from grit_tpu.api import config  # noqa: PLC0415

        if not rs.spec.snapshot_ref:
            raise AdmissionDenied("spec.snapshotRef is required")
        if rs.spec.replicas < 1:
            raise AdmissionDenied("spec.replicas must be >= 1")
        ceiling = max(1, int(config.SERVE_MAX_CLONES.get()))
        if rs.spec.replicas > ceiling:
            raise AdmissionDenied(
                f"spec.replicas {rs.spec.replicas} exceeds "
                f"{config.SERVE_MAX_CLONES.name}={ceiling}")
        if rs.spec.template.owner_ref is None \
                and rs.spec.template.selector is None:
            raise AdmissionDenied(
                "one of spec.template.ownerRef / spec.template.selector "
                "is required")
        ckpt = cluster.try_get(
            "Checkpoint", rs.spec.snapshot_ref, rs.metadata.namespace)
        if ckpt is None:
            raise AdmissionDenied(
                f"checkpoint {rs.metadata.namespace}/{rs.spec.snapshot_ref} "
                "not found")
        if ckpt.status.phase not in self._OK:
            raise AdmissionDenied(
                f"checkpoint {ckpt.metadata.name} holds no verified "
                f"snapshot to clone (phase={ckpt.status.phase})")


def register_webhooks(cluster: Cluster, agent_manager: AgentManager) -> None:
    """Assemble the webhook set (reference webhooks/webhooks.go:14-24,
    plus the fleet MigrationPlan and serving RestoreSet gates — both
    TPU-native additions)."""

    cluster.register_mutating_webhook("Pod", PodRestoreWebhook(agent_manager), fail_open=True)
    cluster.register_validating_webhook("Checkpoint", CheckpointValidatingWebhook())
    cluster.register_mutating_webhook("Restore", RestoreMutatingWebhook())
    cluster.register_validating_webhook("Restore", RestoreValidatingWebhook())
    cluster.register_validating_webhook(
        "MigrationPlan", MigrationPlanValidatingWebhook())
    cluster.register_validating_webhook(
        "RestoreSet", RestoreSetValidatingWebhook())
