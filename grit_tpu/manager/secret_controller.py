"""Webhook PKI controller: self-contained cert issuance + rotation.

Parity: reference ``pkg/gritmanager/controllers/secret/secret_controller.go``
— generates the webhook server key/cert/CA into the webhook Secret
(generateSecret :137-154), renews when ≥85% of validity has elapsed
(shouldRenewCert :156-184), and patches the CA bundle into the
Validating/Mutating webhook configurations (updateWebhookConfigurations
:186-234). Uses the ``cryptography`` package (the reference uses knative's
cert helpers).
"""

from __future__ import annotations

import datetime
from collections.abc import Callable

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # optional dep: only the PKI paths need it, and the
    # manager stack must stay importable (and every non-webhook
    # controller usable) on hosts without it.
    x509 = hashes = serialization = rsa = NameOID = None  # type: ignore
    HAVE_CRYPTOGRAPHY = False

from grit_tpu.kube.cluster import AlreadyExists, Cluster, NotFound
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import ObjectMeta, Secret

WEBHOOK_SECRET_NAME = "grit-webhook-certs"
WEBHOOK_SECRET_NAMESPACE = "grit-system"
VALIDATING_WEBHOOK_CONFIG = "grit-validating-webhook-configuration"
MUTATING_WEBHOOK_CONFIG = "grit-mutating-webhook-configuration"
CERT_VALIDITY_DAYS = 365
RENEW_FRACTION = 0.85  # reference shouldRenewCert :156-184

SERVER_KEY = "server-key.pem"
SERVER_CERT = "server-cert.pem"
CA_CERT = "ca-cert.pem"


def _generate_certs(
    service_dns: str, validity_days: int = CERT_VALIDITY_DAYS,
    not_before: datetime.datetime | None = None,
) -> dict[str, bytes]:
    """Self-signed CA + server cert for the webhook service DNS name."""

    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            "webhook PKI needs the optional 'cryptography' package")
    if not_before is None:
        not_before = datetime.datetime.now(datetime.timezone.utc)
    not_after = not_before + datetime.timedelta(days=validity_days)

    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "grit-webhook-ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before).not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    srv_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    srv_cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, service_dns)]))
        .issuer_name(ca_name)
        .public_key(srv_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before).not_valid_after(not_after)
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName(service_dns)]), critical=False
        )
        .sign(ca_key, hashes.SHA256())
    )

    return {
        SERVER_KEY: srv_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
        SERVER_CERT: srv_cert.public_bytes(serialization.Encoding.PEM),
        CA_CERT: ca_cert.public_bytes(serialization.Encoding.PEM),
    }


def _should_renew(cert_pem: bytes, at: datetime.datetime | None = None) -> bool:
    """True once ≥85% of the cert's validity window has elapsed (or it can't
    be parsed)."""

    if not HAVE_CRYPTOGRAPHY:
        return True
    try:
        cert = x509.load_pem_x509_certificate(cert_pem)
    except Exception:  # noqa: BLE001
        return True
    if at is None:
        at = datetime.datetime.now(datetime.timezone.utc)
    start = cert.not_valid_before_utc
    end = cert.not_valid_after_utc
    total = (end - start).total_seconds()
    if total <= 0:
        return True
    return (at - start).total_seconds() / total >= RENEW_FRACTION


class SecretController:
    """Reconciles the webhook cert Secret and webhook-config CA bundles."""

    kind = "Secret"

    def __init__(
        self,
        service_dns: str = f"grit-manager-webhook.{WEBHOOK_SECRET_NAMESPACE}.svc",
        now_fn: Callable[[], datetime.datetime] | None = None,
    ) -> None:
        self.service_dns = service_dns
        self._now = now_fn or (lambda: datetime.datetime.now(datetime.timezone.utc))

    def register(self, cluster: Cluster, enqueue: Callable[[Request], None]) -> None:
        # Watch the webhook configurations by fixed name (reference :36-84,
        # 268-294) — recreating them must re-trigger CA patching.
        def on_cfg_event(ev) -> None:
            if ev.name in (VALIDATING_WEBHOOK_CONFIG, MUTATING_WEBHOOK_CONFIG):
                enqueue(Request(WEBHOOK_SECRET_NAMESPACE, WEBHOOK_SECRET_NAME))

        cluster.watch("WebhookConfiguration", on_cfg_event)
        # Kick once at startup.
        enqueue(Request(WEBHOOK_SECRET_NAMESPACE, WEBHOOK_SECRET_NAME))

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        if (req.namespace, req.name) != (WEBHOOK_SECRET_NAMESPACE, WEBHOOK_SECRET_NAME):
            return Result()
        if not HAVE_CRYPTOGRAPHY:
            import logging  # noqa: PLC0415

            logging.getLogger(__name__).warning(
                "secret controller: optional 'cryptography' package not "
                "installed — webhook PKI disabled, certs not provisioned")
            return Result()
        secret = cluster.try_get("Secret", WEBHOOK_SECRET_NAME, WEBHOOK_SECRET_NAMESPACE)
        if secret is None or _should_renew(secret.data.get(SERVER_CERT, b""), self._now()):
            data = _generate_certs(self.service_dns, not_before=self._now())
            if secret is None:
                try:
                    cluster.create(Secret(
                        metadata=ObjectMeta(name=WEBHOOK_SECRET_NAME,
                                            namespace=WEBHOOK_SECRET_NAMESPACE),
                        data=data,
                    ))
                except AlreadyExists:
                    pass
            else:
                cluster.patch(
                    "Secret", WEBHOOK_SECRET_NAME,
                    lambda s: s.data.update(data), WEBHOOK_SECRET_NAMESPACE,
                )
            secret = cluster.get("Secret", WEBHOOK_SECRET_NAME, WEBHOOK_SECRET_NAMESPACE)

        ca = secret.data.get(CA_CERT, b"")
        for cfg_name in (VALIDATING_WEBHOOK_CONFIG, MUTATING_WEBHOOK_CONFIG):
            try:
                cluster.patch(
                    "WebhookConfiguration", cfg_name,
                    lambda cfg: setattr(cfg, "ca_bundle", ca), "",
                )
            except NotFound:
                continue
        # Periodic renewal poll (reference secret_controller.go:119 returns
        # RequeueAfter until the next validity check) — without this the
        # 85%-of-validity rotation would only ever run on external events.
        return Result(requeue_after=self._renewal_check_delay(secret))

    def _renewal_check_delay(self, secret) -> float:
        """Seconds until the next renewal check: 1/10 of remaining validity,
        clamped to [1 h, 24 h]."""

        try:
            cert = x509.load_pem_x509_certificate(secret.data.get(SERVER_CERT, b""))
            remaining = (cert.not_valid_after_utc - self._now()).total_seconds()
        except Exception:  # noqa: BLE001
            return 3600.0
        return max(3600.0, min(remaining / 10.0, 86400.0))
