"""Drain controller: cordoned node → automatic pre-copy live migration.

TPU-native addition with no reference analogue (the reference's migrations
are always operator-initiated Checkpoint CRs; SURVEY §5 "failure
detection"): on GKE, node-pool upgrades and spot/maintenance events cordon
the node before terminating it — exactly the window pre-copy migration is
built for. Pods opt in with the ``grit.dev/migrate-on-drain`` label and
name their checkpoint PVC in the ``grit.dev/drain-volume-claim``
annotation; when their node's ``spec.unschedulable`` flips true, this
controller creates a ``Checkpoint{autoMigration, preCopy}`` per pod and
the ordinary machinery (§3.1/3.2 flow) does the rest: live full dump while
the pod still runs, delta dump + owner-recreated pod on a schedulable
node.

Reconcile is level-triggered and idempotent: the Checkpoint name is a
function of the pod (``drain-<pod>``), an existing CR short-circuits, and
an uncordon simply stops producing new CRs (in-flight migrations finish —
half-migrated state is worse than one extra move).
"""

from __future__ import annotations

import logging
from collections.abc import Callable

from grit_tpu.api.constants import (
    DRAIN_VOLUME_CLAIM_ANNOTATION,
    FIRE_ANNOTATION,
    MIGRATE_ON_DRAIN_LABEL,
    MIGRATION_PRIORITY_ANNOTATION,
    SPOT_NODE_LABELS,
)
from grit_tpu.api.types import (
    PRIORITY_CLASSES,
    STANDBY_PRE_FIRED_PHASES,
    Checkpoint,
    CheckpointPhase,
    CheckpointSpec,
    MigrationPlan,
    MigrationPlanDestination,
    MigrationPlanMember,
    MigrationPlanPhase,
    MigrationPlanSpec,
    VolumeClaimSource,
)
from grit_tpu.kube.cluster import AdmissionDenied, AlreadyExists, Cluster, NotFound
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import ObjectMeta
from grit_tpu.manager.util import agent_job_name
from grit_tpu.obs.metrics import DRAIN_MIGRATIONS, STANDBY_FIRES

log = logging.getLogger(__name__)

# Data-lifecycle default for drain-created Checkpoints: long enough that an
# operator can still restore from the drain checkpoint manually after the
# incident, short enough that repeated drains of a long-lived StatefulSet
# pod don't accumulate PVC payloads under the reused drain-<pod> name.
DRAIN_CHECKPOINT_TTL_SECONDS = 24 * 3600


def drain_checkpoint_name(pod_name: str) -> str:
    return f"drain-{pod_name}"


def drain_plan_name(node_name: str) -> str:
    """The generated MigrationPlan a multi-pod node drain delegates to
    (one per namespace carrying cold-path candidates)."""
    return f"drain-{node_name}"


#: Fire reason the cordon path stamps; uncordon disarms ONLY fires
#: carrying this prefix (a reclaim-notice or operator fire must never
#: be silently cancelled by an unrelated uncordon).
CORDON_FIRE_REASON = "NodeCordoned"


def is_spot_node(node) -> bool:
    """Spot/preemptible capacity, by the cloud's node labels — where the
    reclaim window is measured in seconds and migrate-on-drain pods get
    an always-warm StandbyCheckpoint at schedule time instead of a cold
    Checkpoint at cordon time."""
    labels = node.metadata.labels
    return any(labels.get(k) == "true" for k in SPOT_NODE_LABELS)


class DrainController:
    kind = "Node"

    def __init__(self) -> None:
        # CRs already warned about as non-self-healing Failed, keyed by
        # (ns, name, uid): the metric/log fire once per stuck CR, not once
        # per idempotent node re-scan (reconciles are frequent).
        self._warned_failed: set[tuple[str, str, str]] = set()

    def register(self, cluster: Cluster, enqueue: Callable[[Request], None]) -> None:
        # Secondary watch: a labeled pod appearing on an already-cordoned
        # node (edge: pod created moments before the cordon landed, or the
        # manager restarting mid-drain) must re-trigger its node's scan.
        def on_pod_event(ev) -> None:
            pod = ev.obj
            if pod.metadata.labels.get(MIGRATE_ON_DRAIN_LABEL) != "true":
                return
            if getattr(pod.spec, "node_name", ""):
                enqueue(Request("", pod.spec.node_name))

        cluster.watch("Pod", on_pod_event)

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        node = cluster.try_get("Node", req.name, "")
        if node is None:
            return Result()
        spot = is_spot_node(node)
        cordoned = node.spec.unschedulable
        if not (spot or cordoned):
            return Result()

        # Cold-path candidates: cordoned pods with no existing drain
        # machinery (CR/standby) engaged. They are COLLECTED rather than
        # migrated one by one, so a node carrying several opted-in pods
        # drains as one coordinated MigrationPlan (shared destination
        # choice, fleet budgets) instead of N mutually-unaware CRs; a
        # single candidate keeps the direct drain-<pod> path
        # byte-identical to every PR before this one.
        candidates: list = []
        for pod in cluster.list(
            "Pod", label_selector={MIGRATE_ON_DRAIN_LABEL: "true"}
        ):
            if pod.spec.node_name != req.name:
                continue
            if pod.status.phase != "Running":
                continue
            try:
                cand = self._reconcile_pod(cluster, pod, spot=spot,
                                           cordoned=cordoned)
            except AdmissionDenied as exc:
                # One unmigratable pod (unbound PVC, pod terminating mid-
                # scan) must not abort the loop and block every other
                # opted-in pod on the node.
                log.warning("drain: checkpoint for pod %s/%s denied: %s",
                            pod.metadata.namespace, pod.metadata.name, exc)
                DRAIN_MIGRATIONS.inc(outcome="skipped_admission")
                continue
            if cand is not None:
                candidates.append(cand)
        if candidates:
            by_ns: dict[str, list] = {}
            for pod in candidates:
                by_ns.setdefault(pod.metadata.namespace, []).append(pod)
            for ns, pods in sorted(by_ns.items()):
                try:
                    self._drain_candidates(cluster, req.name, ns, pods)
                except AdmissionDenied as exc:
                    log.warning("drain: plan for node %s ns %s denied: %s",
                                req.name, ns, exc)
                    DRAIN_MIGRATIONS.inc(outcome="skipped_admission")
        return Result()

    def _reconcile_pod(self, cluster: Cluster, pod, *, spot: bool,
                       cordoned: bool):
        """One opted-in pod's drain/standby state machine. Returns the
        pod when it is a COLD-PATH CANDIDATE — cordoned, claim valid,
        no existing CR machinery engaged — for the caller to route
        (direct drain-<pod> CR when alone, a drain MigrationPlan when
        the node carries several); None when handled here.

        Spot nodes arm at SCHEDULE time: an always-warm StandbyCheckpoint
        exists the whole time the pod runs, so the cordon (or the
        preemption watcher's reclaim notice) pays only the final delta.
        Cordon then FIRES the existing standby instead of creating a cold
        ``drain-<pod>`` from scratch; uncordon DISARMS a cordon-fire that
        has not begun firing. Non-spot nodes keep the cold
        cordon-triggered path unchanged."""
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        existing = cluster.try_get("Checkpoint", name, ns)
        standby = (existing is not None and existing.spec.standby
                   and existing.status.pod_uid in
                   ("", pod.metadata.uid))
        if cordoned:
            if standby and existing.status.phase in \
                    STANDBY_PRE_FIRED_PHASES:
                # The fire annotation can land at ANY pre-fired phase —
                # the checkpoint controller forwards it the moment the
                # agent can consume it (level-triggered: a cordon that
                # raced the CR's first reconcile must not be lost).
                self._fire_standby(cluster, existing)
                return None
            # Everything else flows through the cold machinery: a
            # firing/fired standby is an idempotent no-op there, a
            # FAILED standby gets the cold path's self-healing (clear
            # the failed agent Job so the retry runs, or warn loudly),
            # and a stale terminal CR from a previous same-named pod is
            # GC'd — a cordoned pod must never dead-end silently just
            # because its arm died.
            return self._migrate(cluster, pod, create=False)
        # Schedulable (spot) node: keep the pod armed, and roll back a
        # cordon-fire the operator cancelled by uncordoning.
        if standby:
            reason = existing.metadata.annotations.get(FIRE_ANNOTATION, "")
            if reason.startswith(CORDON_FIRE_REASON) \
                    and existing.status.phase in \
                    STANDBY_PRE_FIRED_PHASES:
                self._disarm_standby(cluster, existing)
            return None
        if existing is not None:
            # A cold/stale CR under the drain name: leave the existing
            # machinery (cordon-path _migrate, TTL GC) to its lifecycle;
            # the standby arm waits for the name to free up.
            return None
        self._arm_standby(cluster, pod)
        return None

    def _migrate(self, cluster: Cluster, pod, *, create: bool = True):
        """The cold drain path's existing-CR machinery. With ``create``
        the new drain-<pod> CR is minted here (the pre-plan behavior,
        still used for one-pod drains); without it the pod is RETURNED
        once the machinery concludes a new migration should start, so
        the caller can route it through a MigrationPlan instead."""
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        existing = cluster.try_get("Checkpoint", name, ns)
        if existing is None or existing.status.phase != CheckpointPhase.FAILED:
            # CR healthy/absent: drop any warn-once marker so a LATER
            # relapse into non-self-healing Failed warns again (and the
            # set cannot grow without bound).
            self._warned_failed = {
                k for k in self._warned_failed
                if not (k[0] == ns and k[1] == name)
            }
        if existing is not None:
            # A leftover CR from a PREVIOUS drain of a same-named pod
            # (StatefulSet replicas keep their names) must not suppress
            # this migration forever: if it is terminal and bound to a
            # different pod UID, GC it and migrate the current pod.
            terminal = existing.status.phase in (
                CheckpointPhase.SUBMITTED, CheckpointPhase.FAILED,
            )
            stale = (existing.status.pod_uid
                     and existing.status.pod_uid != pod.metadata.uid)
            if not (terminal and stale):
                if existing.status.phase == CheckpointPhase.FAILED:
                    # FAILED for the *current* pod: the checkpoint
                    # controller retries out of Failed once its failed
                    # agent Job is cleared (checkpoint_controller._failed)
                    # — clear it, so a flaked agent run cannot stall the
                    # drain forever. Non-self-healing failures stay put,
                    # but loudly.
                    job_name = agent_job_name(name)
                    job = cluster.try_get("Job", job_name, ns)
                    if job is not None and job.status.is_failed():
                        try:
                            cluster.delete("Job", job_name, ns)
                        except NotFound:
                            pass
                        DRAIN_MIGRATIONS.inc(outcome="retry_cleared_job")
                        log.info(
                            "drain: cleared failed agent job %s/%s to "
                            "retry checkpoint %s", ns, job_name, name)
                    else:
                        key = (ns, name, existing.metadata.uid)
                        if key not in self._warned_failed:
                            self._warned_failed.add(key)
                            DRAIN_MIGRATIONS.inc(outcome="blocked_failed")
                            log.warning(
                                "drain: checkpoint %s/%s is Failed and not "
                                "self-healing; pod %s will not be migrated "
                                "until the CR is cleared", ns, name,
                                pod.metadata.name)
                return None  # already migrating this pod (idempotent re-scan)
            try:
                cluster.delete("Checkpoint", name, ns)
            except NotFound:
                pass
            DRAIN_MIGRATIONS.inc(outcome="gc_stale")

        claim = self._drain_claim(pod)
        if claim is None:
            return None
        if not create:
            return pod  # cold-path candidate: the caller routes it
        self._create_drain_checkpoint(cluster, pod, claim)
        return None

    def _create_drain_checkpoint(self, cluster: Cluster, pod,
                                 claim: str) -> None:
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        ck = Checkpoint(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=CheckpointSpec(
                pod_name=pod.metadata.name,
                volume_claim=VolumeClaimSource(claim_name=claim),
                auto_migration=True,
                pre_copy=True,  # the drain grace window is pre-copy's case
                # Repeated drains of a long-lived same-named pod
                # (StatefulSet) reuse the drain-<pod> name: without a TTL
                # the stale-CR GC above deletes the old CR but its PVC
                # payload accumulates forever. The TTL's cleanup Job
                # deletes payload + CR after the migration completes.
                ttl_seconds_after_finished=DRAIN_CHECKPOINT_TTL_SECONDS,
            ),
        )
        try:
            cluster.create(ck)
        except AlreadyExists:
            return  # raced another worker/scan — fine, someone created it
        DRAIN_MIGRATIONS.inc(outcome="created")
        log.info("drain: created Checkpoint %s/%s for pod %s", ns, name,
                 pod.metadata.name)

    def _drain_claim(self, pod) -> str | None:
        """The pod's drain PVC claim, or None (with the loud skip) when
        the pod cannot be drain-migrated at all — shared precondition of
        the cold path and the standby arm."""
        ns = pod.metadata.namespace
        claim = pod.metadata.annotations.get(DRAIN_VOLUME_CLAIM_ANNOTATION, "")
        if not claim:
            # Opted in but unmigratable — loud skip, not a broken CR: the
            # checkpoint webhook would reject a claimless Checkpoint anyway.
            log.warning(
                "pod %s/%s has %s but no %s annotation; cannot drain-migrate",
                ns, pod.metadata.name, MIGRATE_ON_DRAIN_LABEL,
                DRAIN_VOLUME_CLAIM_ANNOTATION,
            )
            DRAIN_MIGRATIONS.inc(outcome="skipped_no_claim")
            return None
        if not any(o.controller for o in pod.metadata.owner_references):
            # auto-migration needs a controller owner to recreate the pod
            # (same precondition the checkpoint controller enforces).
            log.warning(
                "pod %s/%s has %s but no controller owner; cannot "
                "drain-migrate", ns, pod.metadata.name, MIGRATE_ON_DRAIN_LABEL,
            )
            DRAIN_MIGRATIONS.inc(outcome="skipped_no_owner")
            return None
        return claim

    # -- multi-pod drains: delegate to a MigrationPlan ------------------------

    def _drain_candidates(self, cluster: Cluster, node_name: str,
                          ns: str, pods: list) -> None:
        """Route one namespace's cold-path candidates. A lone pod keeps
        the direct ``drain-<pod>`` path byte-identical to every PR
        before this one; two or more pods on one cordoned node drain
        through a generated ``drain-<node>`` MigrationPlan — one
        coordinated wave (bin-packed destinations, fleet budgets,
        bounded per-pod retry) instead of N mutually-unaware CRs."""
        existing = cluster.try_get("MigrationPlan",
                                   drain_plan_name(node_name), ns)
        if existing is not None:
            # ALWAYS route through the plan bookkeeping when one exists
            # — even a lone candidate may already be a member of the
            # live plan (its siblings migrated away first), and minting
            # a direct CR for it would race two migrations of one pod.
            self._reconcile_existing_plan(cluster, node_name, ns,
                                          existing, pods)
            return
        if len(pods) == 1:
            claim = self._drain_claim(pods[0])  # validated upstream
            if claim is not None:
                self._create_drain_checkpoint(cluster, pods[0], claim)
            return
        self._create_drain_plan(cluster, node_name, ns, pods)

    def _reconcile_existing_plan(self, cluster: Cluster, node_name: str,
                                 ns: str, plan, pods: list) -> None:
        terminal = plan.status.phase in (
            MigrationPlanPhase.SUCCEEDED,
            MigrationPlanPhase.PARTIALLY_FAILED)
        member_names = {m.pod_name for m in plan.spec.members}
        uncovered = [p for p in pods
                     if p.metadata.name not in member_names]
        covered = [p for p in pods if p.metadata.name in member_names]
        if not terminal:
            # Live plan: a pod that landed on the node after the plan
            # was minted cannot join it (member sets are immutable) —
            # it takes the direct path rather than dead-ending.
            for pod in uncovered:
                self._direct_checkpoint_guarded(cluster, pod)
            return
        uids = {rec.get("pod"): rec.get("podUid", "")
                for rec in plan.status.pods}
        stale = covered and all(
            uids.get(p.metadata.name) not in ("", p.metadata.uid)
            for p in covered)
        if stale:
            # A previous same-named pod generation's verdict (StatefulSet
            # replicas keep their names): GC the plan AND its leftover
            # member CRs — a new plan adopting a stale SUBMITTED member
            # would read this generation as already migrated.
            from grit_tpu.manager.fleet import (  # noqa: PLC0415
                plan_member_checkpoint_name,
            )

            for member in plan.spec.members:
                cluster.try_delete(
                    "Checkpoint",
                    plan_member_checkpoint_name(plan.metadata.name,
                                                member.pod_name), ns)
            cluster.try_delete("MigrationPlan", plan.metadata.name, ns)
            DRAIN_MIGRATIONS.inc(outcome="gc_stale")
            self._create_drain_plan(cluster, node_name, ns, pods)
            return
        # Same pods, plan already gave its verdict: pods the plan failed
        # stay put LOUDLY (the legacy non-self-healing-Failed semantics —
        # an operator clears the plan to retry); late arrivals still
        # migrate directly.
        for pod in uncovered:
            self._direct_checkpoint_guarded(cluster, pod)
        for pod in covered:
            key = (ns, f"{plan.metadata.name}/{pod.metadata.name}",
                   plan.metadata.uid)
            if key not in self._warned_failed:
                self._warned_failed.add(key)
                DRAIN_MIGRATIONS.inc(outcome="blocked_failed")
                log.warning(
                    "drain: plan %s/%s already reached %s; pod %s will "
                    "not be re-migrated until the plan is cleared",
                    ns, plan.metadata.name, plan.status.phase.value,
                    pod.metadata.name)

    def _direct_checkpoint_guarded(self, cluster: Cluster, pod) -> None:
        """One pod's direct drain-<pod> CR with the legacy per-pod
        denial handling: an unmigratable pod is skipped loudly, never
        blocking its siblings."""
        claim = self._drain_claim(pod)
        if claim is None:
            return
        try:
            self._create_drain_checkpoint(cluster, pod, claim)
        except AdmissionDenied as exc:
            log.warning("drain: checkpoint for pod %s/%s denied: %s",
                        pod.metadata.namespace, pod.metadata.name, exc)
            DRAIN_MIGRATIONS.inc(outcome="skipped_admission")

    def _plannable(self, cluster: Cluster, pod) -> bool:
        """Whether the pod would pass the MigrationPlan webhook's
        per-member gates (Bound PVC, known priority class) — pre-checked
        per pod so one bad member cannot veto its siblings' wave: the
        webhook denies the WHOLE plan, the legacy path denied per pod,
        and the generated-plan path must not be coarser. Unplannable
        pods take the direct drain-<pod> route (whose webhook never
        looks at priority — a typo'd class still migrates, exactly as
        before this subsystem existed)."""
        claim = self._drain_claim(pod)
        if claim is None:
            return False
        pvc = cluster.try_get("PersistentVolumeClaim", claim,
                              pod.metadata.namespace)
        if pvc is None or pvc.status.phase != "Bound":
            return False
        prio = pod.metadata.annotations.get(
            MIGRATION_PRIORITY_ANNOTATION, "")
        return not prio or prio in PRIORITY_CLASSES

    def _create_drain_plan(self, cluster: Cluster, node_name: str,
                           ns: str, pods: list) -> None:
        # Candidate destinations: every Ready, schedulable node except
        # the one being drained — capacity unbounded (the drain path
        # declares none; operators wanting HBM-aware packing write the
        # MigrationPlan themselves). No destination at all → the direct
        # per-pod path (legacy semantics — the replacement pods go
        # wherever the scheduler puts them).
        destinations = [
            MigrationPlanDestination(node_name=node.metadata.name)
            for node in sorted(cluster.list("Node", ""),
                               key=lambda n: n.metadata.name)
            if node.metadata.name != node_name
            and node.status.ready() and not node.spec.unschedulable
        ]
        if not destinations:
            for pod in pods:
                self._direct_checkpoint_guarded(cluster, pod)
            return
        # Pods that would fail the plan webhook's member gates take the
        # direct path (and its legacy per-pod denial) instead of
        # vetoing the plan for everyone.
        plannable = [p for p in pods if self._plannable(cluster, p)]
        plannable_names = {p.metadata.name for p in plannable}
        leftovers = [p for p in pods
                     if p.metadata.name not in plannable_names]
        for pod in leftovers:
            self._direct_checkpoint_guarded(cluster, pod)
        if len(plannable) == 1:
            self._direct_checkpoint_guarded(cluster, plannable[0])
            return
        members = []
        for pod in plannable:
            claim = self._drain_claim(pod)
            if claim is None:
                continue
            members.append(MigrationPlanMember(
                pod_name=pod.metadata.name,
                volume_claim=VolumeClaimSource(claim_name=claim)))
        if not members:
            return
        plan = MigrationPlan(
            metadata=ObjectMeta(name=drain_plan_name(node_name),
                                namespace=ns),
            spec=MigrationPlanSpec(
                members=members,
                destinations=destinations,
                pre_copy=True,
                ttl_seconds_after_finished=DRAIN_CHECKPOINT_TTL_SECONDS,
            ),
        )
        try:
            cluster.create(plan)
        except AlreadyExists:
            return  # raced another worker/scan
        DRAIN_MIGRATIONS.inc(outcome="plan_created")
        log.info("drain: created MigrationPlan %s/%s for %d pods on "
                 "node %s", ns, plan.metadata.name, len(members),
                 node_name)

    # -- spot-node standby arm / fire / disarm --------------------------------

    def _arm_standby(self, cluster: Cluster, pod) -> None:
        """Schedule-time arm: an opted-in pod Running on spot capacity
        gets an always-warm StandbyCheckpoint NOW, so the later cordon or
        reclaim notice pays only the final delta + blackout."""
        claim = self._drain_claim(pod)
        if claim is None:
            return
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        ck = Checkpoint(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=CheckpointSpec(
                pod_name=pod.metadata.name,
                volume_claim=VolumeClaimSource(claim_name=claim),
                auto_migration=True,
                pre_copy=True,
                standby=True,
                ttl_seconds_after_finished=DRAIN_CHECKPOINT_TTL_SECONDS,
            ),
        )
        try:
            cluster.create(ck)
        except AlreadyExists:
            return
        DRAIN_MIGRATIONS.inc(outcome="standby_armed")
        log.info("drain: armed StandbyCheckpoint %s/%s for pod %s on spot "
                 "capacity", ns, name, pod.metadata.name)

    def _fire_standby(self, cluster: Cluster, ckpt: Checkpoint) -> None:
        """Cordon fires the existing warm standby instead of creating a
        cold drain-<pod> Checkpoint from scratch — the whole point of
        having kept the base warm."""
        if ckpt.metadata.annotations.get(FIRE_ANNOTATION):
            return  # already fired (by us, the watcher, or an operator)

        def mutate(obj: Checkpoint) -> None:
            obj.metadata.annotations[FIRE_ANNOTATION] = CORDON_FIRE_REASON

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate,
                      ckpt.metadata.namespace)
        STANDBY_FIRES.inc(trigger="cordon")
        DRAIN_MIGRATIONS.inc(outcome="standby_fired")
        log.info("drain: cordon fired standby checkpoint %s/%s",
                 ckpt.metadata.namespace, ckpt.metadata.name)

    def _disarm_standby(self, cluster: Cluster, ckpt: Checkpoint) -> None:
        """Uncordon cancels a cordon-fire that has not begun firing: the
        annotation is stripped and the standby keeps idling armed. A
        fire already forwarded to the agent (phase Firing onwards)
        completes — half-migrated state is worse than one extra move."""

        def mutate(obj: Checkpoint) -> None:
            obj.metadata.annotations.pop(FIRE_ANNOTATION, None)

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate,
                      ckpt.metadata.namespace)
        DRAIN_MIGRATIONS.inc(outcome="standby_disarmed")
        log.info("drain: uncordon disarmed standby checkpoint %s/%s",
                 ckpt.metadata.namespace, ckpt.metadata.name)
