"""Drain controller: cordoned node → automatic pre-copy live migration.

TPU-native addition with no reference analogue (the reference's migrations
are always operator-initiated Checkpoint CRs; SURVEY §5 "failure
detection"): on GKE, node-pool upgrades and spot/maintenance events cordon
the node before terminating it — exactly the window pre-copy migration is
built for. Pods opt in with the ``grit.dev/migrate-on-drain`` label and
name their checkpoint PVC in the ``grit.dev/drain-volume-claim``
annotation; when their node's ``spec.unschedulable`` flips true, this
controller creates a ``Checkpoint{autoMigration, preCopy}`` per pod and
the ordinary machinery (§3.1/3.2 flow) does the rest: live full dump while
the pod still runs, delta dump + owner-recreated pod on a schedulable
node.

Reconcile is level-triggered and idempotent: the Checkpoint name is a
function of the pod (``drain-<pod>``), an existing CR short-circuits, and
an uncordon simply stops producing new CRs (in-flight migrations finish —
half-migrated state is worse than one extra move).
"""

from __future__ import annotations

import logging
from collections.abc import Callable

from grit_tpu.api.constants import (
    DRAIN_VOLUME_CLAIM_ANNOTATION,
    FIRE_ANNOTATION,
    MIGRATE_ON_DRAIN_LABEL,
    SPOT_NODE_LABELS,
)
from grit_tpu.api.types import (
    STANDBY_PRE_FIRED_PHASES,
    Checkpoint,
    CheckpointPhase,
    CheckpointSpec,
    VolumeClaimSource,
)
from grit_tpu.kube.cluster import AdmissionDenied, AlreadyExists, Cluster, NotFound
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import ObjectMeta
from grit_tpu.manager.util import agent_job_name
from grit_tpu.obs.metrics import DRAIN_MIGRATIONS, STANDBY_FIRES

log = logging.getLogger(__name__)

# Data-lifecycle default for drain-created Checkpoints: long enough that an
# operator can still restore from the drain checkpoint manually after the
# incident, short enough that repeated drains of a long-lived StatefulSet
# pod don't accumulate PVC payloads under the reused drain-<pod> name.
DRAIN_CHECKPOINT_TTL_SECONDS = 24 * 3600


def drain_checkpoint_name(pod_name: str) -> str:
    return f"drain-{pod_name}"


#: Fire reason the cordon path stamps; uncordon disarms ONLY fires
#: carrying this prefix (a reclaim-notice or operator fire must never
#: be silently cancelled by an unrelated uncordon).
CORDON_FIRE_REASON = "NodeCordoned"


def is_spot_node(node) -> bool:
    """Spot/preemptible capacity, by the cloud's node labels — where the
    reclaim window is measured in seconds and migrate-on-drain pods get
    an always-warm StandbyCheckpoint at schedule time instead of a cold
    Checkpoint at cordon time."""
    labels = node.metadata.labels
    return any(labels.get(k) == "true" for k in SPOT_NODE_LABELS)


class DrainController:
    kind = "Node"

    def __init__(self) -> None:
        # CRs already warned about as non-self-healing Failed, keyed by
        # (ns, name, uid): the metric/log fire once per stuck CR, not once
        # per idempotent node re-scan (reconciles are frequent).
        self._warned_failed: set[tuple[str, str, str]] = set()

    def register(self, cluster: Cluster, enqueue: Callable[[Request], None]) -> None:
        # Secondary watch: a labeled pod appearing on an already-cordoned
        # node (edge: pod created moments before the cordon landed, or the
        # manager restarting mid-drain) must re-trigger its node's scan.
        def on_pod_event(ev) -> None:
            pod = ev.obj
            if pod.metadata.labels.get(MIGRATE_ON_DRAIN_LABEL) != "true":
                return
            if getattr(pod.spec, "node_name", ""):
                enqueue(Request("", pod.spec.node_name))

        cluster.watch("Pod", on_pod_event)

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        node = cluster.try_get("Node", req.name, "")
        if node is None:
            return Result()
        spot = is_spot_node(node)
        cordoned = node.spec.unschedulable
        if not (spot or cordoned):
            return Result()

        for pod in cluster.list(
            "Pod", label_selector={MIGRATE_ON_DRAIN_LABEL: "true"}
        ):
            if pod.spec.node_name != req.name:
                continue
            if pod.status.phase != "Running":
                continue
            try:
                self._reconcile_pod(cluster, pod, spot=spot,
                                    cordoned=cordoned)
            except AdmissionDenied as exc:
                # One unmigratable pod (unbound PVC, pod terminating mid-
                # scan) must not abort the loop and block every other
                # opted-in pod on the node.
                log.warning("drain: checkpoint for pod %s/%s denied: %s",
                            pod.metadata.namespace, pod.metadata.name, exc)
                DRAIN_MIGRATIONS.inc(outcome="skipped_admission")
        return Result()

    def _reconcile_pod(self, cluster: Cluster, pod, *, spot: bool,
                       cordoned: bool) -> None:
        """One opted-in pod's drain/standby state machine.

        Spot nodes arm at SCHEDULE time: an always-warm StandbyCheckpoint
        exists the whole time the pod runs, so the cordon (or the
        preemption watcher's reclaim notice) pays only the final delta.
        Cordon then FIRES the existing standby instead of creating a cold
        ``drain-<pod>`` from scratch; uncordon DISARMS a cordon-fire that
        has not begun firing. Non-spot nodes keep the cold
        cordon-triggered path unchanged."""
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        existing = cluster.try_get("Checkpoint", name, ns)
        standby = (existing is not None and existing.spec.standby
                   and existing.status.pod_uid in
                   ("", pod.metadata.uid))
        if cordoned:
            if standby and existing.status.phase in \
                    STANDBY_PRE_FIRED_PHASES:
                # The fire annotation can land at ANY pre-fired phase —
                # the checkpoint controller forwards it the moment the
                # agent can consume it (level-triggered: a cordon that
                # raced the CR's first reconcile must not be lost).
                self._fire_standby(cluster, existing)
                return
            # Everything else flows through the cold machinery: a
            # firing/fired standby is an idempotent no-op there, a
            # FAILED standby gets the cold path's self-healing (clear
            # the failed agent Job so the retry runs, or warn loudly),
            # and a stale terminal CR from a previous same-named pod is
            # GC'd — a cordoned pod must never dead-end silently just
            # because its arm died.
            self._migrate(cluster, pod)
            return
        # Schedulable (spot) node: keep the pod armed, and roll back a
        # cordon-fire the operator cancelled by uncordoning.
        if standby:
            reason = existing.metadata.annotations.get(FIRE_ANNOTATION, "")
            if reason.startswith(CORDON_FIRE_REASON) \
                    and existing.status.phase in \
                    STANDBY_PRE_FIRED_PHASES:
                self._disarm_standby(cluster, existing)
            return
        if existing is not None:
            # A cold/stale CR under the drain name: leave the existing
            # machinery (cordon-path _migrate, TTL GC) to its lifecycle;
            # the standby arm waits for the name to free up.
            return
        self._arm_standby(cluster, pod)

    def _migrate(self, cluster: Cluster, pod) -> None:
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        existing = cluster.try_get("Checkpoint", name, ns)
        if existing is None or existing.status.phase != CheckpointPhase.FAILED:
            # CR healthy/absent: drop any warn-once marker so a LATER
            # relapse into non-self-healing Failed warns again (and the
            # set cannot grow without bound).
            self._warned_failed = {
                k for k in self._warned_failed
                if not (k[0] == ns and k[1] == name)
            }
        if existing is not None:
            # A leftover CR from a PREVIOUS drain of a same-named pod
            # (StatefulSet replicas keep their names) must not suppress
            # this migration forever: if it is terminal and bound to a
            # different pod UID, GC it and migrate the current pod.
            terminal = existing.status.phase in (
                CheckpointPhase.SUBMITTED, CheckpointPhase.FAILED,
            )
            stale = (existing.status.pod_uid
                     and existing.status.pod_uid != pod.metadata.uid)
            if not (terminal and stale):
                if existing.status.phase == CheckpointPhase.FAILED:
                    # FAILED for the *current* pod: the checkpoint
                    # controller retries out of Failed once its failed
                    # agent Job is cleared (checkpoint_controller._failed)
                    # — clear it, so a flaked agent run cannot stall the
                    # drain forever. Non-self-healing failures stay put,
                    # but loudly.
                    job_name = agent_job_name(name)
                    job = cluster.try_get("Job", job_name, ns)
                    if job is not None and job.status.is_failed():
                        try:
                            cluster.delete("Job", job_name, ns)
                        except NotFound:
                            pass
                        DRAIN_MIGRATIONS.inc(outcome="retry_cleared_job")
                        log.info(
                            "drain: cleared failed agent job %s/%s to "
                            "retry checkpoint %s", ns, job_name, name)
                    else:
                        key = (ns, name, existing.metadata.uid)
                        if key not in self._warned_failed:
                            self._warned_failed.add(key)
                            DRAIN_MIGRATIONS.inc(outcome="blocked_failed")
                            log.warning(
                                "drain: checkpoint %s/%s is Failed and not "
                                "self-healing; pod %s will not be migrated "
                                "until the CR is cleared", ns, name,
                                pod.metadata.name)
                return  # already migrating this pod (idempotent re-scan)
            try:
                cluster.delete("Checkpoint", name, ns)
            except NotFound:
                pass
            DRAIN_MIGRATIONS.inc(outcome="gc_stale")

        claim = self._drain_claim(pod)
        if claim is None:
            return

        ck = Checkpoint(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=CheckpointSpec(
                pod_name=pod.metadata.name,
                volume_claim=VolumeClaimSource(claim_name=claim),
                auto_migration=True,
                pre_copy=True,  # the drain grace window is pre-copy's case
                # Repeated drains of a long-lived same-named pod
                # (StatefulSet) reuse the drain-<pod> name: without a TTL
                # the stale-CR GC above deletes the old CR but its PVC
                # payload accumulates forever. The TTL's cleanup Job
                # deletes payload + CR after the migration completes.
                ttl_seconds_after_finished=DRAIN_CHECKPOINT_TTL_SECONDS,
            ),
        )
        try:
            cluster.create(ck)
        except AlreadyExists:
            return  # raced another worker/scan — fine, someone created it
        DRAIN_MIGRATIONS.inc(outcome="created")
        log.info("drain: created Checkpoint %s/%s for pod %s", ns, name,
                 pod.metadata.name)

    def _drain_claim(self, pod) -> str | None:
        """The pod's drain PVC claim, or None (with the loud skip) when
        the pod cannot be drain-migrated at all — shared precondition of
        the cold path and the standby arm."""
        ns = pod.metadata.namespace
        claim = pod.metadata.annotations.get(DRAIN_VOLUME_CLAIM_ANNOTATION, "")
        if not claim:
            # Opted in but unmigratable — loud skip, not a broken CR: the
            # checkpoint webhook would reject a claimless Checkpoint anyway.
            log.warning(
                "pod %s/%s has %s but no %s annotation; cannot drain-migrate",
                ns, pod.metadata.name, MIGRATE_ON_DRAIN_LABEL,
                DRAIN_VOLUME_CLAIM_ANNOTATION,
            )
            DRAIN_MIGRATIONS.inc(outcome="skipped_no_claim")
            return None
        if not any(o.controller for o in pod.metadata.owner_references):
            # auto-migration needs a controller owner to recreate the pod
            # (same precondition the checkpoint controller enforces).
            log.warning(
                "pod %s/%s has %s but no controller owner; cannot "
                "drain-migrate", ns, pod.metadata.name, MIGRATE_ON_DRAIN_LABEL,
            )
            DRAIN_MIGRATIONS.inc(outcome="skipped_no_owner")
            return None
        return claim

    # -- spot-node standby arm / fire / disarm --------------------------------

    def _arm_standby(self, cluster: Cluster, pod) -> None:
        """Schedule-time arm: an opted-in pod Running on spot capacity
        gets an always-warm StandbyCheckpoint NOW, so the later cordon or
        reclaim notice pays only the final delta + blackout."""
        claim = self._drain_claim(pod)
        if claim is None:
            return
        name = drain_checkpoint_name(pod.metadata.name)
        ns = pod.metadata.namespace
        ck = Checkpoint(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=CheckpointSpec(
                pod_name=pod.metadata.name,
                volume_claim=VolumeClaimSource(claim_name=claim),
                auto_migration=True,
                pre_copy=True,
                standby=True,
                ttl_seconds_after_finished=DRAIN_CHECKPOINT_TTL_SECONDS,
            ),
        )
        try:
            cluster.create(ck)
        except AlreadyExists:
            return
        DRAIN_MIGRATIONS.inc(outcome="standby_armed")
        log.info("drain: armed StandbyCheckpoint %s/%s for pod %s on spot "
                 "capacity", ns, name, pod.metadata.name)

    def _fire_standby(self, cluster: Cluster, ckpt: Checkpoint) -> None:
        """Cordon fires the existing warm standby instead of creating a
        cold drain-<pod> Checkpoint from scratch — the whole point of
        having kept the base warm."""
        if ckpt.metadata.annotations.get(FIRE_ANNOTATION):
            return  # already fired (by us, the watcher, or an operator)

        def mutate(obj: Checkpoint) -> None:
            obj.metadata.annotations[FIRE_ANNOTATION] = CORDON_FIRE_REASON

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate,
                      ckpt.metadata.namespace)
        STANDBY_FIRES.inc(trigger="cordon")
        DRAIN_MIGRATIONS.inc(outcome="standby_fired")
        log.info("drain: cordon fired standby checkpoint %s/%s",
                 ckpt.metadata.namespace, ckpt.metadata.name)

    def _disarm_standby(self, cluster: Cluster, ckpt: Checkpoint) -> None:
        """Uncordon cancels a cordon-fire that has not begun firing: the
        annotation is stripped and the standby keeps idling armed. A
        fire already forwarded to the agent (phase Firing onwards)
        completes — half-migrated state is worse than one extra move."""

        def mutate(obj: Checkpoint) -> None:
            obj.metadata.annotations.pop(FIRE_ANNOTATION, None)

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate,
                      ckpt.metadata.namespace)
        DRAIN_MIGRATIONS.inc(outcome="standby_disarmed")
        log.info("drain: uncordon disarmed standby checkpoint %s/%s",
                 ckpt.metadata.namespace, ckpt.metadata.name)
