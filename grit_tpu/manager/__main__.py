"""grit-manager process entrypoint (``python -m grit_tpu.manager``).

Parity: reference ``cmd/grit-manager/grit-manager.go`` + ``app/manager.go``.
The reconciliation logic is transport-agnostic (it runs against the
:class:`grit_tpu.kube.cluster.Cluster` protocol); this entrypoint serves
health/readiness endpoints and runs the manager against the configured
cluster adapter. The in-cluster kube-apiserver adapter is provided by the
deployment image; without one this runs the manager against an in-memory
cluster — useful for smoke tests and local development
(``--demo`` seeds a node/PVC/pod and drives one checkpoint through).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _health_server(port: int, ready: threading.Event) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path == "/healthz":
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"ok")
            elif self.path == "/readyz":
                code = 200 if ready.is_set() else 503
                self.send_response(code)
                self.end_headers()
                self.wfile.write(b"ok" if code == 200 else b"not ready")
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # quiet
            return

    srv = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="grit-manager")
    p.add_argument("--health-port", type=int, default=10352)
    p.add_argument("--webhook-port", type=int, default=10350)
    p.add_argument("--metrics-port", type=int, default=10351)
    p.add_argument("--agent-config", default="grit-agent-config")
    p.add_argument("--enable-leader-election", action="store_true")
    p.add_argument("--demo", action="store_true",
                   help="run one checkpoint lifecycle against an in-memory "
                        "cluster and exit (smoke test)")
    args = p.parse_args(argv)

    from grit_tpu.kube.cluster import Cluster
    from grit_tpu.manager.manager import build_manager
    from grit_tpu.obs import start_metrics_server

    ready = threading.Event()
    srv = _health_server(args.health_port, ready)
    metrics_srv = start_metrics_server(args.metrics_port)

    cluster = Cluster()
    mgr = build_manager(cluster)
    ready.set()

    if args.demo:
        from grit_tpu.api.types import (
            Checkpoint, CheckpointPhase, CheckpointSpec, VolumeClaimSource,
        )
        from grit_tpu.kube.objects import (
            Condition, NodeStatus, ObjectMeta, Node, PersistentVolumeClaim,
            Pod, PVCStatus,
        )

        cluster.create(Node(
            metadata=ObjectMeta(name="demo-node", namespace=""),
            status=NodeStatus(
                conditions=[Condition(type="Ready", status="True")]
            ),
        ))
        cluster.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name="demo-pvc"),
            status=PVCStatus(phase="Bound"),
        ))
        pod = Pod(metadata=ObjectMeta(name="demo-pod"))
        pod.spec.node_name = "demo-node"
        pod.status.phase = "Running"
        cluster.create(pod)
        cluster.create(Checkpoint(
            metadata=ObjectMeta(name="demo"),
            spec=CheckpointSpec(
                pod_name="demo-pod",
                volume_claim=VolumeClaimSource(claim_name="demo-pvc"),
            ),
        ))
        mgr.run_until_quiescent()
        ck = cluster.get("Checkpoint", "demo")
        job = cluster.try_get("Job", "grit-agent-demo")
        print(json.dumps({
            "phase": str(ck.status.phase),
            "agent_job": job.metadata.name if job else None,
            "node": ck.status.node_name,
        }))
        srv.shutdown()
        metrics_srv.shutdown()
        return 0 if ck.status.phase == CheckpointPhase.CHECKPOINTING else 1

    print(f"grit-manager: serving health on :{args.health_port} "
          "(in-memory cluster; in-cluster adapter not configured)",
          flush=True)
    try:
        while True:
            mgr.run_until_quiescent()
            time.sleep(1.0)
    except KeyboardInterrupt:
        srv.shutdown()
        metrics_srv.shutdown()
        return 0


if __name__ == "__main__":
    sys.exit(main())
