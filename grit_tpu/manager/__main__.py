"""grit-manager process entrypoint (``python -m grit_tpu.manager``).

Parity: reference ``cmd/grit-manager/grit-manager.go`` + ``app/manager.go``.
Resolves an apiserver connection the way client-go does — explicit
``--master`` URL, else in-cluster serviceaccount, else kubeconfig — and runs
the full deployable assembly (:class:`grit_tpu.manager.run.ManagerRuntime`:
webhook TLS server, optional Lease leader election, controllers). When no
apiserver is configured at all it falls back to an in-memory cluster with a
loud warning — useful only for smoke tests and ``--demo``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from grit_tpu.api import config


def _health_server(port: int, ready: threading.Event) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path == "/healthz":
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"ok")
            elif self.path == "/readyz":
                code = 200 if ready.is_set() else 503
                self.send_response(code)
                self.end_headers()
                self.wfile.write(b"ok" if code == 200 else b"not ready")
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # quiet
            return

    srv = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _resolve_cluster(args):
    """client-go config resolution order: --master > explicit --kubeconfig >
    in-cluster > $KUBECONFIG/~/.kube/config.

    Returns (cluster, description) — cluster is None when no apiserver is
    *configured* (caller falls back to in-memory). A configured but
    unreachable apiserver is a startup error, not a fallback.
    """
    from grit_tpu.kube.client import KubeCluster, KubeConfig

    if args.master:
        cfg = KubeConfig.from_url(args.master, token=args.token or None)
        return KubeCluster(cfg), f"apiserver {args.master}"
    if args.kubeconfig:  # explicit flag outranks in-cluster (client-go)
        return (
            KubeCluster(KubeConfig.from_kubeconfig(args.kubeconfig)),
            f"kubeconfig {args.kubeconfig}",
        )
    if os.environ.get("KUBERNETES_SERVICE_HOST"):
        return KubeCluster(KubeConfig.in_cluster()), "in-cluster"
    kubeconfig = os.environ.get("KUBECONFIG") or os.path.expanduser(
        "~/.kube/config"
    )
    if os.path.exists(kubeconfig):
        return (
            KubeCluster(KubeConfig.from_kubeconfig(kubeconfig)),
            f"kubeconfig {kubeconfig}",
        )
    return None, "in-memory (no apiserver configured)"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="grit-manager")
    p.add_argument("--health-port", type=int, default=10352)
    p.add_argument("--webhook-port", type=int, default=10350)
    p.add_argument("--metrics-port", type=int, default=10351)
    p.add_argument("--agent-config", default="grit-agent-config")
    p.add_argument("--master", default=config.MASTER.get(),
                   help="apiserver URL (overrides in-cluster/kubeconfig)")
    p.add_argument("--kubeconfig", default="")
    p.add_argument("--token", default=config.TOKEN.get())
    p.add_argument("--namespace", default="grit-system",
                   help="namespace for the leader-election Lease")
    p.add_argument("--enable-leader-election", action="store_true")
    p.add_argument("--enable-profiling", action="store_true",
                   help="serve /debug/pprof/profile (sampled CPU profile) "
                        "on the metrics port, alongside /debug/threadz")
    p.add_argument("--version", action="store_true")
    p.add_argument("--demo", action="store_true",
                   help="run one checkpoint lifecycle against an in-memory "
                        "cluster and exit (smoke test)")
    args = p.parse_args(argv)

    from grit_tpu.version import version_string

    if args.version:
        print(version_string())
        return 0

    from grit_tpu.obs import start_metrics_server

    print(version_string(), flush=True)
    ready = threading.Event()
    srv = _health_server(args.health_port, ready)
    metrics_srv = start_metrics_server(
        args.metrics_port, profiling=args.enable_profiling
    )

    if args.demo:
        return _run_demo(srv, metrics_srv, ready)

    cluster, where = _resolve_cluster(args)
    if cluster is None:
        return _run_in_memory(args, srv, metrics_srv, ready, where)

    from grit_tpu.manager.run import ManagerRuntime

    runtime = ManagerRuntime(
        cluster,
        webhook_port=args.webhook_port,
        enable_leader_election=args.enable_leader_election,
        lease_namespace=args.namespace,
    )
    runtime.start()
    ready.set()
    print(
        f"grit-manager: connected to {where}; webhooks :{args.webhook_port}, "
        f"metrics :{args.metrics_port}, health :{args.health_port}, "
        f"leader-election={'on' if args.enable_leader_election else 'off'}",
        flush=True,
    )

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # not the main thread (tests)
            pass
    while not stop.is_set():
        if runtime.lost_leadership.is_set():
            print("grit-manager: lost leader lease, exiting for re-election",
                  file=sys.stderr, flush=True)
            runtime.stop()
            srv.shutdown()
            metrics_srv.shutdown()
            return 1
        stop.wait(0.5)
    runtime.stop()
    srv.shutdown()
    metrics_srv.shutdown()
    return 0


def _run_in_memory(args, srv, metrics_srv, ready, where: str) -> int:
    from grit_tpu.kube.cluster import Cluster
    from grit_tpu.manager.manager import build_manager

    cluster = Cluster()
    mgr = build_manager(cluster)
    ready.set()
    print(
        f"grit-manager: WARNING — running against {where}; nothing will be "
        "reconciled in any real cluster. Set --master/--kubeconfig or deploy "
        "in-cluster.",
        file=sys.stderr, flush=True,
    )
    print(f"grit-manager: serving health on :{args.health_port}", flush=True)
    try:
        while True:
            mgr.run_until_quiescent()
            time.sleep(1.0)
    except KeyboardInterrupt:
        srv.shutdown()
        metrics_srv.shutdown()
        return 0


def _run_demo(srv, metrics_srv, ready) -> int:
    from grit_tpu.api.types import (
        Checkpoint, CheckpointPhase, CheckpointSpec, VolumeClaimSource,
    )
    from grit_tpu.kube.cluster import Cluster
    from grit_tpu.kube.objects import (
        Condition, NodeStatus, ObjectMeta, Node, PersistentVolumeClaim,
        Pod, PVCStatus,
    )
    from grit_tpu.manager.manager import build_manager

    cluster = Cluster()
    mgr = build_manager(cluster)
    ready.set()

    cluster.create(Node(
        metadata=ObjectMeta(name="demo-node", namespace=""),
        status=NodeStatus(
            conditions=[Condition(type="Ready", status="True")]
        ),
    ))
    cluster.create(PersistentVolumeClaim(
        metadata=ObjectMeta(name="demo-pvc"),
        status=PVCStatus(phase="Bound"),
    ))
    pod = Pod(metadata=ObjectMeta(name="demo-pod"))
    pod.spec.node_name = "demo-node"
    pod.status.phase = "Running"
    cluster.create(pod)
    cluster.create(Checkpoint(
        metadata=ObjectMeta(name="demo"),
        spec=CheckpointSpec(
            pod_name="demo-pod",
            volume_claim=VolumeClaimSource(claim_name="demo-pvc"),
        ),
    ))
    mgr.run_until_quiescent()
    ck = cluster.get("Checkpoint", "demo")
    job = cluster.try_get("Job", "grit-agent-demo")
    print(json.dumps({
        "phase": str(ck.status.phase),
        "agent_job": job.metadata.name if job else None,
        "node": ck.status.node_name,
    }))
    srv.shutdown()
    metrics_srv.shutdown()
    return 0 if ck.status.phase == CheckpointPhase.CHECKPOINTING else 1


if __name__ == "__main__":
    sys.exit(main())
