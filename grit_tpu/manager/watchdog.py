"""Manager watchdog policy: leases, phase deadlines, retry classification.

Shared by the checkpoint and restore controllers. Three detection signals
turn a silently-wedged migration leg into an explicit decision:

- **Stale heartbeat** — the agent renews ``grit.dev/heartbeat`` on its
  Job (:mod:`grit_tpu.agent.lease`); an age beyond ``GRIT_LEASE_TIMEOUT_S``
  means the agent process is gone or wedged (exported as
  ``grit_agent_heartbeat_age_seconds``).
- **Progress stall** — the lease still beats (the process lives) but the
  ``grit.dev/progress`` snapshot the lease patches alongside it shows no
  forward progress (bytes, round, phase all frozen) for
  ``GRIT_PROGRESS_STALL_S``: a frozen sender on a healthy process — the
  one failure the lease alone can never see — classifies retriable
  without waiting out the full phase deadline.
- **Phase deadline** — wall time since the CR entered its current phase
  (condition transition time) beyond ``GRIT_PHASE_DEADLINE_S``: even a
  dutifully-heartbeating agent that never finishes is an overrun.
- **Job failure** — the Job went Failed; the agent's termination-reason
  file (:mod:`grit_tpu.agent.termination`) says whether a fresh attempt
  can help.

The verdict feeds bounded re-creation: ``grit.dev/attempt`` counts
attempts (capped by ``GRIT_AGENT_MAX_ATTEMPTS``), ``grit.dev/retry-at``
holds the earliest next-Job time (capped exponential backoff + jitter,
``GRIT_RETRY_BACKOFF_S``/``GRIT_RETRY_BACKOFF_CAP_S``). Exhausted or
terminal verdicts fail fast — through the abort path when the source may
be quiesced (checkpoint leg), with the agent's recorded reason surfaced
into the CR conditions either way.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from grit_tpu.agent.termination import read_termination
from grit_tpu.api.constants import (
    ATTEMPT_ANNOTATION,
    HEARTBEAT_ANNOTATION,
    PROGRESS_ANNOTATION,
    RETRY_AT_ANNOTATION,
)
from grit_tpu.kube.objects import Condition, Job, now
from grit_tpu.api import config
from grit_tpu.obs.metrics import HEARTBEAT_AGE
from grit_tpu.retry import backoff_delay

STALE_HEARTBEAT = "StaleHeartbeat"
PHASE_DEADLINE = "PhaseDeadlineExceeded"
PROGRESS_STALL = "ProgressStalled"
STANDBY_STALE = "StandbyStale"
AGENT_JOB_FAILED = "AgentJobFailed"

#: Watchdog-detected overrun causes: the wedged-but-Active Job is deleted
#: so the retry replaces it, and the verdict is inherently retriable (the
#: agent never got to record why).
OVERRUN_CAUSES = (STALE_HEARTBEAT, PHASE_DEADLINE, PROGRESS_STALL,
                  STANDBY_STALE)


def lease_timeout_s() -> float:
    return config.LEASE_TIMEOUT_S.get()


def phase_deadline_s() -> float:
    return config.PHASE_DEADLINE_S.get()


def max_attempts() -> int:
    return max(1, config.AGENT_MAX_ATTEMPTS.get())


def retry_backoff_s() -> tuple[float, float]:
    """(base, cap) for the agent-Job re-creation schedule."""
    return (config.RETRY_BACKOFF_S.get(),
            config.RETRY_BACKOFF_CAP_S.get())


# kind -> last observed beat timestamp (manager clock): the periodic
# sampler re-derives the age gauge from this between watchdog polls, so
# a scrape never reads the age as of some historical reconcile.
_last_beats: dict[str, float] = {}


def heartbeat_age(job: Job, kind: str = "") -> float:
    """Seconds since the Job's lease was last renewed (Job creation time
    counts as the first beat — an agent may die before its first renewal,
    and a just-created Job must not read as ancient). Exports the gauge
    when ``kind`` is given."""
    raw = job.metadata.annotations.get(HEARTBEAT_ANNOTATION, "")
    try:
        last = float(raw)
    except ValueError:
        last = 0.0
    last = max(last, job.metadata.creation_timestamp)
    age = max(0.0, now() - last) if last else 0.0
    if kind:
        HEARTBEAT_AGE.set(age, kind=kind)
        _last_beats[kind] = now() - age
    return age


def sample_heartbeat_age() -> None:
    """Periodic-sampler callback (registered by the manager runtime):
    ``grit_agent_heartbeat_age_seconds`` used to update only when a
    reconcile happened to poll a Job — between polls a scrape read the
    age as of that poll, which UNDERSTATES a dying agent exactly when
    it matters. Ages forward from the last observed beat instead.

    Bounded retention: once a beat is older than several lease
    timeouts the watchdog has long since acted (or the Job completed
    and was GC'd — controllers stop polling terminal migrations, so the
    entry is simply the LAST migration's leftover state). Aging it forever
    would drive the gauge to infinity on an idle manager and latch any
    age-based alert; drop the series instead."""
    retention = max(lease_timeout_s(), 60.0) * 4
    for kind, beat in list(_last_beats.items()):
        age = max(0.0, now() - beat)
        if age > retention:
            _last_beats.pop(kind, None)
            HEARTBEAT_AGE.remove(kind=kind)
        else:
            HEARTBEAT_AGE.set(age, kind=kind)


def reset_heartbeat_samples() -> None:
    """Forget observed beats (tests)."""
    _last_beats.clear()


def job_progress(job: Job) -> dict | None:
    """The Job's ``grit.dev/progress`` annotation, parsed; None when
    absent or malformed (an agent predating the telemetry plane — the
    stall check simply does not apply)."""
    raw = job.metadata.annotations.get(PROGRESS_ANNOTATION, "")
    if not raw:
        return None
    try:
        rec = json.loads(raw)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def progress_stalled_s(job: Job) -> float | None:
    """Seconds since the Job's progress snapshot last advanced (bytes,
    round, or phase — the tracker bumps ``advancedAt`` on any of them),
    when that exceeds ``GRIT_PROGRESS_STALL_S``; None while healthy,
    unknowable, or disabled.

    The verdict only applies MID-TRANSFER: bytes have started flowing
    toward a KNOWN total and stopped short of it. A leg that is idle by
    design — a wire-restore agent listening while the source runs its
    pre-copy rounds (no frames, total unknown), a finished leg waiting
    on its peer (shipped == total), a commit wait — must never read as
    stalled, or the watchdog would shoot healthy Jobs every stall
    window. The timestamps are agent wall clock — cross-host skew eats
    into (or pads) the threshold, which is why the default is minutes,
    not seconds."""
    stall_after = float(config.PROGRESS_STALL_S.get())
    if stall_after <= 0:
        return None
    rec = job_progress(job)
    if rec is None:
        return None
    if rec.get("phase") == "standby":
        # Idle-armed standby is a LEGITIMATE state (like the idle
        # wire-restore agent): the governor may sit out minutes-long
        # backed-off intervals with bytes shipped == total frozen by
        # design. The standby_stale_s verdict below watches the governor
        # tick instead; shipped rounds still bump advancedAt, so a
        # fired/arming standby re-enters this check the moment its
        # phase leaves "standby".
        return None
    try:
        advanced = float(rec.get("advancedAt") or 0.0)
        shipped = int(rec.get("bytesShipped") or 0)
        total = int(rec.get("totalBytes") or 0)
    except (TypeError, ValueError):
        return None
    if advanced <= 0 or shipped <= 0 or total <= 0 or shipped >= total:
        return None  # not demonstrably mid-transfer
    stalled = now() - advanced
    return stalled if stalled > stall_after else None


def standby_stale_s(job: Job) -> float | None:
    """Seconds the armed standby's governor tick has been FROZEN, when
    that exceeds ``GRIT_STANDBY_STALE_S``; None while healthy, not a
    standby, or disabled.

    A healthy idle-armed standby stamps ``standby.tickAt`` on every
    fire-poll slice (~1 s cadence), so even a maximally backed-off
    governed interval never trips this. A frozen tick on a fresh lease
    is a governor wedged between rounds — the standby equivalent of
    ProgressStalled: the warm base is silently going stale, which
    defeats the arm's whole point.

    A governed round IN FLIGHT (``standby.roundStartedAt`` stamped at
    round start, cleared at round end) is different: the tick freezes
    for the round's whole duration by design, and a legitimate round —
    a flagship-scale rebase re-dump, a big delta ship over a slow link
    — can run many minutes. Such a round is bounded by the ordinary
    phase deadline instead, so a hung dump is still shot without ever
    shooting a slow-but-moving one inside its normal budget."""
    stale_after = float(config.STANDBY_STALE_S.get())
    if stale_after <= 0:
        return None
    rec = job_progress(job)
    if rec is None or rec.get("phase") != "standby":
        return None
    standby = rec.get("standby")
    if not isinstance(standby, dict):
        return None
    try:
        round_started = float(standby.get("roundStartedAt") or 0.0)
    except (TypeError, ValueError):
        round_started = 0.0
    if round_started > 0:
        stalled = now() - round_started
        return stalled if stalled > phase_deadline_s() else None
    try:
        tick = float(standby.get("tickAt") or 0.0)
    except (TypeError, ValueError):
        return None
    if tick <= 0:
        return None
    stalled = now() - tick
    return stalled if stalled > stale_after else None


def _has_lease(job: Job) -> bool:
    return HEARTBEAT_ANNOTATION in job.metadata.annotations


def phase_started_at(conditions: list[Condition], phase_value: str) -> float:
    """When the CR entered its current phase (condition transition time);
    0.0 when unrecorded (then no deadline can be enforced)."""
    return max((c.last_transition_time for c in conditions
                if c.type == phase_value and c.status == "True"),
               default=0.0)


def overrun_cause(job: Job, phase_started: float, kind: str = "") -> str | None:
    """STALE_HEARTBEAT / PROGRESS_STALL / PHASE_DEADLINE when the
    running Job blew its lease, froze mid-transfer, or the phase its
    deadline; None while healthy.

    The stale-lease verdict requires the Job to have beaten at least
    once (annotation present): an agent on a node where renewal is
    impossible — missing RBAC, no in-cluster config — must not have its
    healthy long-running Job shot at the lease timeout. Such Jobs stay
    bounded by the phase deadline instead.

    The progress-stall verdict is strictly finer than either: it needs a
    FRESH lease (the process demonstrably lives — a dead process is the
    stale-lease case and must classify as that) plus a progress
    snapshot whose ``advancedAt`` went quiet past the stall window — a
    sender frozen in a syscall while its heartbeat thread dutifully
    renews. Slow-but-advancing legs never trip it: any byte, round or
    phase movement resets the clock."""
    age = heartbeat_age(job, kind=kind)  # gauge exported either way
    cause = None
    stalled = None
    if _has_lease(job) and age > lease_timeout_s():
        cause = STALE_HEARTBEAT
    elif _has_lease(job) and age <= lease_timeout_s() \
            and (stalled := progress_stalled_s(job)) is not None:
        cause = PROGRESS_STALL
    elif phase_started and now() - phase_started > phase_deadline_s():
        cause = PHASE_DEADLINE
    if cause is not None:
        _emit_overrun(job, kind, cause, age, stalled)
    return cause


def standby_overrun_cause(job: Job, kind: str = "") -> str | None:
    """Watchdog verdict for a CR parked in the Standby phase, which is
    unbounded BY DESIGN — no phase deadline, no ProgressStalled (idle-
    armed between governed rounds is the steady state). What still gets
    a wedged standby shot: a stale lease (the agent process is gone —
    re-arm a fresh one; the warm base on the PVC survives the retry),
    and a frozen governor tick on a fresh lease (:func:`standby_stale_s`
    — the base silently going stale defeats the arm)."""
    age = heartbeat_age(job, kind=kind)
    cause = None
    stalled = None
    if _has_lease(job) and age > lease_timeout_s():
        cause = STALE_HEARTBEAT
    elif _has_lease(job) and age <= lease_timeout_s() \
            and (stalled := standby_stale_s(job)) is not None:
        cause = STANDBY_STALE
    if cause is not None:
        _emit_overrun(job, kind, cause, age, stalled)
    return cause


def _emit_overrun(job: Job, kind: str, cause: str, age: float,
                  stalled: float | None) -> None:
    # Watchdog verdicts are where migrations silently lose minutes —
    # a first-class flight event, keyed by the CHECKPOINT name like
    # every other emitter (the agents derive it from the work-dir
    # basename; restore Jobs are named after the <ck>-migration
    # Restore CR, so strip the suffix to rejoin the timeline).
    from grit_tpu.manager.util import (  # noqa: PLC0415
        cr_name_from_agent_job,
        parse_slice_member,
    )
    from grit_tpu.obs import flight  # noqa: PLC0415

    uid = cr_name_from_agent_job(job.metadata.name) \
        or job.metadata.name
    if kind == "Restore" and uid.endswith("-migration"):
        uid = uid[:-len("-migration")]
    # Per-host slice Jobs (grit-agent-<cr>-h<k>): the verdict joins the
    # slice CR's timeline, with the host ordinal as a field.
    uid, ordinal = parse_slice_member(uid)
    flight.emit("manager.phase", uid=uid,
                kind=kind or "Job", phase="WatchdogOverrun",
                reason=cause, heartbeat_age_s=round(age, 1),
                **({"ordinal": ordinal} if ordinal is not None else {}),
                **({"progress_stalled_s": round(stalled, 1)}
                   if stalled is not None else {}))


_OVERRUN_NOUN = {
    STALE_HEARTBEAT: "lease",
    PROGRESS_STALL: "progress-stall window",
    STANDBY_STALE: "standby governor-tick window",
    PHASE_DEADLINE: "phase deadline",
}


def overrun_noun(cause: str) -> str:
    """Human name of what the Job overran, for condition messages."""
    return _OVERRUN_NOUN.get(cause, cause)


@dataclass
class FailureVerdict:
    cause: str      # condition reason, e.g. AgentJobFailed / StaleHeartbeat
    message: str
    retriable: bool


def classify_job_failure(
    agent_manager, namespace: str, cr_name: str, cause: str,
    default_message: str,
) -> FailureVerdict:
    """Fold the agent's recorded termination reason (when its host work
    dir is reachable — always true in-process, node-local in production)
    into the watchdog's verdict. Watchdog-detected causes (stale lease,
    progress stall, deadline) are inherently retriable: the agent never
    got to say why."""
    if cause in OVERRUN_CAUSES:
        return FailureVerdict(cause=cause, message=default_message,
                              retriable=True)
    term = read_termination(agent_manager.host_work_path(namespace, cr_name))
    if term is not None:
        msg = f"{term.reason}: {term.message}" if term.message else term.reason
        return FailureVerdict(cause=term.reason or cause, message=msg,
                              retriable=term.retriable)
    # No reason file: an unknown failure retries (bounded) rather than
    # dead-ending a migration on a lost write.
    return FailureVerdict(cause=cause, message=default_message,
                          retriable=True)


# -- retry bookkeeping on the CR ----------------------------------------------


def attempt_count(meta) -> int:
    try:
        return int(meta.annotations.get(ATTEMPT_ANNOTATION, "0"))
    except ValueError:
        return 0


def schedule_retry(cluster, kind: str, name: str, namespace: str,
                   attempt: int) -> float:
    """Stamp attempt+1 and the backoff-delayed retry-at annotation onto
    the CR; returns the delay chosen."""
    base, cap = retry_backoff_s()
    delay = backoff_delay(attempt, base=base, cap=cap)
    retry_at = now() + delay

    def mutate(obj) -> None:
        obj.metadata.annotations[ATTEMPT_ANNOTATION] = str(attempt + 1)
        obj.metadata.annotations[RETRY_AT_ANNOTATION] = f"{retry_at:.3f}"

    cluster.patch(kind, name, mutate, namespace)
    return delay


def retry_wait_remaining(meta) -> float:
    """Seconds until the CR's retry-at allows the next agent Job; <= 0
    when unset or due."""
    raw = meta.annotations.get(RETRY_AT_ANNOTATION, "")
    if not raw:
        return 0.0
    try:
        return float(raw) - now()
    except ValueError:
        return 0.0
